"""CephFS directory snapshots (snaprealm/SnapServer reduced): frozen
subtree metadata + pool-snapshot data reads through dir/.snap paths,
immutability, unlink survival, rmsnap, and MDS crash replay."""

from __future__ import annotations

import pytest

from ceph_tpu.cephfs import CephFS
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    meta = c.create_pool(client, pg_num=4, size=2)
    data = c.create_pool(client, pg_num=8, size=2)
    c.run_mds(meta, data)
    c._fs_pools = (meta, data)
    yield c
    c.stop()


@pytest.fixture
def fs(cluster):
    f = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    f.mount()
    yield f
    f.unmount()


def test_snapshot_freezes_content(fs):
    fs.mkdir("/snapd")
    with fs.open("/snapd/a.txt", "w") as f:
        f.write(b"generation one")
    fs.mkdir("/snapd/sub")
    with fs.open("/snapd/sub/b.txt", "w") as f:
        f.write(b"nested")
    snapid = fs.mksnap("/snapd", "s1")
    assert snapid > 0
    assert "s1" in fs.listsnaps("/snapd")

    # mutate the live tree: overwrite, append, new file
    with fs.open("/snapd/a.txt", "w") as f:
        f.write(b"generation TWO is longer")
    with fs.open("/snapd/new.txt", "w") as f:
        f.write(b"born later")

    # the snapshot still serves generation one
    with fs.open("/snapd/.snap/s1/a.txt") as f:
        assert f.read() == b"generation one"
    with fs.open("/snapd/.snap/s1/sub/b.txt") as f:
        assert f.read() == b"nested"
    # and the live tree serves the new world
    with fs.open("/snapd/a.txt") as f:
        assert f.read() == b"generation TWO is longer"

    # frozen listing has no new.txt; live listing does
    snap_entries = fs.listdir("/snapd/.snap/s1")
    assert set(snap_entries) == {"a.txt", "sub"}
    assert "new.txt" in fs.listdir("/snapd")
    # .snap listing names the snapshots
    assert "s1" in fs.listdir("/snapd/.snap")
    # stat through the snap path reports the frozen size
    assert fs.stat("/snapd/.snap/s1/a.txt")["size"] == \
        len(b"generation one")


def test_snapshot_survives_unlink(fs):
    fs.mkdir("/keep")
    with fs.open("/keep/doomed.txt", "w") as f:
        f.write(b"still here after unlink")
    fs.mksnap("/keep", "before")
    fs.unlink("/keep/doomed.txt")
    with pytest.raises(OSError):
        fs.stat("/keep/doomed.txt")
    with fs.open("/keep/.snap/before/doomed.txt") as f:
        assert f.read() == b"still here after unlink"


def test_snapshots_are_immutable(fs):
    fs.mkdir("/ro")
    with fs.open("/ro/f", "w") as f:
        f.write(b"x")
    fs.mksnap("/ro", "s")
    with pytest.raises(OSError):
        fs.open("/ro/.snap/s/f", "w")
    f = fs.open("/ro/.snap/s/f")
    with pytest.raises(OSError):
        f.write(b"nope")
    with pytest.raises(OSError):
        fs.unlink("/ro/.snap/s/f")
    with pytest.raises(OSError):
        fs.mkdir("/ro/.snap/s/newdir")


def test_rmsnap_and_errors(fs):
    fs.mkdir("/life")
    with fs.open("/life/f", "w") as f:
        f.write(b"v")
    fs.mksnap("/life", "s1")
    with pytest.raises(OSError):
        fs.mksnap("/life", "s1")        # EEXIST
    with pytest.raises(OSError):
        fs.mksnap("/nonexistent", "s")  # ENOENT
    fs.rmsnap("/life", "s1")
    assert fs.listsnaps("/life") == {}
    with pytest.raises(OSError):
        fs.open("/life/.snap/s1/f")
    with pytest.raises(OSError):
        fs.rmsnap("/life", "s1")        # already gone


def test_snapshot_survives_mds_restart(cluster, fs):
    fs.mkdir("/dur")
    with fs.open("/dur/f", "w") as f:
        f.write(b"durable content")
    fs.mksnap("/dur", "keeper")
    with fs.open("/dur/f", "w") as f:
        f.write(b"changed after snap")
    # crash + restart the MDS (suppress the shutdown flush so the
    # journal itself must carry the snapshot records)
    cluster.mds._flush_dirty = lambda: None
    cluster.mds.journal.trim = lambda *a, **k: None
    cluster.kill_mds()
    cluster.run_mds(*cluster._fs_pools)
    f2 = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    f2.mount()
    try:
        assert "keeper" in f2.listsnaps("/dur")
        with f2.open("/dur/.snap/keeper/f") as fh:
            assert fh.read() == b"durable content"
    finally:
        f2.unmount()


# -- quotas (sharing the module cluster) --------------------------------------

def test_quota_max_files(fs):
    fs.mkdir("/q1")
    fs.set_quota("/q1", max_files=3)
    fs.mkdir("/q1/d1")                     # 1
    with fs.open("/q1/f1", "w") as f:      # 2
        f.write(b"x")
    with fs.open("/q1/d1/f2", "w") as f:   # 3 (nested counts)
        f.write(b"y")
    with pytest.raises(OSError) as ei:
        fs.open("/q1/f3", "w")
    assert ei.value.errno == 122           # EDQUOT
    with pytest.raises(OSError):
        fs.mkdir("/q1/d2")
    # freeing an entry unblocks creation
    fs.unlink("/q1/f1")
    with fs.open("/q1/f3", "w") as f:
        f.write(b"z")
    q = fs.get_quota("/q1")
    assert q["max_files"] == 3 and q["used_files"] == 3


def test_quota_max_bytes(fs):
    fs.mkdir("/q2")
    fs.set_quota("/q2", max_bytes=1000)
    with fs.open("/q2/a", "w") as f:
        f.write(b"A" * 600)
    # second write pushing past 1000 bytes is refused at flush/report
    with pytest.raises(OSError) as ei:
        with fs.open("/q2/b", "w") as f:
            f.write(b"B" * 600)
    assert ei.value.errno == 122
    # clearing the quota lifts the limit
    fs.set_quota("/q2", max_bytes=0)
    with fs.open("/q2/c", "w") as f:
        f.write(b"C" * 600)
    q = fs.get_quota("/q2")
    assert q["max_bytes"] == 0


def test_dot_snap_prefixed_names_are_ordinary(fs):
    # ".snapshots" is a normal directory name — only the exact ".snap"
    # segment is magic
    fs.mkdir("/backups")
    fs.mkdir("/backups/.snapshots")
    with fs.open("/backups/.snapshots/f", "w") as f:
        f.write(b"ordinary file")
    with fs.open("/backups/.snapshots/f") as f:
        assert f.read() == b"ordinary file"
    assert "f" in fs.listdir("/backups/.snapshots")
    fs.unlink("/backups/.snapshots/f")
