"""Upmap balancer (mgr balancer module / OSDMap::calc_pg_upmaps): the
optimizer flattens per-OSD PG counts via pg_upmap_items while
preserving failure-domain separation, and its plan executes through
the `osd pg-upmap-items` mon command path."""

from ceph_tpu.balancer import (
    calc_pg_upmaps, crush_parent, plan_commands, spread)
from ceph_tpu.crush import build_flat_map, build_two_level_map
from ceph_tpu.osd import OSDMap, PGPool
from ceph_tpu.osd.osdmap import CEPH_NOSD, POOL_TYPE_REPLICATED


def flat_cluster(n_osds=5, pg_num=64, size=3):
    crush, _root, rule = build_flat_map(n_osds)
    m = OSDMap(crush=crush)
    m.set_max_osd(n_osds)
    for o in range(n_osds):
        m.mark_up(o)
    m.pools[1] = PGPool(pool_id=1, type=POOL_TYPE_REPLICATED, size=size,
                        crush_rule=rule, pg_num=pg_num)
    return m


def host_cluster(n_hosts=5, osds_per_host=2, pg_num=64, size=3):
    crush, _root, rule = build_two_level_map(n_hosts, osds_per_host)
    m = OSDMap(crush=crush)
    n = n_hosts * osds_per_host
    m.set_max_osd(n)
    for o in range(n):
        m.mark_up(o)
    m.pools[1] = PGPool(pool_id=1, type=POOL_TYPE_REPLICATED, size=size,
                        crush_rule=rule, pg_num=pg_num)
    return m


def apply_changes(m, changes):
    for pgid, pairs in changes.items():
        if pairs:
            m.pg_upmap_items[pgid] = pairs
        else:
            m.pg_upmap_items.pop(pgid, None)


class TestOptimizer:
    def test_narrows_spread_on_flat_map(self):
        m = flat_cluster()
        lo0, hi0 = spread(m, 1)
        changes = calc_pg_upmaps(m, max_deviation=1)
        assert changes, "crush placement is never perfectly even"
        apply_changes(m, changes)
        lo1, hi1 = spread(m, 1)
        assert hi1 - lo1 < hi0 - lo0
        assert hi1 - lo1 <= 3      # near-flat after optimization

    def test_mappings_stay_valid(self):
        m = flat_cluster()
        apply_changes(m, calc_pg_upmaps(m))
        pool = m.pools[1]
        for ps in range(pool.pg_num):
            up, prim, _a, _ap = m.pg_to_up_acting_osds(1, ps)
            assert len(up) == pool.size
            assert len(set(up)) == pool.size, "duplicate osd in up set"
            assert all(o != CEPH_NOSD for o in up)
            assert prim in up

    def test_host_failure_domain_preserved(self):
        m = host_cluster()
        changes = calc_pg_upmaps(m, max_deviation=1)
        assert changes
        apply_changes(m, changes)
        pool = m.pools[1]
        for ps in range(pool.pg_num):
            up, _p, _a, _ap = m.pg_to_up_acting_osds(1, ps)
            hosts = [crush_parent(m, o) for o in up]
            assert len(set(hosts)) == len(up), \
                f"pg 1.{ps} co-located on one host: {up}"
        lo, hi = spread(m, 1)
        assert hi - lo <= 3

    def test_idempotent_when_balanced(self):
        m = flat_cluster()
        apply_changes(m, calc_pg_upmaps(m))
        again = calc_pg_upmaps(m)
        # a second pass finds (almost) nothing left to move
        assert len(again) <= 2

    def test_plan_command_shape(self):
        m = flat_cluster()
        cmds = plan_commands(m)
        assert cmds
        for c in cmds:
            assert c["prefix"] == "osd pg-upmap-items"
            assert len(c["id_pairs"]) % 2 == 0
            pool_id, ps = c["pgid"].split(".")
            assert int(pool_id) == 1
            assert 0 <= int(ps) < 64


class TestMonCommandPath:
    def test_upmap_items_via_mon(self):
        import time

        from ceph_tpu.tools.vstart import MiniCluster
        c = MiniCluster(n_osds=4, ms_type="loopback").start()
        try:
            c.wait_for_osd_count(4)
            client = c.client(timeout=15.0)
            pool_id = c.create_pool(client, pg_num=16, size=3)
            io = client.open_ioctx(pool_id)
            for i in range(8):
                io.write_full(f"bal{i}", b"v" * 64)
            # find a pg and a legal swap from its current up set
            m = c.mon.osdmap
            up, _p, _a, _ap = m.pg_to_up_acting_osds(pool_id, 0)
            frm = up[0]
            to = next(o for o in range(4) if o not in up)
            rc, out = client.mon_command(
                {"prefix": "osd pg-upmap-items",
                 "pgid": f"{pool_id}.0", "id_pairs": [frm, to]})
            assert rc == 0, out
            deadline = time.time() + 10
            while time.time() < deadline:
                up2, _p, _a, _ap = c.mon.osdmap.pg_to_up_acting_osds(
                    pool_id, 0)
                if to in up2 and frm not in up2:
                    break
                time.sleep(0.1)
            assert to in up2 and frm not in up2, (up, up2)
            # data written before the remap is still readable after
            time.sleep(1.0)     # let OSDs peer on the new interval
            for i in range(8):
                assert io.read(f"bal{i}") == b"v" * 64
            rc, out = client.mon_command(
                {"prefix": "osd rm-pg-upmap-items",
                 "pgid": f"{pool_id}.0"})
            assert rc == 0, out
            deadline = time.time() + 10
            while time.time() < deadline:
                if (pool_id, 0) not in c.mon.osdmap.pg_upmap_items:
                    break
                time.sleep(0.1)
            assert (pool_id, 0) not in c.mon.osdmap.pg_upmap_items
        finally:
            c.stop()

    def test_bad_upmap_rejected(self):
        from ceph_tpu.tools.vstart import MiniCluster
        c = MiniCluster(n_osds=3, ms_type="loopback").start()
        try:
            c.wait_for_osd_count(3)
            client = c.client(timeout=15.0)
            c.create_pool(client, pg_num=8, size=2)
            rc, _ = client.mon_command(
                {"prefix": "osd pg-upmap-items", "pgid": "99.0",
                 "id_pairs": [0, 1]})
            assert rc == -2
            rc, _ = client.mon_command(
                {"prefix": "osd pg-upmap-items", "pgid": "1.0",
                 "id_pairs": [0, 77]})
            assert rc == -2
            rc, _ = client.mon_command(
                {"prefix": "osd pg-upmap-items", "pgid": "1.0",
                 "id_pairs": [0]})
            assert rc == -22
            rc, _ = client.mon_command(
                {"prefix": "osd rm-pg-upmap-items", "pgid": "1.0"})
            assert rc == -2
        finally:
            c.stop()


def _skewed_map():
    """A flat map with skewed CRUSH weights -> skewed PG counts."""
    m = flat_cluster(n_osds=6, pg_num=128, size=3)
    root = m.crush.bucket(-1)
    root.item_weights = [0x40000, 0x10000, 0x10000, 0x10000,
                         0x10000, 0x8000]
    root.weight = sum(root.item_weights)
    return m


def test_calc_pg_upmaps_converges_both_tails():
    """One invocation flattens BOTH tails to within max_deviation —
    the stop condition must not quit when only one side looks fine."""
    m = _skewed_map()
    before = spread(m, 1)
    changes = calc_pg_upmaps(m, max_deviation=1, max_optimizations=2048)
    apply_changes(m, changes)
    lo, hi = spread(m, 1)
    assert hi - lo < before[1] - before[0]
    assert hi - lo <= 3, (before, (lo, hi))


def test_reweight_by_utilization():
    from ceph_tpu.balancer import (pool_pg_histogram,
                                   reweight_by_utilization)

    m = _skewed_map()
    plan = reweight_by_utilization(m, oload=110)
    assert plan, "skewed map should yield reweights"
    for o, w in plan:
        assert 0.0 <= w < 1.0
    # the nudged osds were genuinely the overloaded ones
    counts = {}
    for pool_id in m.pools:
        for o, pl in pool_pg_histogram(m, pool_id).items():
            counts[o] = counts.get(o, 0) + len(pl)
    mean = sum(counts.values()) / max(1, len(counts))
    for o, _w in plan:
        assert counts.get(o, 0) > mean
