"""OSDMap::Incremental analog: diff/apply/encode round trips, O(delta)
wire size on big maps, and end-to-end delta distribution with gap
recovery."""

from __future__ import annotations

import time

import numpy as np
import pytest

from ceph_tpu.crush import build_two_level_map
from ceph_tpu.osd.map_codec import (
    apply_incremental, decode_incremental, decode_osdmap, diff_osdmap,
    encode_incremental, encode_osdmap)
from ceph_tpu.osd.osdmap import OSDMap, PGPool


def _roundtrip_equal(a: OSDMap, b: OSDMap) -> bool:
    return encode_osdmap(a, with_auth=True) == \
        encode_osdmap(b, with_auth=True)


def _big_map(n_hosts=250, per_host=40) -> OSDMap:
    crush_map, _root, rid = build_two_level_map(n_hosts, per_host)
    m = OSDMap(epoch=1, crush=crush_map)
    m.set_max_osd(n_hosts * per_host)
    for i in range(n_hosts * per_host):
        m.osd_state[i] = 3
        m.osd_weight[i] = 0x10000
        m.osd_addrs[i] = f"10.0.{i >> 8}.{i & 255}:6800"
    m.pools[1] = PGPool(pool_id=1, type=1, size=3, min_size=2,
                        crush_rule=rid, pg_num=256, pgp_num=256)
    return m


def test_diff_apply_roundtrip_small_change():
    old = _big_map()
    new = decode_osdmap(encode_osdmap(old, with_auth=True))
    new.epoch = 2
    new.mark_down(17)
    new.osd_weight[99] = 0x8000
    new.pg_temp[(1, 7)] = [3, 4, 5]
    inc = diff_osdmap(old, new)
    blob = encode_incremental(inc)
    # O(delta): a one-osd change on a 10k-osd map is tiny
    full = len(encode_osdmap(new))
    assert len(blob) < full / 100, (len(blob), full)
    applied = decode_osdmap(encode_osdmap(old, with_auth=True))
    apply_incremental(applied, decode_incremental(blob))
    assert _roundtrip_equal(applied, new)


def test_diff_apply_pool_and_sidetables():
    old = _big_map()
    new = decode_osdmap(encode_osdmap(old, with_auth=True))
    new.epoch = 2
    new.pools[2] = PGPool(pool_id=2, type=2, size=4, min_size=3,
                          crush_rule=0, pg_num=64, pgp_num=64,
                          ec_profile={"k": "2", "m": "2"})
    del new.pools[1]
    new.config_db = {"global": {"debug": "5"}}
    new.fs_db = {"name": "cephfs", "max_mds": 1, "ranks": {},
                 "standbys": [], "metadata_pool": 2, "data_pool": 2}
    new.pg_upmap_items[(2, 3)] = [(1, 9)]
    inc = decode_incremental(encode_incremental(diff_osdmap(old, new)))
    applied = decode_osdmap(encode_osdmap(old, with_auth=True))
    apply_incremental(applied, inc)
    assert _roundtrip_equal(applied, new)


def test_apply_rejects_gaps():
    old = _big_map()
    new = decode_osdmap(encode_osdmap(old, with_auth=True))
    new.epoch = 5
    inc = diff_osdmap(old, new)
    with pytest.raises(ValueError):
        apply_incremental(old, inc)     # 1 -> 5 is not contiguous


def test_crush_change_ships_crush():
    old = _big_map()
    new = decode_osdmap(encode_osdmap(old, with_auth=True))
    new.epoch = 2
    new.crush.bucket(-1).weight += 1
    inc = diff_osdmap(old, new)
    assert "crush" in inc
    applied = decode_osdmap(encode_osdmap(old, with_auth=True))
    apply_incremental(applied, decode_incremental(
        encode_incremental(inc)))
    assert _roundtrip_equal(applied, new)


def test_removal_deltas():
    old = _big_map()
    old.pg_temp[(1, 3)] = [1, 2, 3]
    old.primary_temp[(1, 4)] = 7
    new = decode_osdmap(encode_osdmap(old, with_auth=True))
    new.epoch = 2
    del new.pg_temp[(1, 3)]
    del new.primary_temp[(1, 4)]
    inc = decode_incremental(encode_incremental(diff_osdmap(old, new)))
    applied = decode_osdmap(encode_osdmap(old, with_auth=True))
    apply_incremental(applied, inc)
    assert _roundtrip_equal(applied, new)


def test_cluster_distributes_deltas_live():
    """Live cluster: normal churn rides incrementals (the mon's history
    fills), every subscriber converges, and the deltas are a tiny
    fraction of the full map."""
    from ceph_tpu.tools.vstart import MiniCluster
    c = MiniCluster(n_osds=3).start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=8, size=2)
        mon = c.mon
        e0 = mon.osdmap.epoch
        # churn: weight changes -> one inc per epoch
        for i in range(4):
            rc, _ = client.mon_command({"prefix": "osd reweight",
                                        "id": 0,
                                        "weight": 0.5 + i * 0.1})
            assert rc == 0
        deadline = time.time() + 10
        while client.osdmap.epoch < mon.osdmap.epoch \
                and time.time() < deadline:
            time.sleep(0.05)
        assert client.osdmap.epoch == mon.osdmap.epoch
        assert client.osdmap.osd_weight[0] == mon.osdmap.osd_weight[0]
        incs = {e: b for e, b in mon._inc_history.items() if e > e0}
        assert incs, "churn produced no incrementals"
        full = len(encode_osdmap(mon.osdmap))
        for e, b in incs.items():
            assert len(b) < full / 4, (e, len(b), full)
        # OSDs converged off the same stream (their map pushes ride the
        # subscription renew tick — wait for it like the client above)
        deadline = time.time() + 10
        while time.time() < deadline and any(
                o.osdmap.epoch < mon.osdmap.epoch
                for o in c.osds.values()):
            time.sleep(0.05)
        for osd in c.osds.values():
            assert osd.osdmap.epoch == mon.osdmap.epoch
        # I/O still correct on the delta-built maps
        io = client.open_ioctx(pool)
        io.write_full("after-churn", b"delta-built map works")
        assert io.read("after-churn") == b"delta-built map works"

        # gapped subscriber: epoch far behind a TRIMMED history gets a
        # full map (simulate by clearing history and subscribing stale)
        from ceph_tpu.mon.monitor import MMonSubscribe

        class FakeCon:
            def __init__(self):
                self.sent = []
                self.peer_name = None

            def send_message(self, m):
                self.sent.append(m)

        with mon._lock:
            mon._inc_history.clear()
        sub = MMonSubscribe(name="client.9998", addr="nowhere",
                            epoch=max(1, mon.osdmap.epoch - 3))
        sub.connection = FakeCon()
        mon.ms_dispatch(sub)
        assert sub.connection.sent, "no backfill reply"
        assert sub.connection.sent[0].map_blob, \
            "gapped subscriber should get a FULL map"
        # and a merely-one-behind subscriber gets deltas once history
        # exists again
        rc, _ = client.mon_command({"prefix": "osd reweight", "id": 1,
                                    "weight": 0.9})
        assert rc == 0
        sub2 = MMonSubscribe(name="client.9999", addr="nowhere",
                             epoch=mon.osdmap.epoch - 1)
        sub2.connection = FakeCon()
        mon.ms_dispatch(sub2)
        assert sub2.connection.sent
        assert sub2.connection.sent[0].incs and \
            not sub2.connection.sent[0].map_blob
    finally:
        c.stop()
