"""GF(2^8) algebra over the polynomial 0x11d (x^8 + x^4 + x^3 + x^2 + 1).

This is the finite field used by the reference's erasure-code plugins (ISA-L's
ec_base and gf-complete's w=8 default both use 0x11d).  Everything here is host-side
numpy: table construction, matrix generators, and Gauss-Jordan inversion.  The device
kernels in ceph_tpu.ops consume the tables produced here.
"""

from .tables import (
    GF_POLY,
    gf_exp,
    gf_log,
    gf_mul,
    gf_div,
    gf_inv,
    gf_pow,
    mul_table,
    bit_matrix,
    nibble_bit_table,
)
from .matrix import (
    gen_cauchy1_matrix,
    gen_rs_vandermonde_matrix,
    gf_matmul,
    gf_invert_matrix,
)

__all__ = [
    "GF_POLY",
    "gf_exp",
    "gf_log",
    "gf_mul",
    "gf_div",
    "gf_inv",
    "gf_pow",
    "mul_table",
    "bit_matrix",
    "nibble_bit_table",
    "gen_cauchy1_matrix",
    "gen_rs_vandermonde_matrix",
    "gf_matmul",
    "gf_invert_matrix",
]
