"""Cross-daemon distributed tracing with SPAN TREES, sampling, and
tail retention of slow traces (src/tracing/oprequest.tp +
src/common/zipkin_trace.h analogs, Dapper-style span model).

A trace is a tree of spans.  Each span has a span_id, a
parent_span_id, begin/end times, and key/value attributes (pool, pg,
op size, kernel batch shape); point events (OpTracker stages,
messenger tx, device h2d/d2h) attach to the span that was current when
they fired.  The ids ride the message frame (a flagged header
extension carrying ``(trace_id, parent_span_id)``, see msg.message):
the client's root span parents its op's tx span, every receiver opens
an ``rx <MsgType>`` dispatch span parented to the sender's span, and
the whole client → primary → shard → commit tree reconstructs from the
rows.  ``dump(trace_id)`` returns the flat time-ordered rows (the
admin-socket payload); ``span_tree(trace_id)`` nests them.

Sampling policy — head sampling plus tail retention:

  * ``tracing_sample_rate`` (config): probability that an UNTRACED
    client op opens a trace (``maybe_sampled``).  Explicit
    ``trace_ctx`` calls are always traced (a forced trace).
  * ``tracing_slow_threshold`` (config): a completed trace whose ROOT
    span ran at least this long is promoted into a bounded slow-trace
    ring (``tracing_slow_ring`` entries) instead of being evicted with
    the rest — the Dapper tail-based retention that keeps exactly the
    traces worth debugging.  Fast traces age out of the active table.

Propagation is THREAD-SCOPED: the dispatch loop installs the current
(trace_id, span_id) for the duration of handling a traced message, so
synchronous fan-out (the op pipeline) is covered; work handed to
timers/workers starts untraced unless it re-enters with set_current
from the ids stored on the message.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager

from ceph_tpu.common import lockdep

_tls = threading.local()
# import-time module lock: named under CEPH_TPU_LOCKDEP=1 (the env
# gate is read before any module imports), plain otherwise
_lock = lockdep.make_lock("tracing::registry")

#: active/recent traces kept for stitching (FIFO eviction; slow traces
#: survive in the dedicated ring below)
_ACTIVE_CAP_DEFAULT = 512
_active_cap = _ACTIVE_CAP_DEFAULT
#: span+event rows per trace (runaway-fan-out guard)
MAX_ROWS_PER_TRACE = 4096

#: head-sampling probability for maybe_sampled (0 = only explicit traces)
_DEFAULT_SAMPLE_RATE = 0.0
_sample_rate = _DEFAULT_SAMPLE_RATE
#: root-span duration (seconds) at/above which a completed trace is
#: promoted into the slow ring
_DEFAULT_SLOW_THRESHOLD = 0.5
_slow_threshold = _DEFAULT_SLOW_THRESHOLD
_DEFAULT_SLOW_RING = 64
_slow_ring_size = _DEFAULT_SLOW_RING

#: trace_id -> _Trace (insertion-ordered for FIFO eviction)
_traces: "OrderedDict[int, _Trace]" = OrderedDict()
#: trace_id -> completed slow-trace snapshot (tail retention)
_slow: "OrderedDict[int, dict]" = OrderedDict()


class Span:
    """One node of a trace tree.

    Two clocks per span, deliberately: ``start``/``end`` are
    wall-clock DISPLAY timestamps (row ordering, dashboards, humans
    correlating with logs), while ``start_mono``/``end_mono`` pair a
    monotonic clock for every DURATION — an NTP step mid-span used to
    yield negative/skewed durations, which then mis-ranked the
    slow-trace tail sampling exactly when a clock jump made latency
    interesting.
    """

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name",
                 "daemon", "start", "end", "attrs", "start_mono",
                 "end_mono")

    def __init__(self, trace_id: int, span_id: int, parent_span_id: int,
                 name: str, daemon: str, start: float,
                 attrs: dict | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.name = name
        self.daemon = daemon
        self.start = start
        self.start_mono = time.monotonic()
        self.end: float | None = None
        self.end_mono: float | None = None
        self.attrs = attrs or {}

    @property
    def duration(self) -> float | None:
        """Monotonic-clock duration (never negative, NTP-immune)."""
        return (None if self.end_mono is None
                else self.end_mono - self.start_mono)

    def row(self) -> dict:
        r = {"trace_id": self.trace_id, "daemon": self.daemon,
             "event": self.name, "t": self.start, "kind": "span",
             "span_id": self.span_id,
             "parent_span_id": self.parent_span_id,
             "dur": self.duration}
        if self.attrs:
            r["attrs"] = dict(self.attrs)
        return r


class _Trace:
    __slots__ = ("trace_id", "spans", "events", "root_span_id",
                 "started", "completed", "dropped_rows")

    def __init__(self, trace_id: int):
        self.trace_id = trace_id
        #: span_id -> Span (insertion ordered)
        self.spans: "OrderedDict[int, Span]" = OrderedDict()
        #: (span_id, daemon, event, t) point events
        self.events: list[tuple[int, str, str, float]] = []
        self.root_span_id = 0
        self.started = time.time()
        self.completed = False
        self.dropped_rows = 0

    def n_rows(self) -> int:
        return len(self.spans) + len(self.events)

    def rows(self) -> list[dict]:
        out = [sp.row() for sp in self.spans.values()]
        out.extend({"trace_id": self.trace_id, "daemon": d, "event": e,
                    "t": t, "kind": "event", "span_id": sid}
                   for sid, d, e, t in self.events)
        out.sort(key=lambda r: r["t"])
        return out


# -- ids and thread context ---------------------------------------------------

def new_trace_id() -> int:
    return int.from_bytes(os.urandom(8), "big") >> 1 or 1


def new_span_id() -> int:
    return int.from_bytes(os.urandom(8), "big") >> 1 or 1


def current() -> int:
    """The calling thread's current trace id (0 = untraced)."""
    return getattr(_tls, "ctx", (0, 0))[0]


def current_span() -> int:
    """The calling thread's current span id (0 = none)."""
    return getattr(_tls, "ctx", (0, 0))[1]


def set_current(trace_id, span_id: int = 0):
    """Install (trace_id, span_id) as the thread's current context;
    returns the previous context (restore it via set_current when
    done).  Accepts either two ints or the tuple a prior call
    returned."""
    if isinstance(trace_id, tuple):
        trace_id, span_id = trace_id
    prev = getattr(_tls, "ctx", (0, 0))
    _tls.ctx = (trace_id, span_id)
    return prev


# -- trace table internals ----------------------------------------------------

def _get_trace(tid: int, create: bool = True) -> _Trace | None:
    """Caller must hold _lock."""
    tr = _traces.get(tid)
    if tr is None and create:
        if tid in _slow:
            # the trace already completed, was promoted, and aged out
            # of the active table: a straggler row must not resurrect
            # an empty ghost that would shadow the archived snapshot
            return None
        tr = _Trace(tid)
        _traces[tid] = tr
        while len(_traces) > _active_cap:
            _evict_one_locked()
    return tr


def _evict_one_locked() -> None:
    """Drop one trace: COMPLETED (fast, un-promoted) traces go first —
    an in-flight trace may still turn out slow, and evicting it would
    defeat tail retention exactly when sampling load makes it matter.
    Only when every retained trace is still open does the oldest open
    one go (the runaway bound must hold regardless)."""
    for tid, tr in _traces.items():
        if tr.completed:
            del _traces[tid]
            return
    _traces.popitem(last=False)


def begin_span(name: str, daemon: str, trace_id: int | None = None,
               parent_span_id: int | None = None,
               attrs: dict | None = None) -> Span | None:
    """Open a span.  trace_id/parent default to the thread context;
    returns None when there is no trace to attach to.  Does NOT touch
    the thread context — callers that dispatch work under the span
    install it via set_current."""
    tid = current() if trace_id is None else trace_id
    if not tid:
        return None
    parent = current_span() if parent_span_id is None else parent_span_id
    sp = Span(tid, new_span_id(), parent, name, daemon,
              time.time(), attrs)
    with _lock:
        tr = _get_trace(tid)
        if tr is None or tr.n_rows() >= MAX_ROWS_PER_TRACE:
            if tr is not None:
                tr.dropped_rows += 1
            return None
        tr.spans[sp.span_id] = sp
        if not tr.root_span_id and not parent:
            tr.root_span_id = sp.span_id
    return sp


def finish_span(span: Span | None, t: float | None = None) -> None:
    """Close a span.  ``t`` (wall clock) overrides the DISPLAY end
    timestamp only — duration math always pairs the monotonic clock,
    with an explicit t treated as a caller-computed wall offset from
    the span's own start (``t=span.start`` = instantaneous marker), so
    a stepped wall clock can never produce a negative duration."""
    if span is None:
        return
    with _lock:
        if t is None:
            span.end = time.time()
            span.end_mono = time.monotonic()
        else:
            span.end = t
            span.end_mono = span.start_mono + max(0.0, t - span.start)


def span_event(span: Span | None, event: str,
               t: float | None = None) -> None:
    """Attach a point event to an open span."""
    if span is None:
        return
    record(span.daemon, event, trace_id=span.trace_id,
           span_id=span.span_id, t=t)


def set_attrs(span: Span | None, **attrs) -> None:
    if span is None:
        return
    with _lock:
        span.attrs.update(attrs)


@contextmanager
def span(name: str, daemon: str = "", **attrs):
    """Open a child span of the thread's current span for the duration
    of the block; no-op (yields None) when the thread is untraced."""
    tid = current()
    if not tid:
        yield None
        return
    sp = begin_span(name, daemon or "span", attrs=attrs or None)
    if sp is None:        # row-cap hit
        yield None
        return
    prev = set_current(tid, sp.span_id)
    try:
        yield sp
    finally:
        set_current(prev)
        finish_span(sp)


@contextmanager
def trace_ctx(trace_id: int | None = None, name: str = "trace",
              daemon: str = "client"):
    """Open (or join) a trace for the calling thread.  The contextmanager
    opens a span; when that span is the trace's ROOT, exiting completes
    the trace (tail-retention check against tracing_slow_threshold)."""
    tid = trace_id or new_trace_id()
    join = current() == tid
    sp = begin_span(name, daemon, trace_id=tid,
                    parent_span_id=current_span() if join else 0)
    prev = set_current(tid, sp.span_id if sp else 0)
    try:
        yield tid
    finally:
        set_current(prev)
        finish_span(sp)
        if sp is not None:
            _maybe_complete(tid, sp)


@contextmanager
def maybe_sampled(name: str = "op", daemon: str = "client"):
    """Head sampling: join the current trace if one exists, else open a
    new one with probability ``tracing_sample_rate``.  Yields the trace
    id (0 when unsampled)."""
    tid = current()
    if tid:
        yield tid
        return
    if _sample_rate <= 0.0 or random.random() >= _sample_rate:
        yield 0
        return
    with trace_ctx(name=name, daemon=daemon) as t:
        yield t


def _maybe_complete(tid: int, root: Span) -> None:
    with _lock:
        tr = _traces.get(tid)
        if tr is None or tr.root_span_id != root.span_id:
            return
        tr.completed = True
        dur = root.duration or 0.0
        if dur < _slow_threshold:
            return
        _slow[tid] = {
            "trace_id": tid,
            "root": root.name,
            "daemon": root.daemon,
            "duration": round(dur, 6),
            "completed_at": root.end,
            "n_spans": len(tr.spans),
            "rows": tr.rows(),
        }
        while len(_slow) > _slow_ring_size:
            _slow.popitem(last=False)


# -- event recording ----------------------------------------------------------

def record(daemon: str, event: str, trace_id: int | None = None,
           span_id: int | None = None, t: float | None = None) -> None:
    """Attach a point event to a trace (to the thread's current span
    when it belongs to the same trace)."""
    tid = trace_id if trace_id is not None else current()
    if not tid:
        return
    if span_id is None:
        span_id = current_span() if current() == tid else 0
    stamp_t = time.time() if t is None else t
    with _lock:
        tr = _get_trace(tid)
        if tr is None or tr.n_rows() >= MAX_ROWS_PER_TRACE:
            if tr is not None:
                tr.dropped_rows += 1
            return
        if not span_id:
            # an event recorded off-thread (explicit trace_id) still
            # belongs in the tree: attach it to the trace root
            span_id = tr.root_span_id
        tr.events.append((span_id, daemon, event, stamp_t))


def stamp(msg, daemon: str) -> None:
    """Transport send hook: a message sent by a thread holding a trace
    inherits the ids (once) — the send itself becomes an instantaneous
    ``tx <MsgType>`` span whose span_id rides the frame as the
    receiver's parent, so the rx dispatch span parents under this hop.
    Runs on the CALLER's thread — transports that encode later on an
    event loop still carry the ids because they live on the message."""
    if getattr(msg, "trace_id", 0):
        return
    tid = current()
    if not tid:
        return
    msg.trace_id = tid
    sp = begin_span(f"tx {type(msg).__name__}", daemon, trace_id=tid)
    if sp is not None:
        finish_span(sp, t=sp.start)      # instantaneous hop marker
        msg.parent_span_id = sp.span_id
    else:
        msg.parent_span_id = current_span()


# -- query surface ------------------------------------------------------------

def events(trace_id: int) -> list[dict]:
    return [{"daemon": r["daemon"], "event": r["event"], "t": r["t"]}
            for r in dump(trace_id)]


def dump(trace_id: int | None = None) -> list[dict]:
    """Stitched span-structured timeline(s), time-ordered — the
    admin-socket payload.  Every row carries span_id (and, for spans,
    parent_span_id/dur/attrs).  Falls back to the slow ring for traces
    already evicted from the active table."""
    with _lock:
        if trace_id is None:
            out = []
            for tr in _traces.values():
                out.extend(tr.rows())
            # slow-ring-only traces (already evicted from the active
            # table) stay visible in the unfiltered view too
            for tid, snap in _slow.items():
                if tid not in _traces:
                    out.extend(dict(r) for r in snap["rows"])
            out.sort(key=lambda r: r["t"])
            return out
        tr = _traces.get(trace_id)
        if tr is not None:
            return tr.rows()
        snap = _slow.get(trace_id)
        return [dict(r) for r in snap["rows"]] if snap else []


def trace_ids() -> list[int]:
    with _lock:
        return sorted(set(_traces) | set(_slow))


def tree_from_rows(rows: list[dict]) -> list[dict]:
    """Nest span rows into trees: spans with their events and
    children.  Spans whose parent is unknown (0, or a span on a daemon
    whose rows were not shipped) surface as roots.  Shared by
    span_tree and the mgr insights module's cluster-wide merge."""
    nodes: dict[int, dict] = {}
    for r in rows:
        if r.get("kind") == "span":
            nodes[r["span_id"]] = {
                "span_id": r["span_id"],
                "parent_span_id": r.get("parent_span_id", 0),
                "name": r.get("event"), "daemon": r.get("daemon"),
                "start": r.get("t"), "dur": r.get("dur"),
                "attrs": r.get("attrs", {}),
                "events": [], "children": []}
    roots: list[dict] = []
    for r in rows:
        if r.get("kind") == "span":
            n = nodes[r["span_id"]]
            parent = nodes.get(n["parent_span_id"])
            (parent["children"] if parent else roots).append(n)
        else:
            holder = nodes.get(r.get("span_id", 0))
            if holder is not None:
                holder["events"].append(
                    {"daemon": r.get("daemon"), "event": r.get("event"),
                     "t": r.get("t")})
    return roots


def span_tree(trace_id: int) -> dict:
    """One trace's nested tree view."""
    rows = dump(trace_id)
    return {"trace_id": trace_id, "n_rows": len(rows),
            "spans": tree_from_rows(rows)}


# -- slow-trace ring (tail retention) -----------------------------------------

def slow_traces() -> list[dict]:
    """Completed traces whose root span crossed the slow threshold,
    oldest first (each entry: trace_id, root, daemon, duration,
    completed_at, n_spans, rows)."""
    with _lock:
        return [dict(s) for s in _slow.values()]


def slow_trace_digests(limit: int = 16,
                       max_rows: int = 128) -> list[dict]:
    """Compact newest-first digests for MMgrReport (rows capped)."""
    with _lock:
        snaps = list(_slow.values())[-limit:]
    out = []
    for s in reversed(snaps):
        d = {k: s[k] for k in ("trace_id", "root", "daemon", "duration",
                               "completed_at", "n_spans")}
        d["rows"] = [dict(r) for r in s["rows"][:max_rows]]
        out.append(d)
    return out


def slow_summary() -> dict:
    """{count, p99_root_ms} over the slow ring — bench.py's tail-latency
    digest."""
    with _lock:
        durs = sorted(s["duration"] for s in _slow.values())
    if not durs:
        return {"count": 0, "p99_root_ms": 0.0}
    p99 = durs[min(len(durs) - 1, int(0.99 * (len(durs) - 1) + 0.999))]
    return {"count": len(durs), "p99_root_ms": round(p99 * 1e3, 3)}


# -- policy knobs -------------------------------------------------------------

def set_sample_rate(rate) -> None:
    global _sample_rate
    _sample_rate = min(1.0, max(0.0, float(rate)))


def set_slow_threshold(seconds) -> None:
    global _slow_threshold
    _slow_threshold = max(0.0, float(seconds))


def set_slow_ring(size: int) -> None:
    global _slow_ring_size
    _slow_ring_size = max(1, int(size))
    with _lock:
        while len(_slow) > _slow_ring_size:
            _slow.popitem(last=False)


def set_active_cap(size: int) -> None:
    """Bound on concurrently retained (non-slow) traces; test surface."""
    global _active_cap
    _active_cap = max(1, int(size))
    with _lock:
        while len(_traces) > _active_cap:
            _traces.popitem(last=False)


def configure_from_conf(conf) -> None:
    """Bind the sampling knobs to a context's config with hot reload.

    The trace tables are process-global while configs are per-context
    (multi-daemon processes construct many): construction only applies
    values that DIFFER from the defaults — it never resets a global
    back to its default, or every later daemon/client construction
    would silently undo an operator's `config set` on another daemon.
    Runtime changes propagate through the observers."""
    for name, setter, dflt in (
            ("tracing_sample_rate", set_sample_rate,
             _DEFAULT_SAMPLE_RATE),
            ("tracing_slow_threshold", set_slow_threshold,
             _DEFAULT_SLOW_THRESHOLD),
            ("tracing_slow_ring", set_slow_ring, _DEFAULT_SLOW_RING)):
        try:
            v = conf.get(name)
            if float(v) != dflt:
                setter(v)
            conf.add_observer(
                name, lambda _n, val, s=setter: s(val))
        except KeyError:   # option table without the knob
            pass


def reset() -> None:
    """Drop every trace and restore default policy (test isolation)."""
    global _sample_rate, _slow_threshold, _slow_ring_size, _active_cap
    with _lock:
        _traces.clear()
        _slow.clear()
    _sample_rate = _DEFAULT_SAMPLE_RATE
    _slow_threshold = _DEFAULT_SLOW_THRESHOLD
    _slow_ring_size = _DEFAULT_SLOW_RING
    _active_cap = _ACTIVE_CAP_DEFAULT
