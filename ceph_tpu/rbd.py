"""librbd-lite — block images striped over RADOS objects
(src/librbd/ analog: ImageRequest -> ObjectRequest over a striped
layout; header object + rbd_data.<id>.<objno> data objects).

An image is a fixed-size virtual block device: create/open/read/write
at arbitrary byte offsets, resize, stat, remove.  On top of the basic
I/O path:

  * rbd_directory — pool-level image registry (librbd's rbd_directory
    omap object), so `list_images` needs no name probes
  * exclusive lock — the managed lock over the cls lock object class
    on the header (librbd ManagedLock/ExclusiveLock): acquire/release/
    break, and writes refuse while another owner holds it
  * snapshots — snap_create/list/remove/rollback + read(snap=...),
    riding pool snapshots namespaced per image (`rbd.<image>.<snap>`),
    with the image size frozen in the header's snap table
  * clone — flatten-style copy of a snapshot into a new image
"""

from __future__ import annotations

import binascii
import json

from ceph_tpu.osdc.journaler import Journaler
from ceph_tpu.osdc.striper import StripeLayout, StripedObject

RBD_DIRECTORY = "rbd_directory"

#: image feature bits (librbd feature flags; journaling gates the
#: write-ahead event journal that rbd-mirror replays)
FEATURE_JOURNALING = "journaling"


class Image:
    HEADER_FMT = "rbd_header.{name}"
    DATA_FMT = "rbd_data.{name}"

    def __init__(self, ioctx, name: str):
        self.io = ioctx
        self.name = name
        self._meta = None

    # -- lifecycle ------------------------------------------------------------

    @classmethod
    def create(cls, ioctx, name: str, size: int,
               order: int = 22, stripe_unit: int = 1 << 16,
               stripe_count: int = 4, primary: bool = True,
               features: list[str] | None = None) -> "Image":
        """order = log2(object size), like rbd create --order.
        primary=False creates a demoted replication target atomically
        (no primary window for a mirror-daemon crash to leave open)."""
        header = cls.HEADER_FMT.format(name=name)
        exists = True
        try:
            ioctx.stat(header)
        except OSError:
            exists = False
        if exists:
            raise FileExistsError(f"image {name!r} exists")
        meta = {"size": size, "order": order,
                "stripe_unit": stripe_unit,
                "stripe_count": stripe_count, "snaps": {},
                "features": list(features or []), "primary": primary}
        ioctx.write_full(header, json.dumps(meta).encode())
        ioctx.set_omap(RBD_DIRECTORY, {name: b"1"})
        img = cls(ioctx, name)
        img._meta = meta
        return img

    def _load(self) -> dict:
        if self._meta is None:
            blob = self.io.read(self.HEADER_FMT.format(name=self.name))
            self._meta = json.loads(blob.decode())
        return self._meta

    def _striped(self) -> StripedObject:
        m = self._load()
        layout = StripeLayout(stripe_unit=m["stripe_unit"],
                              stripe_count=m["stripe_count"],
                              object_size=1 << m["order"])
        return StripedObject(self.io, self.DATA_FMT.format(name=self.name),
                             layout)

    # -- features / journaling (librbd/Journal.h:43 analog) -------------------

    JOURNAL_FMT = "journal_rbd.{name}"

    def features(self) -> list[str]:
        return list(self._load().get("features", []))

    def feature_enable(self, feature: str) -> None:
        m = self._load()
        feats = m.setdefault("features", [])
        if feature in feats:
            return
        feats.append(feature)
        if feature == FEATURE_JOURNALING:
            j = self._journal()
            try:
                j.open()
            except OSError:
                j.create()
        self._save_meta(m)

    def feature_disable(self, feature: str) -> None:
        m = self._load()
        if feature in m.get("features", []):
            m["features"].remove(feature)
            self._save_meta(m)

    def _journal(self) -> Journaler:
        return Journaler(self.io, self.JOURNAL_FMT.format(name=self.name))

    def _journal_event(self, event: dict) -> None:
        """Write-ahead: mutations on a journaled image append the event
        and flush BEFORE touching image data (librbd Journal ordering);
        rbd-mirror replays these on the peer cluster.  Events carry
        absolute offsets/states so replay is idempotent."""
        if FEATURE_JOURNALING not in self._load().get("features", []):
            return
        j = self._journal()
        try:
            j.open()
        except OSError:
            j.create()   # feature set at create-time (mirror targets)
        j.append_entry(json.dumps(event).encode())
        j.flush()

    # -- primary / demote (rbd mirror promote/demote) -------------------------

    def is_primary(self) -> bool:
        return bool(self._load().get("primary", True))

    def promote(self) -> None:
        m = self._load()
        m["primary"] = True
        self._save_meta(m)

    def demote(self) -> None:
        """Non-primary images are read-only replication targets; only
        the mirror daemon's replay applies to them (mirror_apply)."""
        m = self._load()
        m["primary"] = False
        self._save_meta(m)

    def _check_primary(self) -> None:
        # re-read the header: another handle (the mirror daemon, an
        # operator CLI) may have demoted us — librbd learns this through
        # its header watch; here a read per gated mutation is the analog
        self._meta = None
        if not self._load().get("primary", True):
            raise OSError(30, f"image {self.name!r} is non-primary "
                              "(demoted mirror target)")  # EROFS

    # -- I/O ------------------------------------------------------------------

    def stat(self) -> dict:
        m = self._load()
        return {"size": m["size"], "order": m["order"],
                "stripe_unit": m["stripe_unit"],
                "stripe_count": m["stripe_count"],
                "features": list(m.get("features", [])),
                "primary": m.get("primary", True)}

    def write(self, data: bytes, offset: int = 0) -> int:
        self._check_primary()   # refreshes the header cache too
        m = self._load()
        if offset + len(data) > m["size"]:
            raise ValueError("write past end of image")
        self._check_lock()
        self._journal_event({"op": "write", "off": offset,
                             "data": binascii.hexlify(data).decode()})
        self._striped().write(data, offset)
        return len(data)

    def mirror_apply(self, event: dict) -> None:
        """Apply one replayed journal event (rbd-mirror's Replayer):
        bypasses the primary gate — replication IS how a demoted image
        changes — but still respects sizes and is idempotent."""
        op = event["op"]
        if op == "write":
            data = binascii.unhexlify(event["data"])
            m = self._load()
            end = event["off"] + len(data)
            if end > m["size"]:
                m["size"] = end
                self._save_meta(m)
            self._striped().write(data, event["off"])
        elif op == "resize":
            m = self._load()
            if event["size"] < m["size"]:
                self._striped().truncate(event["size"])
            m["size"] = event["size"]
            self._save_meta(m)
        elif op == "snap_create":
            if event["snap"] not in self.snap_list():
                self._snap_create_internal(event["snap"])
        elif op == "snap_remove":
            if event["snap"] in self.snap_list():
                self._snap_remove_internal(event["snap"])
        elif op == "snap_rollback":
            # the target rolls back against ITS copy of the snapshot
            # (created by the replayed snap_create at the same journal
            # position, so contents match the primary's at rollback time)
            self._snap_rollback_internal(event["snap"])
        else:
            raise ValueError(f"unknown journal event {op!r}")

    def read(self, offset: int = 0, length: int = 0,
             snap: str | None = None) -> bytes:
        m = self._load()
        snapid = 0
        size = m["size"]
        if snap is not None:
            ent = m.get("snaps", {}).get(snap)
            if ent is None:
                raise KeyError(f"no snapshot {snap!r}")
            snapid, size = ent["snapid"], ent["size"]
        if length <= 0 or offset + length > size:
            length = max(0, size - offset)
        data = self._striped().read(offset, length, snapid=snapid)
        if len(data) < length:      # unwritten space reads as zeros
            data = data + bytes(length - len(data))
        return data

    # -- exclusive lock (librbd ManagedLock over cls lock) --------------------

    def _header(self) -> str:
        return self.HEADER_FMT.format(name=self.name)

    def lock_acquire(self, owner: str) -> None:
        self.io.execute(self._header(), "lock", "lock",
                        json.dumps({"owner": owner}).encode())
        self._owner = owner

    def lock_release(self, owner: str | None = None) -> None:
        self.io.execute(self._header(), "lock", "unlock",
                        json.dumps({"owner": owner
                                    or getattr(self, "_owner",
                                               None)}).encode())
        self._owner = None

    def lock_info(self) -> dict:
        return json.loads(self.io.execute(self._header(), "lock", "info"))

    def break_lock(self) -> None:
        """Steal a dead client's lock (rbd lock break)."""
        holder = self.lock_info().get("holder")
        if holder:
            self.io.execute(self._header(), "lock", "unlock",
                            json.dumps({"owner": holder}).encode())

    def _check_lock(self) -> None:
        """Writes respect an exclusive lock held by another owner.  A
        handle that holds the lock itself skips the round trip (its
        ownership stands until it releases; a concurrent break_lock is
        the operator declaring this writer dead, as in the reference,
        where the broken client is blocklisted).  Any other handle pays
        one lock_info per write — correctness over latency here."""
        if getattr(self, "_owner", None) is not None:
            return
        try:
            holder = self.lock_info().get("holder")
        except OSError:
            holder = None
        if holder is not None:
            raise OSError(16, f"image locked by {holder!r}")  # EBUSY

    # -- snapshots (pool snaps namespaced per image) --------------------------

    def _save_meta(self, m: dict) -> None:
        self.io.write_full(self._header(), json.dumps(m).encode())
        self._meta = m

    def snap_create(self, snap: str) -> int:
        self._check_primary()
        snapid = self._snap_create_internal(snap)
        # journal AFTER the mon op succeeds: a failed snap must never
        # replay onto the mirror (the reverse window — snap taken, crash
        # before journaling — loses only the mirror's copy of the snap,
        # the recoverable direction)
        self._journal_event({"op": "snap_create", "snap": snap})
        return snapid

    def _snap_create_internal(self, snap: str) -> int:
        """Snapshot without the primary gate or journaling: the public
        path wraps this; mirror replay (mirror_apply) calls it directly
        so replicated snaps neither re-journal on the target nor bounce
        off its demoted state."""
        m = self._load()
        if snap in m.get("snaps", {}):
            raise FileExistsError(f"snapshot {snap!r} exists")
        rc, out = self.io.client.mon_command({
            "prefix": "osd pool mksnap", "pool": self.io.pool_id,
            "snap": f"rbd.{self.name}.{snap}"})
        if rc != 0:
            raise OSError(-rc or 5, out)
        reply = json.loads(out)
        snapid = reply["snapid"]
        # map-propagation barrier: a write issued right after this must
        # carry the post-snap epoch, or a stale primary could skip the
        # pre-write COW clone and silently corrupt the snapshot
        if "epoch" in reply:
            self.io.client.wait_for_epoch(reply["epoch"])
        m.setdefault("snaps", {})[snap] = {"snapid": snapid,
                                           "size": m["size"]}
        self._save_meta(m)
        return snapid

    def snap_list(self) -> dict:
        return dict(self._load().get("snaps", {}))

    def snap_remove(self, snap: str) -> None:
        self._check_primary()
        self._snap_remove_internal(snap)
        self._journal_event({"op": "snap_remove", "snap": snap})

    def _snap_remove_internal(self, snap: str) -> None:
        m = self._load()
        if snap not in m.get("snaps", {}):
            raise KeyError(f"no snapshot {snap!r}")
        rc, out = self.io.client.mon_command({
            "prefix": "osd pool rmsnap", "pool": self.io.pool_id,
            "snap": f"rbd.{self.name}.{snap}"})
        if rc != 0:
            raise OSError(-rc or 5, out)
        del m["snaps"][snap]
        self._save_meta(m)

    def snap_rollback(self, snap: str) -> None:
        """Restore image content to the snapshot (rbd snap rollback —
        object-by-object copy-back, librbd's simple_rollback).  On a
        journaled image the rollback is journaled like any other mutation
        (write-ahead, before the data moves): the mirror replays it
        against its own replicated snapshot, so the pair stays converged
        instead of silently diverging on an unjournaled full rewrite."""
        self._check_primary()
        if snap not in self._load().get("snaps", {}):
            raise KeyError(f"no snapshot {snap!r}")
        self._check_lock()
        self._journal_event({"op": "snap_rollback", "snap": snap})
        self._snap_rollback_internal(snap)

    def _snap_rollback_internal(self, snap: str) -> None:
        m = self._load()
        ent = m.get("snaps", {}).get(snap)
        if ent is None:
            raise KeyError(f"no snapshot {snap!r}")
        data = self.read(0, ent["size"], snap=snap)
        st = self._striped()
        st.truncate(0)
        st.write(data, 0)
        m["size"] = ent["size"]
        self._save_meta(m)

    def clone(self, dst_name: str, snap: str) -> "Image":
        """Copy a snapshot into a new image (clone + immediate flatten:
        the lite model has no parent/child overlay chain)."""
        m = self._load()
        ent = m.get("snaps", {}).get(snap)
        if ent is None:
            raise KeyError(f"no snapshot {snap!r}")
        dst = Image.create(self.io, dst_name, size=ent["size"],
                           order=m["order"], stripe_unit=m["stripe_unit"],
                           stripe_count=m["stripe_count"])
        data = self.read(0, ent["size"], snap=snap)
        if data.rstrip(b"\x00"):
            dst.write(data, 0)
        return dst

    def resize(self, new_size: int) -> None:
        self._check_primary()
        m = self._load()
        self._check_lock()
        self._journal_event({"op": "resize", "size": new_size})
        if new_size < m["size"]:
            # shrink trims the discarded extent (real rbd semantics):
            # growing back later must read zeros, not stale payload
            self._striped().truncate(new_size)
        m["size"] = new_size
        self._save_meta(m)

    def remove(self) -> None:
        # librbd refuses removal while snapshots exist: the pool snaps
        # are only reachable through this header's name->snapid table
        if self._load().get("snaps"):
            raise OSError(16, "image has snapshots (remove them first)")
        self._check_lock()   # and while another owner holds the lock
        self._striped().remove()
        try:
            self.io.remove(self.HEADER_FMT.format(name=self.name))
        except OSError:
            pass
        try:
            self.io.rm_omap_keys(RBD_DIRECTORY, [self.name])
        except OSError:
            pass
        self._meta = None


def list_images(ioctx, probe: list[str] | None = None) -> list[str]:
    """Pool image listing from the rbd_directory omap object, unioned
    with probe hits (legacy images created before the directory existed
    still appear, even once the directory object does)."""
    found = set()
    try:
        found.update(ioctx.get_omap(RBD_DIRECTORY))
    except OSError:
        pass
    for name in probe or []:
        if name in found:
            continue
        try:
            ioctx.stat(Image.HEADER_FMT.format(name=name))
            found.add(name)
        except OSError:
            continue
    return sorted(found)
