"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding (pjit/shard_map over a
jax.sharding.Mesh) is exercised without TPU hardware — the same mechanism the driver's
dryrun uses.  This must be configured before jax initializes its backends.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# config.update, not the env var: the environment exports JAX_PLATFORMS=axon (the
# real TPU tunnel) and the plugin outranks an env override, but tests need the
# virtual 8-device CPU mesh.  When a TPU platform IS advertised by the
# environment, expose it ALONGSIDE cpu ("cpu,axon": cpu stays the default
# backend) so the compiled-TPU cross-validation gate runs by default on TPU
# hosts instead of being silently skipped — that suite is the only thing that
# catches Mosaic compiled-path miscompiles (round 3's is_out bug).
_plat = os.environ.get("CEPH_TPU_TEST_PLATFORM")
if _plat is None:
    _env = os.environ.get("JAX_PLATFORMS", "")
    _tpu = next((p for p in ("axon", "tpu") if p in _env.split(",")), None)
    _plat = f"cpu,{_tpu}" if _tpu else "cpu"
jax.config.update("jax_platforms", _plat)

import ceph_tpu  # noqa: E402,F401  (enables x64 before tests create arrays)
