"""Sharded op queue with mClock QoS scheduling.

The reference pushes every op through a sharded work queue
(osd/OSD.h:1725-1807 ShardedOpWQ over ShardedThreadPool,
common/WorkQueue.h:619): ops shard by PG so one slow PG cannot head-of-line
block the rest, and within a shard an mClock scheduler (osd/mClock*,
dmclock submodule) arbitrates between op classes — client I/O, sub-ops,
recovery, scrub, snap-trim — by (reservation, weight, limit) tags.

This is that engine, reduced to its algorithmic core:

  * `ShardedOpQueue(n_shards, n_workers_per_shard)` — items enqueue by a
    shard key (the pgid), each shard owns an `MClockQueue` + worker
    thread(s); per-(shard, class) FIFO order is preserved, which with
    pg-keyed sharding gives the per-PG ordering the OSD requires.
  * `MClockQueue` — dmclock tag math: each class k has a reservation
    r_k (ops/s guaranteed), weight w_k (share of excess), limit l_k
    (ops/s cap, 0 = none).  Each enqueued op gets tags
        R_k = max(now, R_k_prev + 1/r_k)
        L_k = max(now, L_k_prev + 1/l_k)
        P_k = max(now, P_k_prev + 1/w_k)        (proportional tag)
    Dequeue picks the earliest R-tag that is ≤ now (reservation phase);
    otherwise the earliest P-tag among classes whose L-tag permits
    (weight phase); otherwise — every backlogged class limit-throttled —
    the earliest L-tag (work-conserving fallback: serve whoever's cap
    expires soonest rather than idle).

dmclock reference: the mClock paper's tag rules as embodied in the
reference's `osd_op_queue=mclock_*` options (common/options.cc).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field


@dataclass
class ClassInfo:
    """QoS parameters for one op class (dmclock ClientInfo analog)."""

    reservation: float = 0.0   # guaranteed ops/s (0 = none)
    weight: float = 1.0        # share of excess capacity
    limit: float = 0.0         # ops/s cap (0 = unlimited)


#: default op classes (osd_op_queue mclock profiles: client ops get
#: weight-dominant service, recovery/scrub/snaptrim run in the excess)
DEFAULT_CLASSES = {
    "client": ClassInfo(reservation=0.0, weight=100.0, limit=0.0),
    "subop": ClassInfo(reservation=0.0, weight=80.0, limit=0.0),
    "recovery": ClassInfo(reservation=10.0, weight=10.0, limit=0.0),
    "scrub": ClassInfo(reservation=0.0, weight=5.0, limit=100.0),
    "snaptrim": ClassInfo(reservation=0.0, weight=5.0, limit=100.0),
}


@dataclass
class _ClassState:
    info: ClassInfo
    q: deque = field(default_factory=deque)
    r_tag: float = 0.0
    p_tag: float = 0.0
    l_tag: float = 0.0


class MClockQueue:
    """Single-shard mClock scheduler over named op classes.

    Client ops may be tagged per client ("client.<id>" class names,
    mClockClientQueue analog): each client gets its own dmclock tag
    stream from the ``client_template`` (reservation/weight/limit), so
    one chatty client cannot starve the rest — the per-client
    reservations/limits the reference's dmclock client queue provides.
    Idle per-client classes are pruned so the table stays bounded."""

    #: idle per-client classes older than this are dropped
    CLIENT_IDLE_PRUNE = 60.0

    def __init__(self, classes: dict[str, ClassInfo] | None = None,
                 client_template: ClassInfo | None = None):
        self._classes: dict[str, _ClassState] = {}
        for name, info in (classes or DEFAULT_CLASSES).items():
            self._classes[name] = _ClassState(info=info)
        self.client_template = client_template
        self._client_last_seen: dict[str, float] = {}
        self._enq_count = 0
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def class_backlog(self, prefix: str) -> int:
        """Queued items across classes matching the prefix."""
        return sum(len(st.q) for n, st in self._classes.items()
                   if n == prefix or n.startswith(prefix + "."))

    def enqueue(self, klass: str, item, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        st = self._classes.get(klass)
        if st is None:
            if klass.startswith("client.") and self.client_template:
                info = ClassInfo(
                    reservation=self.client_template.reservation,
                    weight=self.client_template.weight,
                    limit=self.client_template.limit)
            else:
                info = ClassInfo()
            st = self._classes[klass] = _ClassState(info=info)
        if klass.startswith("client."):
            self._client_last_seen[klass] = now
            self._enq_count += 1
            if self._enq_count % 256 == 0:
                self._prune_clients(now)
        i = st.info
        if not st.q:
            # idle class: tags restart from now (dmclock idle reset);
            # weight 0 is treated as the minimum share, not a crash
            st.r_tag = now + (1.0 / i.reservation if i.reservation else 0.0)
            st.p_tag = now + 1.0 / max(i.weight, 1e-6)
            st.l_tag = now + (1.0 / i.limit if i.limit else 0.0)
        st.q.append(item)
        self._len += 1

    def _prune_clients(self, now: float) -> None:
        stale = [n for n, seen in self._client_last_seen.items()
                 if now - seen > self.CLIENT_IDLE_PRUNE
                 and not self._classes[n].q]
        for n in stale:
            del self._classes[n]
            del self._client_last_seen[n]

    def _advance(self, st: _ClassState, now: float) -> None:
        i = st.info
        if i.reservation:
            st.r_tag = max(now, st.r_tag + 1.0 / i.reservation)
        if i.limit:
            st.l_tag = max(now, st.l_tag + 1.0 / i.limit)
        st.p_tag = max(now, st.p_tag + 1.0 / max(i.weight, 1e-6))

    def dequeue(self, now: float | None = None):
        """Return (class, item) or None if empty."""
        now = time.monotonic() if now is None else now
        backlogged = [(n, st) for n, st in self._classes.items() if st.q]
        if not backlogged:
            return None
        # phase 1: honor reservations that are due
        due = [(st.r_tag, n, st) for n, st in backlogged
               if st.info.reservation and st.r_tag <= now]
        if due:
            _tag, name, st = min(due)
            self._advance(st, now)
            self._len -= 1
            return name, st.q.popleft()
        # phase 2: weight-proportional among classes under their limit
        ok = [(st.p_tag, n, st) for n, st in backlogged
              if not st.info.limit or st.l_tag <= now]
        if ok:
            _tag, name, st = min(ok)
            self._advance(st, now)
            self._len -= 1
            return name, st.q.popleft()
        # phase 3: everything limited — work-conserving: earliest limit tag
        _tag, name, st = min((st.l_tag, n, st) for n, st in backlogged)
        self._advance(st, now)
        self._len -= 1
        return name, st.q.popleft()


class ShardedOpQueue:
    """N independent mClock shards, each drained by worker thread(s).

    Items shard by key (hash(pgid) % n_shards) so per-PG order is kept
    and one stuck PG only wedges its shard (ShardedOpWQ semantics).
    """

    #: tagged clients together may queue up to this many times the
    #: per-client cap before the shard refuses all client intake
    CLIENT_AGGREGATE_FACTOR = 16

    def __init__(self, handler, n_shards: int = 2,
                 n_workers_per_shard: int = 1,
                 classes: dict[str, ClassInfo] | None = None,
                 name: str = "osd",
                 client_template: ClassInfo | None = None,
                 max_client_backlog: int = 0):
        self._handler = handler
        self._n = max(1, n_shards)
        self._shards = []
        self._stop = False
        #: client-intake cap per shard (0 = unbounded): enqueue of a
        #: "client" / "client.N" op BLOCKS while the shard's client
        #: backlog is at the cap — dispatch-side backpressure, while
        #: peer/recovery classes always flow (the reference gates client
        #: intake with throttles end-to-end; sub-ops must not deadlock)
        self.max_client_backlog = max_client_backlog
        self._threads: list[threading.Thread] = []
        for s in range(self._n):
            q = MClockQueue(classes, client_template=client_template)
            # analysis: allow[bare-lock] -- per-shard parking condition: waiters hold no other lock; one node per shard would still merge by name
            cv = threading.Condition()
            self._shards.append((q, cv))
            for w in range(max(1, n_workers_per_shard)):
                t = threading.Thread(
                    target=self._worker, args=(q, cv),
                    name=f"{name}-opwq-{s}.{w}", daemon=True)
                t.start()
                self._threads.append(t)

    def enqueue(self, shard_key, klass: str, item) -> bool:
        """Queue an item; returns False when a CLIENT op is refused at
        the per-shard backlog cap.  Refusal (not blocking) is the
        backpressure mechanism: the caller runs on the daemon's single
        messenger dispatch thread, and blocking it on one wedged shard
        would gate heartbeats, sub-ops and map updates for every healthy
        PG.  A refused client op gets no reply; the client's timeout
        resend retries it (and dedups against the log if it already
        landed) — the reference's front-door throttles achieve the same
        per-client pushback via per-connection reader blocking, which a
        shared dispatch thread cannot afford."""
        q, cv = self._shards[hash(shard_key) % self._n]
        with cv:
            if (self.max_client_backlog
                    and (klass == "client" or klass.startswith("client."))):
                # with per-client tagging the cap is PER CLIENT class:
                # one chatty client hitting its cap must not refuse every
                # other client's intake (that would re-create exactly the
                # head-of-line blocking the per-client dmclock tags
                # remove); untagged "client" ops keep the aggregate cap.
                # A larger aggregate ceiling still bounds total shard
                # memory — without it N distinct client ids could queue
                # N x cap items between them
                if (klass.startswith("client.")
                        and q.class_backlog(klass)
                        >= self.max_client_backlog):
                    return False
                total_cap = (self.max_client_backlog
                             if klass == "client"
                             else self.max_client_backlog
                             * self.CLIENT_AGGREGATE_FACTOR)
                if q.class_backlog("client") >= total_cap:
                    return False
            q.enqueue(klass, item)
            cv.notify()
        return True

    def shutdown(self) -> None:
        self._stop = True
        for _q, cv in self._shards:
            with cv:
                cv.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)

    def _worker(self, q: MClockQueue, cv: threading.Condition) -> None:
        while True:
            with cv:
                while not self._stop and len(q) == 0:
                    cv.wait(timeout=0.1)
                if self._stop:
                    return
                got = q.dequeue()
            if got is None:
                continue
            klass, item = got
            try:
                self._handler(klass, item)
            except Exception:
                from ceph_tpu.common.logging import get_logger
                get_logger("osd").exception("opwq handler failed (%s)",
                                            klass)
