"""Regression tests for the round-4 advisor findings: the mksnap COW
race (a WR-caps holder writing right after mksnap must not overwrite the
head in place), rmsnap swallowing non-ENOENT mon errors, the Swift
TempAuth token secret being derived from a heap address, empty bucket
owners granting ownership to every authenticated principal, and
ListObjectVersions dropping entries when the pagination marker row was
deleted between pages."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.cephfs import CephFS
from ceph_tpu.rgw_rest import S3Error, S3Gateway
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    meta = c.create_pool(client, pg_num=4, size=2)
    data = c.create_pool(client, pg_num=8, size=2)
    c.run_mds(meta, data)
    c._fs_pools = (meta, data)
    yield c
    c.stop()


@pytest.fixture
def fs(cluster):
    f = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    f.mount()
    yield f
    f.unmount()


# -- mksnap COW race --------------------------------------------------------

def test_write_through_open_handle_after_mksnap_preserves_snapshot(fs):
    """The medium finding: a client holding WR/BUFFER caps across mksnap
    writes right after it.  mksnap's freeze must recall WR from EVERY
    holder, and the re-acquisition round-trip must hand the writer the
    post-snapshot epoch barrier — so the post-snap write COWs the head
    instead of silently corrupting the snapshot."""
    gen1 = b"generation one"
    gen2 = b" THEN generation two"
    fs.mkdir("/cowrace")
    f = fs.open("/cowrace/f.txt", "w")
    f.write(gen1)
    # handle stays OPEN across the snapshot
    fs.mksnap("/cowrace", "s1")
    # the freeze stripped WR|BUFFER from this holder: the next write has
    # to re-acquire caps (cap_want) and honor the epoch barrier
    f.write(gen2)
    f.close()
    with fs.open("/cowrace/.snap/s1/f.txt") as snap:
        assert snap.read() == gen1
    with fs.open("/cowrace/f.txt") as live:
        assert live.read() == gen1 + gen2


def test_osd_clones_on_op_snapc_ahead_of_its_map(cluster):
    """A writer whose osdmap already carries a pool snapshot must get
    copy-on-write even from an OSD whose own map does not yet: the op's
    SnapContext stamp (MOSDOp.write_snapc) wins over the server map."""
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=4, size=2)
    io = client.open_ioctx(pool)
    io.write_full("racer", b"pre-snapshot state")
    # simulate "client learned of snap 1 before the OSDs": bump ONLY the
    # client's view of the pool snap_seq
    client.osdmap.pools[pool].snap_seq = 1
    for osd in cluster.osds.values():
        assert osd.osdmap.pools[pool].snap_seq == 0
    io.write_full("racer", b"post-snapshot state")
    # the pre-write state must have been cloned at seq 1
    assert io.read("racer", 64, snapid=1) == b"pre-snapshot state"
    assert io.read("racer", 64) == b"post-snapshot state"


# -- rmsnap error propagation ----------------------------------------------

def test_rmsnap_mon_failure_keeps_snap_record(cluster, fs):
    fs.mkdir("/rmfail")
    with fs.open("/rmfail/a.txt", "w") as f:
        f.write(b"snapped")
    fs.mksnap("/rmfail", "keepme")
    mds = cluster.mds
    real = mds.objecter.mon_command
    calls = {"n": 0}

    def flaky(cmd):
        if cmd.get("prefix") == "osd pool rmsnap" and calls["n"] == 0:
            calls["n"] += 1
            return -110, b""    # ETIMEDOUT
        return real(cmd)

    mds.objecter.mon_command = flaky
    try:
        with pytest.raises(OSError):
            fs.rmsnap("/rmfail", "keepme")
        # the record that names the pool snapshot must survive the
        # failure (else the snap + clones leak unreferenced)
        assert "keepme" in fs.listsnaps("/rmfail")
        with fs.open("/rmfail/.snap/keepme/a.txt") as f:
            assert f.read() == b"snapped"
        # and the retry succeeds
        fs.rmsnap("/rmfail", "keepme")
        assert "keepme" not in fs.listsnaps("/rmfail")
    finally:
        mds.objecter.mon_command = real


# -- swift token secret ----------------------------------------------------

def test_swift_token_secret_is_random():
    from ceph_tpu.rgw_swift import SwiftRestServer

    a = SwiftRestServer(gateway=S3Gateway.__new__(S3Gateway))
    b = SwiftRestServer(gateway=S3Gateway.__new__(S3Gateway))
    try:
        assert len(a._token_secret) == 32
        assert a._token_secret != b._token_secret
    finally:
        a._frontend.stop()     # closes listener, selector, wake pipe
        b._frontend.stop()


# -- empty bucket owner ----------------------------------------------------

def test_empty_bucket_owner_matches_nobody(cluster):
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=4, size=2)
    gw = S3Gateway(client.open_ioctx(pool))
    gw.create_bucket("unowned", owner="")
    # an authenticated principal is NOT the owner of an ownerless bucket
    with pytest.raises(S3Error):
        gw.authorize("unowned", "mallory", write=True)
    with pytest.raises(S3Error):
        gw.authorize_owner("unowned", "mallory")
    # private + ownerless: reads denied too
    with pytest.raises(S3Error):
        gw.authorize("unowned", "mallory", write=False)
    # a real owner still passes
    gw.create_bucket("owned", owner="alice")
    gw.authorize("owned", "alice", write=True)
    gw.authorize_owner("owned", "alice")


def test_sync_never_creates_ownerless_bucket(cluster):
    from ceph_tpu.rgw_sync import ZoneSyncAgent

    client = cluster.client(timeout=20.0)
    p1 = cluster.create_pool(client, pg_num=4, size=2)
    p2 = cluster.create_pool(client, pg_num=4, size=2)
    src = S3Gateway(client.open_ioctx(p1))
    dst = S3Gateway(client.open_ioctx(p2))
    agent = ZoneSyncAgent(src, dst)
    src.create_bucket("b1", owner="alice")
    # meta read failure must PROPAGATE, not create an ownerless bucket
    real = src._bucket

    def broken(name, must_exist=True):
        raise S3Error("InternalError", "transient")

    src._bucket = broken
    try:
        with pytest.raises(S3Error):
            agent._ensure_bucket("b1")
    finally:
        src._bucket = real
    with pytest.raises(S3Error):
        dst._bucket("b1")
    # healthy path replicates the owner
    agent._ensure_bucket("b1")
    assert dst._bucket("b1").meta_all().get("owner") == "alice"
    # repair path: a pre-existing destination bucket stranded with an
    # empty owner (replicated under the old code) gets backfilled
    dst._bucket("b1").set_meta("owner", "")
    agent._ensure_bucket("b1")
    assert dst._bucket("b1").meta_all().get("owner") == "alice"


# -- ListObjectVersions marker deletion ------------------------------------

def test_list_versions_survives_deleted_marker_row(cluster):
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=4, size=2)
    gw = S3Gateway(client.open_ioctx(pool))
    gw.create_bucket("pager", owner="alice")
    gw.set_versioning("pager", "Enabled")
    vids = []
    for i in range(3):
        _etag, vid = gw.put_object("pager", "key-a", f"v{i}".encode(), {})
        vids.append(vid)
        time.sleep(0.002)   # distinct time_ns ids / mtimes
    gw.put_object("pager", "key-b", b"other", {})
    page1, truncated = gw.list_versions("pager", "", 1)
    assert truncated and len(page1) == 1
    marker_key, marker_entry, _ = page1[0]
    marker_vid = marker_entry["version_id"]
    assert marker_key == "key-a" and marker_vid == vids[2]
    # delete the marker row between pages
    gw.delete_object("pager", "key-a", vid=marker_vid)
    rest, _ = gw.list_versions("pager", "", 100,
                               key_marker=marker_key,
                               vid_marker=marker_vid)
    keys = [(k, e["version_id"]) for k, e, _l in rest]
    # the surviving older versions of the marker key must still list
    assert ("key-a", vids[0]) in keys
    assert ("key-a", vids[1]) in keys
    assert any(k == "key-b" for k, _v in keys)
