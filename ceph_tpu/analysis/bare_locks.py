"""Bare-lock lint (check family ``bare-lock``).

Every ``threading.Lock()``/``RLock()``/``Condition()`` constructed
outside ``common/lockdep.py``'s ``make_lock``/``make_condition``
factories is invisible to runtime lock-order checking — the exact gap
this PR closes on the dispatch/decode/mapping hot paths.  New code
must name its locks; the few justified bare locks (import-time module
locks created before lockdep can be enabled, per-instance leaf locks
with measured overhead concerns) carry inline suppressions.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis import Finding
from ceph_tpu.analysis.core import TreeIndex, name_chain

_CTORS = {"Lock", "RLock", "Condition"}


def check(index: TreeIndex):
    findings = []
    for relpath, mod in sorted(index.by_path.items()):
        if mod.modname.endswith("common.lockdep"):
            continue        # the factory itself
        threading_aliases = {a for a, imp in mod.imports.items()
                             if imp == ("module", "threading")}
        from_imports = {a for a, imp in mod.imports.items()
                        if imp[0] == "symbol" and imp[1] == "threading"
                        and imp[2] in _CTORS}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func)
            if not chain:
                continue
            hit = None
            if (len(chain) == 2 and chain[0] in threading_aliases
                    and chain[1] in _CTORS):
                hit = chain[1]
            elif len(chain) == 1 and chain[0] in from_imports:
                hit = chain[0]
            if hit:
                findings.append(Finding(
                    "bare-lock", relpath, node.lineno, hit.lower(),
                    f"bare threading.{hit}() — invisible to lockdep; "
                    f"use lockdep.make_lock(name)"
                    + ("/make_condition(name)" if hit == "Condition"
                       else "")))
    return findings
