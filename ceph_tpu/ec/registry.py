"""Erasure-code plugin registry.

The reference loads plugins with dlopen and a version handshake
(ErasureCodePluginRegistry, src/erasure-code/ErasureCodePlugin.cc:126-184) and
preloads `osd_erasure_code_plugins` at daemon start (global_init.cc:558).  Here
plugins are Python classes registered by name; ``factory`` validates the profile
the same way the reference's factory() re-checks the returned profile
(ErasureCodePlugin.cc:92-120).  Thread-safe like the reference's singleton.
"""

from __future__ import annotations

import threading

from .interface import ErasureCodeInterface, ErasureCodeProfile


class ErasureCodePlugin:
    """Plugin shim: knows how to construct a codec for a profile."""

    def __init__(self, name: str, codec_factory):
        self.name = name
        self._codec_factory = codec_factory

    def factory(self, profile: ErasureCodeProfile) -> ErasureCodeInterface:
        codec = self._codec_factory(profile)
        codec.init(profile)
        return codec


class ErasureCodePluginRegistry:
    """Singleton name -> plugin map (ErasureCodePlugin.h:45-79)."""

    _instance: "ErasureCodePluginRegistry | None" = None
    # analysis: allow[bare-lock] -- plugin registry singleton guard; startup only
    _instance_lock = threading.Lock()

    def __init__(self):
        # analysis: allow[bare-lock] -- plugin instance-cache leaf lock
        self._lock = threading.Lock()
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self.disable_dlclose = True  # vestigial reference knob, kept for parity

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise ValueError(f"plugin {name!r} already registered")
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        with self._lock:
            return self._plugins.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._plugins)

    def factory(self, name: str, profile: ErasureCodeProfile,
                ) -> ErasureCodeInterface:
        """Build + init a codec; KeyError for unknown plugins (the reference
        returns -ENOENT after a failed dlopen)."""
        plugin = self.get(name)
        if plugin is None:
            raise KeyError(
                f"erasure-code plugin {name!r} not found; "
                f"known: {self.names()}")
        return plugin.factory(profile)


def instance() -> ErasureCodePluginRegistry:
    return ErasureCodePluginRegistry.instance()


def register(name: str, codec_factory) -> None:
    """Module-level convenience used by plugin modules at import time (the
    analog of __erasure_code_init)."""
    instance().add(name, ErasureCodePlugin(name, codec_factory))
