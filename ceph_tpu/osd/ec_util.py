"""EC stripe math + per-shard integrity (src/osd/ECUtil.{h,cc} analog).

StripeInfo is stripe_info_t: a fixed stripe_unit (bytes per shard per
stripe) makes an EC object a sequence of stripes of width k*su; shard s
holds column s of every stripe.  Partial writes become stripe-aligned
read-modify-write, and the affected stripes encode in ONE batched device
call — the per-stripe loop of ECUtil::encode (osd/ECUtil.cc:136) is the
batch axis.

HashInfo (osd/ECUtil.cc:161-177) keeps a checksum over each shard
object; a mismatch on read marks the shard failed so the gather ladder
reconstructs from the others and the primary repairs the bad copy.  The
reference uses hardware crc32c (Castagnoli); here the C-speed zlib
crc32 stands in — the polynomial is an implementation detail of the
integrity attr (it never crosses wire-compat boundaries), the
detection semantics are identical.
"""

from __future__ import annotations

import zlib

import numpy as np


def shard_crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


class StripeInfo:
    """stripe_info_t: geometry of a striped EC object."""

    def __init__(self, k: int, stripe_unit: int):
        self.k = k
        self.su = stripe_unit
        self.width = k * stripe_unit

    def object_stripes(self, size: int) -> int:
        return max(1, -(-size // self.width))

    def shard_len(self, size: int) -> int:
        return self.object_stripes(size) * self.su

    def stripe_range(self, offset: int, length: int) -> tuple[int, int]:
        """[first, last) stripes touched by a byte range."""
        if length <= 0:
            return (0, 0)
        return (offset // self.width,
                -(-(offset + length) // self.width))

    def split(self, data: np.ndarray) -> np.ndarray:
        """Whole-object bytes (padded) -> (stripes, k, su)."""
        n = self.object_stripes(len(data))
        padded = np.zeros(n * self.width, dtype=np.uint8)
        padded[:len(data)] = data
        return padded.reshape(n, self.k, self.su)

    def join(self, stripes: np.ndarray) -> np.ndarray:
        """(stripes, k, su) -> flat object bytes (padded length)."""
        return stripes.reshape(-1)

    def shard_column(self, stripes: np.ndarray, s: int) -> np.ndarray:
        """shard s's bytes across the given stripes: (n, su) -> flat."""
        return np.ascontiguousarray(stripes[:, s, :]).reshape(-1)


class HashInfo:
    """Per-shard checksum (attr blob "hinfo")."""

    @staticmethod
    def compute(shard_bytes: bytes) -> bytes:
        return shard_crc(shard_bytes).to_bytes(4, "little")

    @staticmethod
    def matches(shard_bytes: bytes, blob: bytes | None) -> bool:
        if not blob:
            return True   # legacy object without a hash: trust it
        return HashInfo.compute(shard_bytes) == blob
