"""Profile the CRUSH fast path components on TPU at the bench shape."""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from bench import chained_rates, median_band
from ceph_tpu.crush import build_two_level_map
from ceph_tpu.crush.mapper_jax import BatchMapper


def main():
    crush_map, _root, rid = build_two_level_map(250, 40)
    wrng = np.random.default_rng(42)
    for b in crush_map.buckets:
        if b is not None and b.type == 1:
            b.item_weights = [int(w) for w in
                              wrng.integers(0x8000, 0x20000, b.size)]
            b.weight = sum(b.item_weights)
    root = crush_map.bucket(-1)
    root.item_weights = [crush_map.bucket(h).weight for h in root.items]
    root.weight = sum(root.item_weights)

    n_osds = 10000
    reweight = np.full(n_osds, 0x10000, dtype=np.int64)
    idx = wrng.permutation(n_osds)
    reweight[idx[:1000]] = 0x8000
    reweight[idx[1000:1200]] = 0

    bm = BatchMapper(crush_map)
    n_pgs, numrep = 65536, 3
    rw = jnp.asarray(reweight)
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.integers(0, 2**32, (n_pgs,), dtype=np.uint32))

    fast = bm._fastpath(rid)
    fm = fast
    R0 = numrep + 6  # DEFAULT_BLOCK

    pc = fm._pallas

    def t_of(step, carry, n_lo=2, n_hi=8):
        med, lo, hi = median_band(chained_rates(step, carry, n_lo, n_hi, reps=5))
        return med

    # root columns only
    def root_step(x):
        pos, ids = pc.root_columns(x, rw, R0)
        return x ^ ids[0].astype(jnp.uint32)

    jax.block_until_ready(root_step(xs))
    t_root = t_of(root_step, xs)
    print(f"root_columns R={R0}: {t_root*1e3:8.2f} ms  ({n_pgs/t_root/1e6:.3f} Mpps-equiv)")

    # root + leaf
    def rl_step(x):
        pos, ids = pc.root_columns(x, rw, R0)
        lid = pc.leaf_columns(x, pos, R0)
        return x ^ lid[0].astype(jnp.uint32)

    jax.block_until_ready(rl_step(xs))
    t_rl = t_of(rl_step, xs)
    print(f"root+leaf:          {t_rl*1e3:8.2f} ms  ({n_pgs/t_rl/1e6:.3f} Mpps-equiv)")

    # full run (winners + consume + compact)
    def full_step(x):
        p = fm.run(x, rw, numrep)
        return x ^ p[:, 0].astype(jnp.uint32)

    jax.block_until_ready(full_step(xs))
    t_full = t_of(full_step, xs)
    print(f"full run:           {t_full*1e3:8.2f} ms  ({n_pgs/t_full/1e6:.3f} Mpps)")


if __name__ == "__main__":
    main()
