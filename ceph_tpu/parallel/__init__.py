"""Multi-chip parallelism for ceph_tpu.

The reference scales with a cluster messenger fanning shard writes to k+m OSDs
(src/osd/ECBackend.cc:2033) and a thread pool for bulk remaps
(src/osd/OSDMapMapping.h:17).  The TPU-native equivalents are mesh axes:

    dp   placement/stripe data parallelism — independent PGs/stripes spread
         across devices (the ParallelPGMapper / ECUtil stripe-loop axis).
    ec   shard parallelism — the k+m chunk fan-out of an EC write lives across
         devices, and recovery's shard fan-in (MOSDECSubOpRead) becomes an
         all_gather over this axis riding ICI.

See SURVEY.md §2.3 / §5 for the messenger→collectives mapping.
"""

from .mesh import make_mesh, factor_devices
from .sharded import sharded_encode, make_cluster_step

__all__ = ["make_mesh", "factor_devices", "sharded_encode", "make_cluster_step"]
