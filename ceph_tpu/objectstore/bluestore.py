"""BlueStore-lite — a disk-backed object store in the BlueStore shape
(src/os/bluestore/: raw block device + RocksDB metadata).

Architecture mirrors the reference's split:

  block file       object DATA lives in fixed-size extents of one flat
                   file ("the raw device"), handed out by a bitmap
                   allocator (BitmapAllocator analog) and returned on
                   delete/overwrite — data is NOT resident in RAM,
                   every read hits the block file.
  KV (LogDB)       all METADATA — per-object extent maps, sizes, attrs,
                   omap, collection membership — in the append-only KV
                   store standing in for RocksDB, giving atomic
                   transaction commits and replay-on-mount for free.

Crash consistency is BlueStore's: block-content updates are
COPY-ON-WRITE (a patched block lands in a freshly allocated extent;
the object's extent map flips to it only inside the KV commit), data
is fsync'd before the ONE KV transaction that references it, and the
displaced blocks return to the allocator only after that commit
succeeds.  A crash anywhere leaves the old metadata pointing at
untouched old blocks.  The allocator itself is never trusted from a
snapshot: mount rebuilds the free list from the committed extent maps
(BlueStore fsck/allocation-recovery analog), so a hard kill can never
resurrect in-use blocks as free.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import threading
import zlib

_WAL_HDR = struct.Struct("<II")   # block index, intra-block offset

from .kv import LogDB
from .objectstore import ObjectStore
from .transaction import (
    OP_CLONE, OP_COLL_MOVE, OP_MKCOLL, OP_OMAP_RMKEYS, OP_OMAP_SETKEYS,
    OP_REMOVE, OP_RMCOLL, OP_SETATTR, OP_TOUCH, OP_TRUNCATE, OP_WRITE,
    OP_ZERO,
    Transaction)

BLOCK = 4096          # allocation unit ("min_alloc_size")

#: deferred-write entries per object before they fold into blocks
#: (bluestore_prefer_deferred_size-style knob, entry-count flavored)
WAL_MAX = 16


class BitmapAllocator:
    """Free-extent tracking over the block file
    (os/bluestore/BitmapAllocator analog, block granularity)."""

    def __init__(self):
        self._free: set[int] = set()
        self._next = 0
        # analysis: allow[bare-lock] -- allocator free-set leaf lock (BlueStore::lock itself is named)
        self._lock = threading.Lock()

    def allocate(self, n_blocks: int) -> list[int]:
        with self._lock:
            out = []
            while self._free and len(out) < n_blocks:
                out.append(self._free.pop())
            while len(out) < n_blocks:
                out.append(self._next)
                self._next += 1
            return sorted(out)

    def release(self, blocks: list[int]) -> None:
        with self._lock:
            self._free.update(blocks)

    def restore(self, next_block: int, free: list[int]) -> None:
        with self._lock:
            self._next = next_block
            self._free = set(free)


def _okey(cid: str, oid: str) -> str:
    return f"{cid}\x00{oid}"


#: compression_mode values that compress (the reference's "passive"
#: compresses only on client hints, which this stack does not carry)
_COMP_MODES_ON = ("aggressive", "force")


class BlueStoreLite(ObjectStore):
    """ObjectStore on a block file + KV metadata.

    With a context, write-time block checksums batch into the
    ``bluestore_data`` dispatch channel (one coalesced device digest
    call per transaction batch, coalescing further across concurrent
    txcs/stores at the engine), reads above a threshold verify through
    the same channel, and per-pool/global ``compression_mode`` runs
    blocks through a compressor plugin before they hit the block file.
    Without one (or with the knobs off) every path is the seed's
    scalar ``zlib.crc32`` loop — which also remains the bit-exact
    oracle the channel's fault ladder falls back to."""

    def __init__(self, path: str, ctx=None):
        if not path:
            raise ValueError("bluestore needs a directory path")
        self.path = path
        self._ctx = ctx
        self._block_path = os.path.join(path, "block")
        self._db = LogDB(os.path.join(path, "kv"))
        self._alloc = BitmapAllocator()
        self._f = None
        # store-level perf set (l_bluestore_* analog); the owning daemon
        # registers it into its context's collection
        from ceph_tpu.common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("bluestore")
                     .add_u64("txc")
                     .add_time_avg("commit_lat")
                     .add_time_avg("apply_lat")
                     .add_u64("csum_batches")
                     .add_u64("csum_blocks")
                     .add_u64("csum_scalar_blocks")
                     .add_u64("csum_fallbacks")
                     .add_u64("read_verify_batches")
                     .add_u64("read_verify_blocks")
                     .add_u64("compress_blocks")
                     .add_u64("compress_rejected")
                     .add_u64("compress_roundtrip_failures")
                     .add_u64("kv_journal_truncated")
                     .create_perf_counters())
        from ceph_tpu.common.lockdep import make_lock
        self._lock = make_lock(f"BlueStore::lock({path})")
        #: blocks displaced by the in-flight transaction batch; returned
        #: to the allocator only after its KV commit lands
        self._freed: list[int] = []
        #: freshly allocated block -> STORED payload whose crc32 the
        #: in-flight batch still owes; ONE coalesced device call at
        #: commit fills them (scalar zlib on any failure — a csum is
        #: never committed unset)
        self._pending_csum: dict[int, bytes] = {}
        #: engine the in-flight batch rides (None = scalar batch)
        self._batch_eng = None
        #: cid -> resolved compression policy, cached per batch so the
        #: hot per-block path reads the conf once per collection
        self._comp_cache: dict[str, tuple | None] = {}
        #: pool id -> (compression_mode, compression_algorithm) pushed
        #: from the osdmap's per-pool fields (set_pool_compression)
        self._pool_comp: dict[int, tuple[str, str]] = {}
        #: algorithm -> plugin instance (compressor.create is registry-
        #: locked; the write path must not take that lock per block)
        self._compressors: dict[str, object] = {}
        #: whether the in-flight batch wrote any block (a pure deferred-
        #: write batch skips the block-file fsync entirely — the whole
        #: point of the WAL path: one KV commit, no data syncs)
        self._block_dirty = False
        #: deferred-write entries of the in-flight batch, per object key:
        #: committed as individual "wal" column keys alongside the meta
        #: (RocksDB deferred-write keys in the reference) — NOT inlined
        #: into the meta blob, which would make every commit rewrite the
        #: accumulated patch bytes
        self._wal_pending: dict[str, list] = {}
        self._wal_rms: list[str] = []
        #: okey -> sorted committed wal keys (avoids a store-wide column
        #: scan per read of a WAL-bearing object); rebuilt at mount,
        #: maintained at commit
        self._wal_index: dict[str, list[str]] = {}
        #: store-global WAL key sequence: per-meta counters reset when
        #: an object is removed+recreated in one batch, and a reused key
        #: would collide with its own pending deletion inside the same
        #: KV transaction (sets apply before rms)
        self._wal_seq = 0

    # -- lifecycle ------------------------------------------------------------

    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        open(self._block_path, "wb").close()
        kv = os.path.join(self.path, "kv")
        if os.path.isdir(kv):
            shutil.rmtree(kv)
        elif os.path.exists(kv):
            os.unlink(kv)

    def mkfs_if_needed(self) -> None:
        if not os.path.exists(self._block_path):
            self.mkfs()

    def mount(self) -> None:
        self._db.open()
        # surface the KV journal's replay-truncation ledger: a chopped
        # journal means lost transactions, and it must be visible as a
        # counter (perf + the process-global sink), never just a log line
        tf = getattr(self._db, "truncated_frames", 0)
        if tf:
            from ceph_tpu.ops import telemetry
            self.perf.inc("kv_journal_truncated", tf)
            telemetry.bluestore_stats().inc("kv_journal_truncated", tf)
            telemetry.bluestore_stats().inc(
                "kv_journal_lost_bytes",
                getattr(self._db, "truncated_bytes", 0))
        self._f = open(self._block_path, "r+b")
        # rebuild the allocator from the committed extent maps — the
        # only crash-safe source of truth (fsck-style recovery; a
        # snapshot written at umount would be stale after a hard kill
        # and hand out live blocks)
        used: set[int] = set()
        for blob in self._db.get_range("obj").values():
            meta = json.loads(blob.decode())
            used.update(b for b in meta["extents"] if b >= 0)
        nxt = max(used) + 1 if used else 0
        self._alloc.restore(nxt, sorted(set(range(nxt)) - used))
        self._wal_index = {}
        self._wal_seq = 0
        for k in sorted(self._db.get_range("wal")):
            okey, _, seq = k.rpartition("\x00")
            self._wal_index.setdefault(okey, []).append(k)
            self._wal_seq = max(self._wal_seq, int(seq))

    def umount(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        self._db.close()

    # -- metadata helpers -----------------------------------------------------

    def _meta(self, cid: str, oid: str) -> dict | None:
        blob = self._db.get("obj", _okey(cid, oid))
        if blob is None:
            return None
        return json.loads(blob.decode())

    def _put_meta(self, kvt, cid: str, oid: str, meta: dict) -> None:
        kvt.set("obj", _okey(cid, oid), json.dumps(meta).encode())

    @staticmethod
    def _new_meta() -> dict:
        return {"size": 0, "extents": [], "attrs": {}, "omap": {},
                "csum": [], "comp": [], "wal_n": 0, "wal_seq": 0}

    # -- config / engine / compression plumbing -------------------------------

    def _conf(self, key: str, default):
        """A registered option off the owning context's conf, or the
        default for bare stores (tools, tests without a context)."""
        if self._ctx is None:
            return default
        try:
            return self._ctx.conf.get(key)
        except Exception:
            return default

    def _batch_engine(self):
        """The engine this batch's ``bluestore_data`` submissions ride
        — or None for the scalar path.  None when: no context, knob
        off, or the CALLER is an engine worker thread (store commits
        run on completion threads via EC-write and recovery
        continuations; blocking on a future there would starve the
        thread that delivers it).  The channel rides the decode engine
        so store digests coalesce with scrub's — one checksum
        definition, one width-bucketed batch stream."""
        if self._ctx is None or not bool(
                self._conf("bluestore_batched_csum", True)):
            return None
        try:
            eng = self._ctx.decode_dispatch_engine()
            enc = self._ctx.dispatch_engine()
        except Exception:
            return None
        if eng.owns_current_thread() or enc.owns_current_thread():
            return None
        return eng

    def set_pool_compression(self, pool_id: int, mode: str,
                             algorithm: str = "") -> None:
        """Per-pool compression override, pushed by the owning OSD
        when the osdmap's pool table changes (`osd pool set <p>
        compression_mode aggressive`); empty strings fall back to the
        ``bluestore_compression_*`` conf."""
        with self._lock:
            if mode or algorithm:
                self._pool_comp[int(pool_id)] = (str(mode),
                                                 str(algorithm))
            else:
                self._pool_comp.pop(int(pool_id), None)
            self._comp_cache.clear()

    def _comp_policy(self, okey: str | None):
        """(algorithm, required_ratio) when the block should try
        compression, else None — per-pool mode/algorithm first (cid
        prefix "pool.pg"), then the global conf; cached per cid for
        the batch."""
        if okey is None:
            return None
        cid = okey.split("\x00", 1)[0]
        if cid in self._comp_cache:
            return self._comp_cache[cid]
        mode = alg = ""
        head = cid.split(".", 1)[0]
        if head.lstrip("-").isdigit():
            mode, alg = self._pool_comp.get(int(head), ("", ""))
        if not mode:
            mode = str(self._conf("bluestore_compression_mode", "none"))
        pol = None
        if mode in _COMP_MODES_ON:
            if not alg:
                alg = str(self._conf("bluestore_compression_algorithm",
                                     "tpu_bitplane"))
            pol = (alg, float(self._conf(
                "bluestore_compression_required_ratio", 0.875)))
        self._comp_cache[cid] = pol
        return pol

    def _compressor(self, alg: str):
        c = self._compressors.get(alg)
        if c is None:
            from ceph_tpu import compressor as _comp
            c = _comp.create(alg)
            self._compressors[alg] = c
        return c

    def _compress_block(self, padded: bytes, policy):
        """(stored_bytes, comp_entry|None) for one logical block.
        Compression must never fail a write: any plugin error or a
        failed round-trip stores the block raw.  A compressed block
        commits ONLY after decompressing back byte-identical."""
        if policy is None:
            return padded, None
        alg, ratio = policy
        from ceph_tpu.ops import telemetry
        bs = telemetry.bluestore_stats()
        try:
            comp = self._compressor(alg).compress(padded)
        except Exception:
            bs.inc("compress_rejected")
            return padded, None
        if len(comp) > int(BLOCK * ratio):
            bs.inc("compress_rejected")
            return padded, None
        if bool(self._conf("bluestore_compression_verify", True)):
            try:
                ok = self._compressor(alg).decompress(comp) == padded
            except Exception:
                ok = False
            if not ok:
                bs.inc("compress_roundtrip_failures")
                return padded, None
        bs.inc("compress_blocks")
        self.perf.inc("compress_blocks")
        return comp, [alg, len(comp)]

    def _compress_blocks(self, blocks: list, policy) -> list:
        """Batch flavor of ``_compress_block`` for a multi-block
        write: plugins exposing ``compress_batch`` (tpu_bitplane) get
        ONE device call for the whole span; others fall back
        per-block.  Same ratio gate and round-trip verification per
        block."""
        alg, ratio = policy
        comp = self._compressor(alg)
        batch = getattr(comp, "compress_batch", None)
        if batch is None:
            return [self._compress_block(b, policy) for b in blocks]
        from ceph_tpu.ops import telemetry
        bs = telemetry.bluestore_stats()
        try:
            bodies = batch(list(blocks))
        except Exception:
            bs.inc("compress_rejected", len(blocks))
            return [(b, None) for b in blocks]
        verify = bool(self._conf("bluestore_compression_verify", True))
        out = []
        for b, body in zip(blocks, bodies):
            if len(body) > int(BLOCK * ratio):
                bs.inc("compress_rejected")
                out.append((b, None))
                continue
            if verify:
                try:
                    ok = comp.decompress(body) == b
                except Exception:
                    ok = False
                if not ok:
                    bs.inc("compress_roundtrip_failures")
                    out.append((b, None))
                    continue
            bs.inc("compress_blocks")
            self.perf.inc("compress_blocks")
            out.append((body, [alg, len(body)]))
        return out

    def _flush_pending_csums(self, cache) -> None:
        """Fill every csum slot the batch left pending with ONE
        coalesced device digest over the stored payloads — the
        ``bluestore_data`` channel, reusing the scrub digest kernel
        (crc32 column).  The engine coalesces this call with scrub
        digests and other stores' batches at equal width buckets.  Any
        channel failure (breaker open, timeout, device fault) drops to
        the scalar ``zlib.crc32`` oracle, so a csum slot is never
        committed unset.  Runs after apply, before the fsync/KV build,
        so the final metas carry real checksums."""
        if not self._pending_csum:
            return
        # blocks written then displaced within this same batch (COW
        # overwrite of a fresh block) owe nothing
        for b in self._freed:
            self._pending_csum.pop(b, None)
        pending, self._pending_csum = self._pending_csum, {}
        if not pending:
            return
        blocks = sorted(pending)
        blobs = [pending[b] for b in blocks]
        from ceph_tpu.ops import telemetry
        bs = telemetry.bluestore_stats()
        crc_map: dict[int, int] = {}
        eng = self._batch_eng
        if eng is not None and len(blobs) >= int(
                self._conf("bluestore_batched_csum_min", 4)):
            from ceph_tpu.ops.dispatch import submit_bluestore_data
            try:
                dig = submit_bluestore_data(
                    eng, blobs,
                    cost_tag=("_bluestore", "client")).result(
                    timeout=float(
                        self._conf("bluestore_data_timeout", 30.0)))
                crc_map = {b: int(dig[i, 0]) & 0xFFFFFFFF
                           for i, b in enumerate(blocks)}
                bs.inc("csum_batches")
                bs.inc("csum_blocks", len(blocks))
                self.perf.inc("csum_batches")
                self.perf.inc("csum_blocks", len(blocks))
            except Exception as e:
                from ceph_tpu.common.logging import dout
                dout("bluestore", 1,
                     "bluestore_data digest batch failed (%s); "
                     "scalar crc32 carries the batch", e)
                bs.inc("csum_fallbacks")
                crc_map = {}
        if not crc_map:
            crc_map = {b: zlib.crc32(pending[b]) for b in blocks}
            bs.inc("csum_scalar_blocks", len(blocks))
        # fill the slots: every pending block is a fresh, unique
        # allocation, so walking the batch cache's extent maps finds
        # each exactly once (clones may alias a crc to two slots —
        # both get the same stored-payload digest)
        for key, m in cache.items():
            if key[0] == "__coll__" or m is None:
                continue
            cs = self._csums(m)
            for bi, b in enumerate(m["extents"]):
                if b in crc_map and cs[bi] is None:
                    cs[bi] = crc_map[b]

    # -- block I/O ------------------------------------------------------------

    def _read_block(self, block: int) -> bytes:
        self._f.seek(block * BLOCK)
        data = self._f.read(BLOCK)
        return data + bytes(BLOCK - len(data))

    def _stored_read(self, block: int, crc, comp=None) -> bytes:
        """The STORED payload of a block — compressed body or raw
        padded block — verified against its crc32.  A block staged by
        the in-flight batch serves from memory (its crc is computed at
        the commit's coalesced flush)."""
        pend = self._pending_csum.get(block)
        if pend is not None:
            return pend
        data = self._read_block(block)
        stored = data[:comp[1]] if comp else data
        if crc is not None and zlib.crc32(stored) != crc:
            from ceph_tpu.ops import telemetry
            telemetry.bluestore_stats().inc("csum_errors")
            raise IOError(
                f"bluestore checksum mismatch on block {block}: "
                f"stored {crc:#x}, computed {zlib.crc32(stored):#x}")
        return stored

    def _decompress_stored(self, block: int, stored: bytes,
                           comp) -> bytes:
        """Stored payload -> logical BLOCK bytes.  Decompression
        failures surface as IOError (EIO), exactly like a checksum
        mismatch — the typed CompressionError never leaks to RADOS."""
        if not comp:
            return stored
        try:
            out = self._compressor(comp[0]).decompress(stored)
        except Exception as e:
            from ceph_tpu.ops import telemetry
            telemetry.bluestore_stats().inc("decompress_errors")
            raise IOError(
                f"bluestore decompression failed on block {block} "
                f"(alg {comp[0]}): {e}") from e
        if len(out) != BLOCK:
            from ceph_tpu.ops import telemetry
            telemetry.bluestore_stats().inc("decompress_errors")
            raise IOError(
                f"bluestore decompression length mismatch on block "
                f"{block}: {len(out)} != {BLOCK}")
        return out

    def _read_verified(self, block: int, crc, comp=None) -> bytes:
        """Read + verify a block against its stored crc32 and return
        its LOGICAL bytes (BlueStore verifies every blob checksum on
        read; None = legacy/no csum)."""
        return self._decompress_stored(
            block, self._stored_read(block, crc, comp), comp)

    @staticmethod
    def _csums(meta: dict) -> list:
        cs = meta.setdefault("csum", [])
        while len(cs) < len(meta["extents"]):
            cs.append(None)
        return cs

    @staticmethod
    def _comps(meta: dict) -> list:
        """Per-extent compression entries ([alg, stored_len] | None),
        parallel to csum; absent in pre-compression metas."""
        co = meta.setdefault("comp", [])
        while len(co) < len(meta["extents"]):
            co.append(None)
        return co

    def _stage_csum(self, nb: int, stored: bytes, cs: list,
                    bi: int) -> None:
        """Record a freshly written block's checksum obligation: into
        the batch's pending map when this batch rides the engine (one
        coalesced device call at commit), else the scalar crc32 the
        seed computed inline — which is also the flush's fallback, so
        a csum slot is never committed unset."""
        if self._batch_eng is not None:
            self._pending_csum[nb] = stored
            cs[bi] = None
        else:
            cs[bi] = zlib.crc32(stored)

    def _patch_block(self, meta: dict, bi: int, boff: int,
                     chunk: bytes, okey: str | None = None,
                     pre=None) -> None:
        """COW-patch one block, route it through the compression
        policy, and stage its checksum.  The extent map grows with
        holes as needed — a truncate-extended region has size >
        extents coverage, and deferred writes may land there.
        ``pre``: (stored, comp_entry) already produced by a batched
        compression pass for full-block writes."""
        while len(meta["extents"]) <= bi:
            meta["extents"].append(-1)
        cs = self._csums(meta)
        co = self._comps(meta)
        old_block = meta["extents"][bi]
        if boff == 0 and len(chunk) == BLOCK:
            patched = chunk
        elif old_block >= 0:
            old = self._read_verified(old_block, cs[bi], co[bi])
            patched = old[:boff] + chunk + old[boff + len(chunk):]
        else:
            patched = bytes(boff) + chunk
        padded = patched[:BLOCK].ljust(BLOCK, b"\x00")
        if pre is not None:
            stored, centry = pre
        else:
            stored, centry = self._compress_block(
                padded, self._comp_policy(okey))
        nb = self._alloc.allocate(1)[0]
        self._write_block(nb, stored, pad=centry is None)
        meta["extents"][bi] = nb
        co[bi] = centry
        self._stage_csum(nb, stored, cs, bi)
        if old_block >= 0:
            self._freed.append(old_block)

    def _wal_key(self, okey: str, seq: int) -> str:
        return f"{okey}\x00{seq:010d}"

    def _wal_entries(self, okey: str, meta: dict) -> list:
        """Deferred entries for one object, oldest first: committed KV
        keys plus this batch's pending ones."""
        if not meta.get("wal_n"):
            return []
        out = []
        # keys this batch already queued for deletion (a purge from an
        # overwrite/remove earlier in the SAME batch) are dead: a
        # recreated object at the same okey must not overlay them
        dead = set(self._wal_rms)
        for k in self._wal_index.get(okey, []):
            if k in dead:
                continue
            v = self._db.get("wal", k)
            if v is None:
                continue
            bi, boff = _WAL_HDR.unpack_from(v)
            out.append((k, bi, boff, v[_WAL_HDR.size:]))
        for seq, bi, boff, data in self._wal_pending.get(okey, []):
            out.append((None, bi, boff, data))
        return out

    def _purge_wal(self, okey: str, meta: dict | None) -> None:
        """Queue every WAL entry of an object (committed + pending) for
        deletion — overwriting or dropping a destination must not leave
        stale deferred bytes to overlay the new content.  _wal_index is
        NOT touched here: all index maintenance happens after the KV
        commit lands, so ANY pre-commit failure (a later op in the
        batch, the fsync, the KV submit itself) leaves committed
        deferred writes readable — nothing was deleted."""
        for k in self._wal_index.get(okey, []):
            self._wal_rms.append(k)
        self._wal_pending.pop(okey, None)
        if meta is not None:
            meta["wal_n"] = 0

    def _fold_wal(self, okey: str, meta: dict) -> None:
        """Apply deferred small-write entries to their blocks (the WAL
        drain, BlueStore's _deferred_submit).  Runs before any
        non-deferrable mutation so block-level operations always see
        folded content; the entry keys are deleted in the same commit
        that persists the patched extent map."""
        for key, bi, boff, data in self._wal_entries(okey, meta):
            self._patch_block(meta, bi, boff, data, okey=okey)
            if key is not None:
                self._wal_rms.append(key)
        self._wal_pending.pop(okey, None)
        meta["wal_n"] = 0

    def _write_block(self, block: int, data: bytes,
                     pad: bool = True) -> None:
        """Write a block's STORED payload.  ``pad=False`` (compressed
        payloads) writes only the stored bytes — the block's tail
        keeps whatever it held, and reads slice to the comp entry's
        stored length before verifying."""
        self._f.seek(block * BLOCK)
        self._f.write(data[:BLOCK].ljust(BLOCK, b"\x00") if pad
                      else data[:BLOCK])
        self._block_dirty = True

    def _batch_read_verify(self, meta: dict, offset: int, end: int,
                           cs: list, co: list) -> dict[int, bytes]:
        """Verify a wide read's block checksums in ONE device digest
        call (the same ``bluestore_data`` channel write commits use,
        cost-tagged as read work).  Returns {bi: logical bytes} for
        the blocks it verified; {} routes the read through the scalar
        per-block path — including on any engine failure, so reads
        never lose verification, only batching."""
        if not bool(self._conf("bluestore_batched_read_verify", True)):
            return {}
        bis = []
        for bi in range(offset // BLOCK, -(-end // BLOCK)):
            if (bi < len(meta["extents"]) and meta["extents"][bi] >= 0
                    and bi < len(cs) and cs[bi] is not None
                    and meta["extents"][bi] not in self._pending_csum):
                bis.append(bi)
        if len(bis) < int(self._conf("bluestore_batched_read_min", 8)):
            return {}
        eng = self._batch_engine()
        if eng is None:
            return {}
        stored = []
        for bi in bis:
            comp = co[bi] if bi < len(co) else None
            data = self._read_block(meta["extents"][bi])
            stored.append(data[:comp[1]] if comp else data)
        from ceph_tpu.ops import telemetry
        from ceph_tpu.ops.dispatch import submit_bluestore_data
        try:
            dig = submit_bluestore_data(
                eng, stored, cost_tag=("_bluestore", "read")).result(
                timeout=float(self._conf("bluestore_data_timeout",
                                         30.0)))
        except Exception:
            telemetry.bluestore_stats().inc("csum_fallbacks")
            return {}
        out = {}
        for i, bi in enumerate(bis):
            crc = int(dig[i, 0]) & 0xFFFFFFFF
            if crc != cs[bi]:
                telemetry.bluestore_stats().inc("csum_errors")
                raise IOError(
                    f"bluestore checksum mismatch on block "
                    f"{meta['extents'][bi]}: stored {cs[bi]:#x}, "
                    f"computed {crc:#x}")
            out[bi] = self._decompress_stored(
                meta["extents"][bi], stored[i],
                co[bi] if bi < len(co) else None)
        bs = telemetry.bluestore_stats()
        bs.inc("read_verify_batches")
        bs.inc("read_verify_blocks", len(bis))
        return out

    def _obj_read(self, okey: str, meta: dict, offset: int,
                  length: int) -> bytes:
        out = bytearray()
        end = min(offset + length, meta["size"])
        cs = meta.get("csum") or []
        co = meta.get("comp") or []
        verified = self._batch_read_verify(meta, offset, end, cs, co)
        pos = offset
        while pos < end:
            bi = pos // BLOCK
            boff = pos % BLOCK
            n = min(BLOCK - boff, end - pos)
            if bi < len(meta["extents"]) and meta["extents"][bi] >= 0:
                blk = verified.get(bi)
                if blk is None:
                    blk = self._read_verified(
                        meta["extents"][bi],
                        cs[bi] if bi < len(cs) else None,
                        co[bi] if bi < len(co) else None)
                out += blk[boff:boff + n]
            else:
                out += bytes(n)     # hole
            pos += n
        # overlay deferred writes (newer than the blocks, in WAL order;
        # WAL bytes are covered by the KV log's own crc framing)
        for _key, wbi, wboff, wdata in self._wal_entries(okey, meta):
            wstart = wbi * BLOCK + wboff
            lo = max(wstart, offset)
            hi = min(wstart + len(wdata), end)
            if lo < hi:
                out[lo - offset:hi - offset] = \
                    wdata[lo - wstart:hi - wstart]
        return bytes(out)

    def _obj_write(self, okey: str, meta: dict, offset: int,
                   data: bytes) -> None:
        end = offset + len(data)
        # deferred small write (BlueStore deferred/WAL path): a strictly
        # partial single-block overwrite inside the current size lands
        # as a KV-journaled patch — no block read, no block write, no
        # data fsync on the commit path; reads overlay it and it folds
        # into the block once the entry count tops WAL_MAX
        if (0 < len(data) < BLOCK and end <= meta["size"]
                and offset // BLOCK == (end - 1) // BLOCK):
            self._wal_seq += 1
            self._wal_pending.setdefault(okey, []).append(
                (self._wal_seq, offset // BLOCK, offset % BLOCK,
                 bytes(data)))
            meta["wal_n"] = meta.get("wal_n", 0) + 1
            if meta["wal_n"] > WAL_MAX:
                self._fold_wal(okey, meta)
            return
        self._fold_wal(okey, meta)
        need_blocks = -(-max(end, meta["size"]) // BLOCK)
        while len(meta["extents"]) < need_blocks:
            meta["extents"].append(-1)
        # pre-compress the write's aligned full blocks in ONE batched
        # plugin call (tpu_bitplane: one device plane-extraction for
        # the whole span instead of one per block)
        pres: dict[int, tuple] = {}
        policy = self._comp_policy(okey)
        if policy is not None:
            first = -(-offset // BLOCK) * BLOCK
            full = [(pos // BLOCK, data[pos - offset:pos - offset + BLOCK])
                    for pos in range(first, end - BLOCK + 1, BLOCK)]
            if len(full) > 1:
                pres = dict(zip(
                    (bi for bi, _ in full),
                    self._compress_blocks([c for _, c in full],
                                          policy)))
        pos = offset
        di = 0
        while pos < end:
            bi = pos // BLOCK
            boff = pos % BLOCK
            n = min(BLOCK - boff, end - pos)
            # COW via the checksum-maintaining patcher: the old extent
            # stays valid until the KV commit flips the map
            self._patch_block(meta, bi, boff, data[di:di + n],
                              okey=okey, pre=pres.get(bi))
            pos += n
            di += n
        meta["size"] = max(meta["size"], end)

    def _obj_zero(self, okey: str, meta: dict, offset: int,
                  length: int) -> None:
        """Punch holes instead of writing zeros: full blocks drop to
        extent -1 (reads synthesize zeros), edges COW-patch."""
        self._fold_wal(okey, meta)
        cs = self._csums(meta)
        co = self._comps(meta)
        end = offset + length
        pos = offset
        while pos < end:
            bi = pos // BLOCK
            boff = pos % BLOCK
            n = min(BLOCK - boff, end - pos)
            if bi < len(meta["extents"]) and meta["extents"][bi] >= 0:
                if boff == 0 and n == BLOCK:
                    self._freed.append(meta["extents"][bi])
                    meta["extents"][bi] = -1
                    cs[bi] = None
                    co[bi] = None
                else:
                    self._patch_block(meta, bi, boff, bytes(n),
                                      okey=okey)
            pos += n
        if end > meta["size"]:
            while len(meta["extents"]) < -(-end // BLOCK):
                meta["extents"].append(-1)
                cs.append(None)
                co.append(None)
            meta["size"] = end

    def _obj_truncate(self, okey: str, meta: dict, length: int) -> None:
        self._fold_wal(okey, meta)
        if length < meta["size"]:
            keep = -(-length // BLOCK) if length else 0
            self._freed.extend(b for b in meta["extents"][keep:]
                               if b >= 0)
            cs = self._csums(meta)
            co = self._comps(meta)
            meta["extents"] = meta["extents"][:keep]
            meta["csum"] = cs[:keep]
            meta["comp"] = co[:keep]
            # zero the tail of the boundary block (COW)
            if length % BLOCK and meta["extents"] \
                    and meta["extents"][-1] >= 0:
                tail = length % BLOCK
                self._patch_block(meta, len(meta["extents"]) - 1, tail,
                                  bytes(BLOCK - tail), okey=okey)
        meta["size"] = length

    # -- transactions ---------------------------------------------------------

    def _apply_one(self, op, cache, coll_exists, get, ensure,
                   drop) -> None:
        """Apply a single transaction op against the batch cache."""
        if op.op == OP_MKCOLL:
            cache[("__coll__", op.cid)] = {}
        elif op.op == OP_RMCOLL:
            # purge the collection's objects too (MemStore
            # drops the whole dict; the backends must agree)
            prefix = f"{op.cid}\x00"
            for k in self._db.get_range("obj"):
                if k.startswith(prefix):
                    drop(op.cid, k[len(prefix):])
            for (cid, oid), m in list(cache.items()):
                if cid == op.cid and m is not None:
                    drop(cid, oid)
            cache[("__coll__", op.cid)] = None
        elif op.op == OP_TOUCH:
            ensure(op.cid, op.oid)
        elif op.op == OP_WRITE:
            m = ensure(op.cid, op.oid)
            self._obj_write(_okey(op.cid, op.oid), m,
                            op.offset, op.data)
        elif op.op == OP_ZERO:
            m = ensure(op.cid, op.oid)
            self._obj_zero(_okey(op.cid, op.oid), m,
                           op.offset, op.length)
        elif op.op == OP_TRUNCATE:
            m = ensure(op.cid, op.oid)
            self._obj_truncate(_okey(op.cid, op.oid), m,
                               op.length)
        elif op.op == OP_REMOVE:
            drop(op.cid, op.oid)
        elif op.op == OP_OMAP_SETKEYS:
            m = ensure(op.cid, op.oid)
            for k, v in op.keys.items():
                m["omap"][k] = v.hex()
        elif op.op == OP_OMAP_RMKEYS:
            m = ensure(op.cid, op.oid)
            for k in op.rmkeys:
                m["omap"].pop(k, None)
        elif op.op == OP_SETATTR:
            m = ensure(op.cid, op.oid)
            m["attrs"][op.name] = op.data.hex()
        elif op.op == OP_COLL_MOVE:
            # metadata-only move: extents stay where they
            # are, the object record changes collections
            if not coll_exists(op.dest):
                raise KeyError(f"no collection {op.dest!r}")
            m = get(op.cid, op.oid)
            if m is not None:
                # fold before moving: wal keys are addressed
                # by the SOURCE collection
                self._fold_wal(_okey(op.cid, op.oid), m)
                prev = get(op.dest, op.oid)
                if prev is not None:   # overwrite: free old + its WAL
                    self._freed.extend(
                        b for b in prev["extents"] if b >= 0)
                    self._purge_wal(_okey(op.dest, op.oid), prev)
                cache[(op.dest, op.oid)] = m
                cache[(op.cid, op.oid)] = None
        elif op.op == OP_CLONE:
            m = get(op.cid, op.oid)
            if m is None:   # missing src: no-op (MemStore)
                return
            prev = get(op.cid, op.dest)
            if prev is not None:   # overwrite: free old + its WAL
                self._freed.extend(
                    b for b in prev["extents"] if b >= 0)
                self._purge_wal(_okey(op.cid, op.dest), prev)
            self._fold_wal(_okey(op.cid, op.oid), m)
            cs = self._csums(m)
            co = self._comps(m)
            dst = self._new_meta()
            dst["size"] = m["size"]
            dst["attrs"] = dict(m["attrs"])
            dst["omap"] = dict(m["omap"])
            for bi, src in enumerate(m["extents"]):
                if src < 0:
                    dst["extents"].append(-1)
                    dst["csum"].append(None)
                    dst["comp"].append(None)
                    continue
                # copy the STORED payload (compressed body stays
                # compressed — no decode/re-encode round-trip)
                stored = self._stored_read(src, cs[bi], co[bi])
                nb = self._alloc.allocate(1)[0]
                self._write_block(nb, stored, pad=co[bi] is None)
                dst["extents"].append(nb)
                dst["comp"].append(co[bi])
                if src in self._pending_csum:
                    # source was written THIS batch: its crc is still
                    # pending; the clone owes the same digest
                    self._pending_csum[nb] = stored
                    dst["csum"].append(None)
                else:
                    dst["csum"].append(cs[bi])
            cache[(op.cid, op.dest)] = dst


    def queue_transactions(self, txns, on_commit=None) -> None:
        # commit span on the calling op's trace: a traced write shows
        # objectstore commit time next to network fan-out and device
        # time (no-op context when the thread is untraced)
        from ceph_tpu.common import tracing
        with tracing.span("bluestore commit", daemon="bluestore",
                          txns=len(txns)):
            self._queue_transactions(txns, on_commit)

    def _queue_transactions(self, txns, on_commit=None) -> None:
        import time as _time
        t_start = _time.perf_counter()
        with self._lock:
            kvt = self._db.get_transaction()
            cache: dict[tuple, dict | None] = {}
            # per-batch state starts clean and is DISCARDED on failure:
            # an aborted transaction's deferred writes or freed blocks
            # must never leak into the next commit (blocks the aborted
            # batch COW-allocated leak until the next mount's rebuild)
            self._freed = []
            self._wal_pending = {}
            self._wal_rms = []
            self._pending_csum = {}
            self._comp_cache.clear()
            # bind the batch's engine once: every block this batch
            # stages rides (or skips) the channel consistently, and
            # engine-thread callers collapse to the scalar path here
            self._batch_eng = self._batch_engine()

            def coll_exists(cid):
                if ("__coll__", cid) in cache:
                    return cache[("__coll__", cid)] is not None
                return self._db.get("coll", cid) is not None

            def get(cid, oid):
                key = (cid, oid)
                if key not in cache:
                    cache[key] = self._meta(cid, oid)
                return cache[key]

            def ensure(cid, oid):
                if not coll_exists(cid):
                    raise KeyError(f"no collection {cid!r}")
                m = get(cid, oid)
                if m is None:
                    m = self._new_meta()
                    cache[(cid, oid)] = m
                return m

            def drop(cid, oid):
                m = get(cid, oid)
                if m is not None:
                    self._freed.extend(b for b in m["extents"]
                                       if b >= 0)
                    self._purge_wal(_okey(cid, oid), m)
                cache[(cid, oid)] = None

            def apply_ops():
                for t in txns:
                    for op in t.ops:
                        self._apply_one(op, cache, coll_exists, get,
                                        ensure, drop)

            try:
                t_apply = _time.perf_counter()
                apply_ops()
                self.perf.tinc("apply_lat",
                               _time.perf_counter() - t_apply)
                # settle the batch's checksum debt (one coalesced
                # device digest, scalar oracle on any failure) BEFORE
                # the fsync and KV build below read the final metas
                self._flush_pending_csums(cache)
            except Exception:
                self._freed = []
                self._wal_pending = {}
                self._wal_rms = []
                self._pending_csum = {}
                self._comp_cache.clear()
                self._block_dirty = False
                raise
            # data before metadata: fsync the block file, then ONE
            # atomic KV commit referencing it.  Displaced blocks return
            # to the allocator only after the commit — a crash (or an
            # exception above) leaves old metadata over untouched old
            # blocks; blocks this batch allocated then leak in-memory
            # only, and the next mount's rebuild reclaims them.  A batch
            # of pure deferred writes touched no block, so it pays no
            # data fsync at all (the KV commit carries the WAL bytes).
            if self._block_dirty:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._block_dirty = False
            # the KV mutations come from the FINAL cache state, never
            # eagerly per-op: a KV transaction applies sets before rms,
            # so a remove+recreate of one key in a batch (recovery's
            # replace-wholesale push) must collapse to a single set
            for (cid, oid), m in cache.items():
                if cid == "__coll__":
                    if m is not None:
                        kvt.set("coll", oid, b"1")
                    else:
                        kvt.rmkey("coll", oid)
                elif m is not None:
                    self._put_meta(kvt, cid, oid, m)
                else:
                    kvt.rmkey("obj", _okey(cid, oid))
            new_wal_keys: dict[str, list[str]] = {}
            for okey, entries in self._wal_pending.items():
                for seq, bi, boff, data in entries:
                    k = self._wal_key(okey, seq)
                    kvt.set("wal", k, _WAL_HDR.pack(bi, boff) + data)
                    new_wal_keys.setdefault(okey, []).append(k)
            for key in self._wal_rms:
                kvt.rmkey("wal", key)
            self._db.submit_transaction(kvt)
            # index maintenance AFTER the commit landed
            for key in self._wal_rms:
                okey = key.rsplit("\x00", 1)[0]
                lst = self._wal_index.get(okey)
                if lst and key in lst:
                    lst.remove(key)
            for okey, keys in new_wal_keys.items():
                self._wal_index.setdefault(okey, []).extend(keys)
            self._wal_pending = {}
            self._wal_rms = []
            self._alloc.release(self._freed)
            self._freed = []
            self.perf.inc("txc", len(txns))
            self.perf.tinc("commit_lat", _time.perf_counter() - t_start)
        if on_commit:
            on_commit()

    def apply_transaction(self, txn: Transaction) -> None:
        self.queue_transactions([txn])

    # -- reads ----------------------------------------------------------------

    def _get_checked(self, cid: str, oid: str) -> dict:
        if self._db.get("coll", cid) is None:
            raise KeyError(f"no collection {cid!r}")
        m = self._meta(cid, oid)
        if m is None:
            raise KeyError(f"no object {cid}/{oid}")
        return m

    def read(self, cid, oid, offset=0, length=None) -> bytes:
        with self._lock:
            m = self._get_checked(cid, oid)
            if length is None:
                length = m["size"] - offset
            return self._obj_read(_okey(cid, oid), m, offset,
                                  max(0, length))

    def stat(self, cid, oid) -> dict:
        with self._lock:
            return {"size": self._get_checked(cid, oid)["size"]}

    def exists(self, cid, oid) -> bool:
        with self._lock:
            return (self._db.get("coll", cid) is not None
                    and self._meta(cid, oid) is not None)

    def list_objects(self, cid) -> list[str]:
        with self._lock:
            if self._db.get("coll", cid) is None:
                raise KeyError(f"no collection {cid!r}")
            prefix = f"{cid}\x00"
            out = []
            for k in self._db.get_range("obj"):
                if k.startswith(prefix):
                    out.append(k[len(prefix):])
            return sorted(out)

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._db.get_range("coll"))

    def omap_get(self, cid, oid) -> dict:
        with self._lock:
            m = self._get_checked(cid, oid)
            return {k: bytes.fromhex(v) for k, v in m["omap"].items()}

    def getattr(self, cid, oid, name):
        with self._lock:
            m = self._get_checked(cid, oid)
            v = m["attrs"].get(name)
            return bytes.fromhex(v) if v is not None else None
