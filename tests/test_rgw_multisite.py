"""RGW multisite zone sync (rgw_data_sync.cc reduced): two independent
clusters, a primary gateway with datalogs and a pull-replay agent on the
secondary — full sync, incremental deltas, restart-resume from markers,
delete propagation, and datalog trim."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.rgw_rest import S3Gateway
from ceph_tpu.rgw_sync import ZoneSyncAgent, datalog_entries
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture
def zones():
    c1 = MiniCluster(n_osds=3).start()
    c2 = MiniCluster(n_osds=3).start()
    c1.wait_for_osd_count(3)
    c2.wait_for_osd_count(3)
    io1 = c1.client().open_ioctx(c1.create_pool(c1.client(), pg_num=4,
                                                size=2))
    io2 = c2.client().open_ioctx(c2.create_pool(c2.client(), pg_num=4,
                                                size=2))
    src = S3Gateway(io1)
    src.datalog_enabled = True
    dst = S3Gateway(io2)
    yield src, dst
    c1.stop()
    c2.stop()


def test_full_then_incremental_sync(zones):
    src, dst = zones
    src.create_bucket("media", owner="alice")
    src.put_object("media", "a.bin", b"AAAA" * 100, {})
    src.put_object("media", "b.bin", b"BBBB" * 100, {"k": "v"})

    agent = ZoneSyncAgent(src, dst)
    st = agent.sync_once()
    assert st["full_copied"] == 2, st
    data, head = dst.get_object("media", "b.bin")
    assert data == b"BBBB" * 100
    assert head["meta"] == {"k": "v"}

    # incremental: new put + delete propagate
    src.put_object("media", "c.bin", b"CCCC", {})
    src.delete_object("media", "a.bin")
    st = agent.sync_once()
    assert st["applied"] == 2, st
    assert dst.get_object("media", "c.bin")[0] == b"CCCC"
    from ceph_tpu.rgw_rest import S3Error
    with pytest.raises(S3Error):
        dst.get_object("media", "a.bin")

    # idempotent: nothing new applies twice
    st = agent.sync_once()
    assert st["applied"] == 0 and st["full_copied"] == 0


def test_marker_survives_agent_restart(zones):
    src, dst = zones
    src.create_bucket("docs", owner="o")
    src.put_object("docs", "one", b"1", {})
    ZoneSyncAgent(src, dst).sync_once()
    src.put_object("docs", "two", b"2", {})
    # a BRAND NEW agent instance resumes from the persisted marker:
    # only the delta applies, no re-full-sync
    st = ZoneSyncAgent(src, dst).sync_once()
    assert st["full_copied"] == 0
    assert st["applied"] == 1, st
    assert dst.get_object("docs", "two")[0] == b"2"


def test_datalog_trimmed_after_sync(zones):
    src, dst = zones
    src.create_bucket("loggy", owner="o")
    agent = ZoneSyncAgent(src, dst)
    agent.sync_once()                      # establish marker
    for i in range(5):
        src.put_object("loggy", f"k{i}", b"x", {})
    assert len(datalog_entries(src, "loggy")) == 5
    agent.sync_once()
    # processed records were trimmed from the primary's log
    assert datalog_entries(src, "loggy") == []
    assert dst.get_object("loggy", "k4")[0] == b"x"


def test_two_secondaries_converge_despite_trim(zones):
    """Per-peer trim floor (rgw_data_sync sync-status): a FAST secondary
    trimming the datalog must never drop records a SLOW secondary has
    not applied yet — trim stops at min(peer markers)."""
    src, fast_dst = zones
    c3 = MiniCluster(n_osds=3).start()
    try:
        c3.wait_for_osd_count(3)
        io3 = c3.client().open_ioctx(
            c3.create_pool(c3.client(), pg_num=4, size=2))
        slow_dst = S3Gateway(io3)
        src.create_bucket("shared", owner="o")
        fast = ZoneSyncAgent(src, fast_dst, zone_id="zone-fast")
        slow = ZoneSyncAgent(src, slow_dst, zone_id="zone-slow")
        # both register (full sync at empty log)
        fast.sync_once()
        slow.sync_once()
        # writes land; only the FAST one syncs (and tries to trim)
        for i in range(6):
            src.put_object("shared", f"k{i}", f"v{i}".encode(), {})
        fast.sync_once()
        assert fast_dst.get_object("shared", "k5")[0] == b"v5"
        # the records the slow peer still needs SURVIVED the trim
        assert len(datalog_entries(src, "shared")) == 6
        # more writes, another fast pass — still floored by the slow peer
        src.put_object("shared", "late", b"straggler", {})
        fast.sync_once()
        assert len(datalog_entries(src, "shared")) == 7
        # the slow peer catches up from the intact log
        slow.sync_once()
        for i in range(6):
            assert slow_dst.get_object(
                "shared", f"k{i}")[0] == f"v{i}".encode()
        assert slow_dst.get_object("shared", "late")[0] == b"straggler"
        # with BOTH peers past the records, the next pass trims
        fast.sync_once()
        assert datalog_entries(src, "shared") == []
    finally:
        c3.stop()


def test_background_agent_converges(zones):
    src, dst = zones
    src.create_bucket("auto", owner="o")
    agent = ZoneSyncAgent(src, dst, interval=0.2).start()
    try:
        src.put_object("auto", "live", b"streamed", {})
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                if dst.get_object("auto", "live")[0] == b"streamed":
                    break
            except Exception:
                pass
            time.sleep(0.1)
        assert dst.get_object("auto", "live")[0] == b"streamed"
    finally:
        agent.stop()


def test_bucket_deletion_propagates(zones):
    src, dst = zones
    src.create_bucket("doomed", owner="o")
    src.put_object("doomed", "x", b"1", {})
    agent = ZoneSyncAgent(src, dst)
    agent.sync_once()
    assert dst.get_object("doomed", "x")[0] == b"1"
    src.delete_object("doomed", "x")
    agent.sync_once()
    src.delete_bucket("doomed")
    agent.sync_once()
    from ceph_tpu.rgw_rest import S3Error
    with pytest.raises(S3Error):
        dst.get_object("doomed", "x")
    with pytest.raises(S3Error):
        dst.list_objects("doomed", "", 10, "")


def test_lifecycle_expiry_propagates(zones):
    # an object expired by the PRIMARY's lifecycle agent must also
    # disappear from the secondary (datalogged delete)
    src, dst = zones
    state = {"t": 1_700_000_000.0}
    src.clock = lambda: state["t"]
    src.create_bucket("lc", owner="o")
    src.set_lifecycle("lc", [{"prefix": "", "status": "Enabled",
                              "expiration_days": 1}])
    src.put_object("lc", "old", b"bytes", {})
    agent = ZoneSyncAgent(src, dst)
    agent.sync_once()
    assert dst.get_object("lc", "old")[0] == b"bytes"
    state["t"] += 2 * 86400
    st = src.lifecycle_pass()
    assert st["expired"] == 1
    agent.sync_once()
    from ceph_tpu.rgw_rest import S3Error
    with pytest.raises(S3Error):
        dst.get_object("lc", "old")


def test_version_targeted_delete_does_not_nuke_secondary(zones):
    # review scenario: deleting a NONCURRENT version must not replay as
    # a hard delete of the secondary's current object; removing a
    # delete marker (undelete) must restore the object on the peer
    src, dst = zones
    src.create_bucket("verz", owner="o")
    src.set_versioning("verz", "Enabled")
    agent = ZoneSyncAgent(src, dst)
    agent.sync_once()
    _, v1 = src.put_object("verz", "k", b"gen-one", {})
    _, v2 = src.put_object("verz", "k", b"gen-two", {})
    agent.sync_once()
    assert dst.get_object("verz", "k")[0] == b"gen-two"
    # delete the NONCURRENT v1: secondary must keep gen-two
    src.delete_object("verz", "k", vid=v1)
    agent.sync_once()
    assert dst.get_object("verz", "k")[0] == b"gen-two"
    # marker (plain delete) removes it from the peer...
    res = src.delete_object("verz", "k")
    assert res["delete_marker"]
    agent.sync_once()
    from ceph_tpu.rgw_rest import S3Error
    with pytest.raises(S3Error):
        dst.get_object("verz", "k")
    # ...and removing the marker (undelete) restores it
    src.delete_object("verz", "k", vid=res["version_id"])
    agent.sync_once()
    assert dst.get_object("verz", "k")[0] == b"gen-two"
