"""ErasureCode base class — shared logic every matrix-code plugin inherits.

Follows src/erasure-code/ErasureCode.{h,cc}: encode_prepare padding semantics
(SIMD_ALIGN=32, zero-fill the tail of the last data chunks, ErasureCode.cc:
137-172), generic encode via encode_chunks (:174-190), generic decode via
matrix recovery (:198-234), greedy _minimum_to_decode (:89-106), chunk
remapping (:260-279), and profile parsing helpers (:281-329).

The compute path is the batched device kernel: encode_chunks/decode_chunks on
(S, k, B) uint8 arrays lower to one MXU matmul (ceph_tpu.ops.gf_kernel), with
the numpy oracle available for verification (profile runtime=cpu).
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ceph_tpu.common import lockdep
from ceph_tpu.gf.matrix import recovery_matrix
from ceph_tpu.ops.dispatch import bucket_stripes
from ceph_tpu.ops.gf_kernel import ec_encode_ref

from .interface import ErasureCodeInterface, ErasureCodeProfile

SIMD_ALIGN = 32  # ErasureCode.h SIMD_ALIGN — chunk padding quantum

#: recovery matrices kept per codec (ErasureCodeIsaTableCache analog);
#: true LRU — a hot mixed-pattern workload evicts one cold entry at a
#: time instead of periodically dropping every matrix at once
DECODE_CACHE_CAP = 256

#: erasure patterns per stacked decode table before the table is
#: RETIRED and a fresh generation starts: bounds both the table's
#: host+device memory and the jit signature's table axis on long-lived
#: daemons with churning shard membership.  In-flight batches keep
#: their captured (generation-keyed) table alive; the engine key
#: carries the generation, so cross-generation requests never share a
#: batch and every stripe's pattern index stays valid for the table it
#: was registered against.
PATTERN_TABLE_CAP = 512


class ErasureCode(ErasureCodeInterface):
    """Systematic GF(2^8) matrix code driven by a (k+m, k) generator matrix.

    Subclasses set self.k, self.m and implement _build_generator() returning the
    generator matrix (identity on top).  Everything else — padding, batched
    device encode, decode-by-inversion with an LRU recovery-matrix cache
    (ErasureCodeIsaTableCache analog) — lives here.
    """

    #: MDS matrix codecs with batched encode_chunks/decode_chunks can be
    #: laid out striped for range rmw (ECUtil stripe math); non-MDS or
    #: layered codecs fall back to whole-object writes
    supports_rmw_striping = True

    #: codecs whose recovery matrices live at chunk granularity can
    #: submit decodes through the dispatch engine
    #: (submit_decode_chunks); packet-level bitmatrix codecs override
    #: to False and keep the synchronous decode path
    supports_submit_decode = True

    #: profile keys consumed by init (reference: parse() per plugin)
    _PROFILE_KEYS = ("k", "m", "technique", "runtime", "plugin",
                     "crush-failure-domain", "crush-root",
                     "crush-device-class", "directory", "w", "packetsize")

    def __init__(self):
        self.k = 0
        self.m = 0
        self.technique = ""
        self.runtime = "tpu"   # "tpu" (device kernel) or "cpu" (numpy oracle)
        self._generator: np.ndarray | None = None
        self._encoder = None
        #: {mesh: encoder} LRU — submit_chunks through a mesh-sharded
        #: engine uses an encoder whose bit tables are replicated over
        #: that mesh (one broadcast at build, none per flush); keyed by
        #: mesh (not a single slot), so one codec feeding
        #: differently-meshed engines does not rebuild tables on every
        #: alternating submit
        self._mesh_encoders: OrderedDict = OrderedDict()
        self._decode_cache: OrderedDict = OrderedDict()
        #: guards _decode_cache AND the pattern tables: decodes now
        #: submit from many OSD threads through the dispatch engine
        self._decode_lock = lockdep.make_lock("ErasureCode::decode")
        #: t_bucket -> {"gen": generation counter,
        #:              "ids": {(chosen, targets): idx},
        #:              "mats": [(t_bucket, k) uint8 padded matrices],
        #:              "bits": [(k*8, t_bucket*8) uint8 bit matrices],
        #:              "snap": stacked pow2-padded table or None,
        #:              "snap_dev": device-resident copy of snap}
        #: — the heterogeneous-decode pattern registry.  Append-only
        #: WITHIN a generation (indices are stable, so a submitted
        #: stripe's pattern id stays valid however the table grows
        #: behind it); at PATTERN_TABLE_CAP the whole table retires
        #: and a fresh generation starts.
        self._pattern_tables: dict[int, dict] = {}
        #: monotonic generation source for ALL tables of this codec —
        #: never reset (init()'s clear included), so an engine key's
        #: generation component cannot collide across a re-init while
        #: old-generation requests are still queued
        self._pattern_gen = 0
        self._chunk_mapping: list[int] = []

    # -- profile parsing (ErasureCode.cc:281-329 to_int/to_bool) --------------

    @staticmethod
    def to_int(name: str, profile: ErasureCodeProfile, default: int) -> int:
        v = profile.get(name, default)
        try:
            return int(v)
        except (TypeError, ValueError):
            raise ValueError(f"{name}={v!r} is not an integer")

    @staticmethod
    def to_bool(name: str, profile: ErasureCodeProfile, default: bool) -> bool:
        v = str(profile.get(name, default)).lower()
        return v in ("true", "1", "yes")

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        self._generator = np.asarray(self._build_generator(), dtype=np.uint8)
        assert self._generator.shape == (self.k + self.m, self.k)
        self._encoder = None
        with self._decode_lock:
            self._mesh_encoders.clear()
        with self._decode_lock:
            self._decode_cache.clear()
            self._pattern_tables.clear()

    def parse(self, profile: ErasureCodeProfile) -> None:
        """Subclasses override to parse technique-specific keys; must set k, m."""
        self.k = self.to_int("k", profile, self._default_k())
        self.m = self.to_int("m", profile, self._default_m())
        self.runtime = profile.get("runtime", "tpu")
        if self.k < 1 or self.m < 1:
            raise ValueError(f"k={self.k} m={self.m} must be >= 1")
        unknown = set(profile) - set(self._PROFILE_KEYS)
        if unknown:
            raise ValueError(f"unknown profile keys {sorted(unknown)}")

    def _default_k(self) -> int:
        return 7

    def _default_m(self) -> int:
        return 3

    def _build_generator(self) -> np.ndarray:
        raise NotImplementedError

    @property
    def generator(self) -> np.ndarray:
        assert self._generator is not None, "init() not called"
        return self._generator

    # -- chunk geometry -------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        """Bytes the object must pad to before splitting into k chunks."""
        return self.k * SIMD_ALIGN

    def get_chunk_size(self, stripe_width: int) -> int:
        """ErasureCodeJerasure::get_chunk_size semantics: pad the object to the
        alignment quantum, then divide by k."""
        alignment = self.get_alignment()
        padded = (stripe_width + alignment - 1) // alignment * alignment
        return padded // self.k

    # -- minimum_to_decode (ErasureCode.cc:89-106) ----------------------------

    def minimum_to_decode(self, want_to_read: set, available: set) -> set:
        if want_to_read <= available:
            return set(want_to_read)
        if len(available) < self.k:
            raise IOError(
                f"cannot decode {sorted(want_to_read)}: only "
                f"{len(available)} of k={self.k} chunks available")
        return set(sorted(available)[:self.k])

    # -- encode (ErasureCode.cc:137-190) --------------------------------------

    def encode_prepare(self, data: bytes) -> np.ndarray:
        """Pad + split into (k, chunk) uint8 — zero-fill tail chunks
        (ErasureCode.cc:137-172)."""
        chunk = self.get_chunk_size(len(data))
        padded = np.zeros(self.k * chunk, dtype=np.uint8)
        padded[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        return padded.reshape(self.k, chunk)

    def encode(self, want_to_encode: set, data: bytes) -> dict:
        chunks = self.encode_prepare(data)
        parity = np.asarray(self.encode_chunks(chunks[None]))[0]
        allc = {i: chunks[i].tobytes() for i in range(self.k)}
        allc.update({self.k + i: parity[i].tobytes() for i in range(self.m)})
        return {i: allc[i] for i in want_to_encode}

    def encode_chunks(self, data_chunks):
        """(S, k, B) uint8 -> (S, m, B) uint8 on the selected runtime.

        runtime "tpu" runs the batched MXU kernel, "native" the in-repo
        single-core C SIMD encode (the ISA-L-class plugin proper — same
        role as the reference's isa plugin on hosts without the device),
        and "cpu" the numpy oracle (verification)."""
        coding = self.generator[self.k:]
        if self.runtime == "cpu":
            return ec_encode_ref(coding, np.asarray(data_chunks))
        if self.runtime == "native":
            from ceph_tpu.native import ec_encode_native
            return ec_encode_native(coding, np.asarray(data_chunks))
        if self._encoder is None:
            from ceph_tpu.ops.gf_kernel import make_encoder
            self._encoder = make_encoder(coding)
        return self._encoder(np.asarray(data_chunks, dtype=np.uint8))

    #: distinct meshes whose encoders one codec keeps resident
    MESH_ENCODER_CAP = 4

    def _encoder_for_mesh(self, mesh):
        """Encoder with bit tables replicated over ``mesh`` (the
        engine's placement mesh) — a mesh-sharded batch then meets
        mesh-resident tables instead of a per-flush broadcast.
        Mesh-keyed true LRU (meshes hash by value, so a hot-reload's
        rebuilt-but-equal mesh hits the same entry), the OrderedDict
        idiom the recovery caches use; the build (bit tables +
        broadcast) runs OUTSIDE the lock, a racing duplicate is
        idempotent."""
        with self._decode_lock:
            enc = self._mesh_encoders.get(mesh)
            if enc is not None:
                self._mesh_encoders.move_to_end(mesh)
                return enc
        from ceph_tpu.ops.gf_kernel import make_encoder
        enc = make_encoder(self.generator[self.k:], mesh=mesh)
        with self._decode_lock:
            self._mesh_encoders[mesh] = enc
            self._mesh_encoders.move_to_end(mesh)
            while len(self._mesh_encoders) > self.MESH_ENCODER_CAP:
                self._mesh_encoders.popitem(last=False)
        return enc

    def submit_chunks(self, engine, data_chunks, cost_tag=None):
        """Submit an (S, k, B) encode through a dispatch engine
        (ops.dispatch): returns a DispatchFuture of the (S, m, B)
        parity.  Concurrent submits against the same codec and chunk
        width coalesce on the stripe axis into one device call; the
        engine's zero-stripe padding is bit-exact here because the code
        is linear (zeros encode to zeros).  On a mesh-sharded engine
        the coalesced batch additionally splits its stripe axis across
        the mesh (host runtimes opt out — sharding a batch a numpy fn
        would immediately gather back is pure overhead).  ``cost_tag``
        is the (tenant, dmclock class) pair the tenant device-time
        ledger attributes this request's stripe share to."""
        # analysis: allow[blocking] -- chunk input is host bytes/numpy by API contract
        data = np.asarray(data_chunks, dtype=np.uint8)
        key = ("ec_encode", id(self), self.k, self.m, data.shape[-1],
               self.runtime)
        cache_entries = None
        fn = self.encode_chunks
        place = False
        fallback = None
        if type(self).encode_chunks is ErasureCode.encode_chunks:
            # bit-exact host oracle for the engine's failure ladder
            # (zeros-pad linearity holds for the oracle exactly as for
            # the kernel).  Only the base dense encode qualifies: an
            # overriding codec's packet/layered pipeline has no dense
            # generator equivalent, so it keeps retry-only recovery.
            coding = self.generator[self.k:]

            def fallback(batch, _c=coding):
                # analysis: allow[blocking] -- host-oracle fallback receives the engine's rebuilt HOST batch (numpy), never a device value
                return ec_encode_ref(_c, np.asarray(batch))
        if self.runtime == "tpu":
            from ceph_tpu.ops.gf_kernel import _jit_entries
            cache_entries = _jit_entries
            # mesh placement only fits the BASE dense-matrix encode:
            # codecs overriding encode_chunks (packet-level bitmatrix,
            # clay's layered transform) run their own host/packet
            # pipelines a sharded batch would break or gather back
            if type(self).encode_chunks is ErasureCode.encode_chunks:
                place = True
                mesh = engine.placement_mesh()
                if mesh is not None:
                    fn = self._encoder_for_mesh(mesh)
        return engine.submit(key, fn, data,
                             label="ec_encode",
                             cache_entries=cache_entries, place=place,
                             fallback=fallback, cost_tag=cost_tag)

    # -- decode (ErasureCode.cc:198-234 / ErasureCodeIsa.cc:150-310) ----------

    def _recovery_cached(self, key, build) -> np.ndarray:
        """The LRU protocol both recovery caches share (base and the
        packet-level bitmatrix override): move-to-end on hit, evict the
        single least-recent entry past the cap — a hot mixed-pattern
        workload never loses its whole working set at once.  ``build``
        (the matrix inversion) runs OUTSIDE the lock; a racing
        duplicate computation is idempotent."""
        with self._decode_lock:
            mat = self._decode_cache.get(key)
            if mat is not None:
                self._decode_cache.move_to_end(key)
                return mat
        mat = build()
        with self._decode_lock:
            self._decode_cache[key] = mat
            self._decode_cache.move_to_end(key)
            while len(self._decode_cache) > DECODE_CACHE_CAP:
                self._decode_cache.popitem(last=False)
        return mat

    def _recovery(self, chosen: tuple, targets: tuple) -> np.ndarray:
        """LRU-cached recovery matrix (ErasureCodeIsaTableCache
        analog)."""
        return self._recovery_cached(
            (chosen, targets),
            lambda: recovery_matrix(self.generator, list(chosen),
                                    list(targets)))

    def decode_chunks(self, chosen, chunks, targets):
        """chunks: (S, k, B) uint8 rows ``chosen`` -> (S, len(targets), B)."""
        rmat = self._recovery(tuple(chosen), tuple(targets))
        if self.runtime == "cpu":
            return ec_encode_ref(rmat, np.asarray(chunks))
        if self.runtime == "native":
            from ceph_tpu.native import ec_encode_native
            return ec_encode_native(rmat, np.asarray(chunks))
        from ceph_tpu.ops.gf_kernel import ec_encode_jax
        return ec_encode_jax(rmat, np.asarray(chunks, dtype=np.uint8))

    # -- heterogeneous-matrix batched decode (the submit path) ----------------

    def _target_bucket(self, t: int) -> int:
        """Pad target-row counts up to a per-codec constant: every
        pattern with <= m targets (the only counts a degraded read or
        recovery pull can produce) shares ONE bucket, so 1-erasure and
        2-erasure decodes coalesce into the same device call.  Wider
        requests (generic decode_chunks callers) get their own pow-2
        bucket."""
        return bucket_stripes(max(t, self.m, 1))

    def _register_pattern(self, chosen: tuple, targets: tuple
                          ) -> tuple[int, int, dict]:
        """(pattern index, t_bucket, table) for an erasure pattern,
        creating the padded recovery matrix + bit matrix on first
        sight.  The returned TABLE is what the submitter must capture
        (and key its engine requests by ``table["gen"]``): a cap-full
        table retires wholesale, and an in-flight stripe's index is
        only meaningful against the generation it registered with.
        Raises ValueError when the chosen rows are singular."""
        tb = self._target_bucket(len(targets))
        with self._decode_lock:
            tab = self._pattern_tables.get(tb)
            if tab is not None:
                idx = tab["ids"].get((chosen, targets))
                if idx is not None:
                    return idx, tb, tab
        # matrix inversion + bit expansion OUTSIDE the lock; a racing
        # duplicate registration is resolved below
        rmat = self._recovery(chosen, targets)
        padded = np.zeros((tb, self.k), dtype=np.uint8)
        padded[:len(targets)] = rmat
        from ceph_tpu.gf.tables import bit_matrix
        bits = bit_matrix(padded)
        with self._decode_lock:
            tab = self._pattern_tables.get(tb)
            if tab is None or len(tab["mats"]) >= PATTERN_TABLE_CAP:
                # retire the full table: new submissions start a fresh
                # generation (new engine key); in-flight batches keep
                # their captured table object alive until delivered
                self._pattern_gen += 1
                tab = {"gen": self._pattern_gen,
                       "ids": {}, "mats": [], "bits": [],
                       "snap": None, "snap_dev": None}
                self._pattern_tables[tb] = tab
            idx = tab["ids"].get((chosen, targets))
            if idx is None:
                idx = len(tab["mats"])
                tab["ids"][(chosen, targets)] = idx
                tab["mats"].append(padded)
                tab["bits"].append(bits)
                tab["snap"] = None       # table grew: re-snapshot
                tab["snap_dev"] = None   # lazily, host and device
            return idx, tb, tab

    def _pattern_snapshot(self, tab: dict, device: bool = False,
                          mesh=None):
        """(stacked pow2-padded bit table (P, k*8, tb*8) int8, padded
        uint8 matrices, live pattern count) for a captured table
        object — the operand the batched kernel gathers from.  Pow-2
        padding with zero matrices bounds the jit cache by the table
        bucket, not the pattern population; a zero matrix decodes
        anything to zeros, and no live stripe ever indexes a padded
        slot.

        ``device=True`` returns a device-RESIDENT table (cached until
        the table grows): the whole point of coalescing is amortizing
        the dispatch boundary, so the table must not be re-uploaded
        host-to-device on every call — the same rule make_encoder
        applies to the encode tables.  ``mesh`` (a mesh-sharded
        engine's placement mesh) places the device table REPLICATED
        over the mesh so the gather kernel meets a sharded batch with
        consistent shardings; the cached copy is keyed to the mesh and
        rebuilt when it changes.  The stack + upload run OUTSIDE
        the codec lock: the table is append-only within a generation,
        so a prefix copy is consistent and covers every pattern index
        any in-flight batch can carry (indices are assigned before
        submit); a concurrent append just leaves the cached snapshot
        for the next caller to rebuild."""
        with self._decode_lock:
            host = tab["snap"]
            dev = tab["snap_dev"]
            if tab.get("snap_dev_mesh") != mesh:
                dev = None   # mesh changed: re-place (VALUE equality —
                # a hot-reload rebuilds an equal Mesh object, and the
                # cached table placed on it is still the right one)
            mats = list(tab["mats"])
            if host is not None and (dev is not None or not device):
                return (dev if device else host), mats, len(mats)
            bits = list(tab["bits"])
        n = len(bits)
        if host is None:
            host = np.zeros((bucket_stripes(max(n, 1)),)
                            + bits[0].shape, dtype=np.int8)
            host[:n] = np.stack(bits)
        if device:
            import jax
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                dev = jax.device_put(
                    host, NamedSharding(mesh, PartitionSpec()))
            else:
                dev = jax.device_put(host)
        with self._decode_lock:
            if len(tab["bits"]) == n:    # still current: cache it
                tab["snap"] = host
                if device:
                    tab["snap_dev"] = dev
                    tab["snap_dev_mesh"] = mesh
        return (dev if device else host), mats, n

    def _decode_batch_fn(self, tab: dict, tb: int, stats=None):
        """The engine-side fn for one table generation: decodes a
        coalesced (S, k, B) batch whose stripes may span MANY erasure
        patterns (pattern index per stripe in the aux array).  The
        TABLE OBJECT is captured, not looked up: a retired generation
        stays alive — and its indices meaningful — for exactly as long
        as batches against it are in flight.  ``stats`` is the
        DecodeDispatchStats sink the heterogeneity sample lands in —
        the submitting engine's own sink, so a privately-instrumented
        engine sees its patterns histogram populated."""
        def fn(data, pidx):
            # the pattern-heterogeneity sample reads pidx host-side (it
            # is tiny); the copy feeding the KERNEL stays as the engine
            # delivered it — on a mesh-sharded engine that is a sharded
            # device array gathered per-stripe on every chip
            host_pidx = np.asarray(pidx)
            uniq = np.unique(host_pidx)
            device = self.runtime not in ("cpu", "native")
            mesh = getattr(getattr(data, "sharding", None), "mesh", None) \
                if device else None
            snap, mats, live = self._pattern_snapshot(
                tab, device=device, mesh=mesh)
            if stats is not None:
                stats.record_patterns(int(uniq.size), live)
            if not device:
                if self.runtime == "native":
                    from ceph_tpu.native import ec_encode_native as enc
                else:
                    enc = ec_encode_ref
                return self._host_pattern_decode(enc, mats, host_pidx,
                                                 data, tb)
            from ceph_tpu.ops.gf_kernel import ec_decode_batched
            return ec_decode_batched(snap, pidx, data, k=self.k, t=tb)
        return fn

    @staticmethod
    def _host_pattern_decode(enc, mats, host_pidx, data, tb):
        """Group a coalesced decode batch by pattern index and rebuild
        each group with its padded recovery matrix — THE host decode
        semantics, shared by the cpu-runtime branch of
        ``_decode_batch_fn`` and the engine's fallback oracle.  One
        copy on purpose: the two callers must stay byte-for-byte
        equivalent or fallback-vs-device bit-exactness silently
        breaks on the decode channel."""
        out = np.zeros((data.shape[0], tb, data.shape[-1]),
                       dtype=np.uint8)
        for p in np.unique(host_pidx):
            rows = np.nonzero(host_pidx == p)[0]
            out[rows] = np.asarray(enc(mats[int(p)], data[rows]))
        return out

    def _decode_fallback_fn(self, tab: dict, tb: int):
        """Bit-exact host oracle for one decode table generation — the
        engine's failure ladder runs it when the device path stays
        broken: group the coalesced batch by pattern index and rebuild
        each group with its padded recovery matrix through
        ``ec_encode_ref`` (exactly the cpu-runtime branch of
        ``_decode_batch_fn``, which PR 4's tests pin bit-identical to
        the batched kernel)."""
        def fb(data, pidx):
            host_pidx = np.asarray(pidx)
            data = np.asarray(data)
            _snap, mats, _live = self._pattern_snapshot(tab)
            return self._host_pattern_decode(ec_encode_ref, mats,
                                             host_pidx, data, tb)
        return fb

    def submit_decode_chunks(self, engine, chosen, chunks, targets,
                             cost_tag=None):
        """Submit an (S, k, B) decode through a dispatch engine
        (ops.dispatch): returns a DispatchFuture of the
        (S, len(targets), B) rebuilt rows.  The decode-side twin of
        submit_chunks — but where encodes share one matrix, concurrent
        decodes with DIFFERENT erasure patterns still coalesce into one
        device call: each pattern's recovery matrix (reusing the
        _recovery LRU) is registered in a stacked bit-matrix table, the
        per-stripe pattern index rides the engine's aux channel, and
        the kernel gathers the matrix per stripe
        (gf_kernel.ec_decode_batched).  Raises ValueError synchronously
        when the chosen rows are singular, so callers can fall back to
        the widen-and-regather ladder before anything is queued."""
        data = np.asarray(chunks, dtype=np.uint8)
        chosen = tuple(chosen)
        targets = tuple(targets)
        t = len(targets)
        idx, tb, tab = self._register_pattern(chosen, targets)
        pidx = np.full(data.shape[0] if data.ndim else 1, idx,
                       dtype=np.int32)
        # the table GENERATION is part of the key: requests against a
        # retired table must never share a batch with the generation
        # that replaced it — a pattern index is only meaningful
        # against the table it registered with
        key = ("ec_decode", id(self), self.k, tb, data.shape[-1],
               self.runtime, tab["gen"])
        cache_entries = None
        if self.runtime == "tpu":
            from ceph_tpu.ops.gf_kernel import _decode_jit_entries
            cache_entries = _decode_jit_entries
        # heterogeneity samples land in the ENGINE's stats sink when it
        # is decode-instrumented, falling back to the global decode
        # registry (engines with a plain DispatchStats sink)
        from ceph_tpu.ops import telemetry
        stats = engine.stats if isinstance(
            engine.stats, telemetry.DecodeDispatchStats) \
            else telemetry.decode_dispatch_stats()
        inner = engine.submit(key, self._decode_batch_fn(tab, tb, stats),
                              data, aux=(pidx,), label="ec_decode",
                              cache_entries=cache_entries,
                              place=self.runtime == "tpu",
                              fallback=self._decode_fallback_fn(tab, tb),
                              cost_tag=cost_tag)
        if t == tb:
            return inner
        # the batch computes tb target rows per stripe (the bucket);
        # deliver only this request's real ones.  The wrapper future
        # preserves the engine's delivery order — the slice happens in
        # the inner future's callback, on the completion thread.
        from ceph_tpu.ops.dispatch import DispatchFuture
        outer = DispatchFuture()

        def _slice(f, t=t, outer=outer):
            exc = f.exception()
            if exc is not None:
                outer._deliver(None, exc)
            else:
                # analysis: allow[blocking] -- delivered value is already host numpy (completion thread materialized it)
                outer._deliver(np.asarray(f.result())[:, :t, :], None)

        inner.add_done_callback(_slice)
        return outer

    def decode(self, want_to_read: set, chunks: dict) -> dict:
        available = set(chunks)
        out = {i: chunks[i] for i in want_to_read & available}
        missing = sorted(want_to_read - available)
        if not missing:
            return out
        if len(available) < self.k:
            raise IOError(
                f"cannot decode {missing}: only {len(available)} of "
                f"k={self.k} chunks available")
        chosen = sorted(available)[:self.k]
        arr = np.stack([np.frombuffer(chunks[i], dtype=np.uint8)
                        for i in chosen])
        rebuilt = np.asarray(self.decode_chunks(chosen, arr[None], missing))[0]
        for idx, i in enumerate(missing):
            out[i] = rebuilt[idx].tobytes()
        return out

    # -- chunk remapping (ErasureCode.cc:260-279) -----------------------------

    @staticmethod
    def to_mapping(mapping: str) -> list[int]:
        """Parse a mapping string like "_DDD_DD" — 'D' positions hold chunks,
        other characters are gaps (used by LRC; ErasureCode.cc:260-279)."""
        out = []
        for pos, c in enumerate(mapping):
            if c == "D":
                out.append(pos)
        return out

    def get_chunk_mapping(self) -> list:
        return list(self._chunk_mapping)

    # -- CRUSH rule (ErasureCode.cc:53-72) ------------------------------------

    def create_rule(self, name: str, crush_map) -> int:
        from ceph_tpu.crush.builder import add_simple_rule
        return add_simple_rule(crush_map, -1, 0, "indep")
