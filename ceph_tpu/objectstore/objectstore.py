"""ObjectStore contract + MemStore + FileStore.

The contract mirrors os/ObjectStore.h: mount/umount, collections, object
read/stat/list, omap access, and atomic queue_transactions with on_commit
callbacks.  MemStore (src/os/memstore/) is the in-RAM test backend; FileStore
persists to a directory tree with a crc-framed write-ahead journal replayed on
mount (src/os/filestore/ FileJournal structure).
"""

from __future__ import annotations

import os
import shutil
import struct
import threading
import zlib

from .transaction import (
    OP_CLONE, OP_COLL_MOVE, OP_MKCOLL, OP_OMAP_RMKEYS, OP_OMAP_SETKEYS,
    OP_REMOVE, OP_RMCOLL, OP_SETATTR, OP_TOUCH, OP_TRUNCATE, OP_WRITE,
    OP_ZERO,
    Transaction)


class ObjectStore:
    """Abstract store (os/ObjectStore.h)."""

    def mount(self) -> None:
        raise NotImplementedError

    def umount(self) -> None:
        raise NotImplementedError

    def mkfs(self) -> None:
        raise NotImplementedError

    def mkfs_if_needed(self) -> None:
        """mkfs only when no prior state exists — a restart must keep data
        (OSD::init reads the superblock, it does not reformat)."""
        self.mkfs()

    def queue_transactions(self, txns: list[Transaction],
                           on_commit=None) -> None:
        """Apply atomically in order; on_commit fires after durability
        (os/ObjectStore.h:1460)."""
        raise NotImplementedError

    def apply_transaction(self, txn: Transaction) -> None:
        self.queue_transactions([txn])

    # reads
    def read(self, cid: str, oid: str, offset: int = 0,
             length: int | None = None) -> bytes:
        raise NotImplementedError

    def stat(self, cid: str, oid: str) -> dict:
        raise NotImplementedError

    def exists(self, cid: str, oid: str) -> bool:
        raise NotImplementedError

    def list_objects(self, cid: str) -> list[str]:
        raise NotImplementedError

    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def omap_get(self, cid: str, oid: str) -> dict:
        raise NotImplementedError

    def getattr(self, cid: str, oid: str, name: str) -> bytes | None:
        raise NotImplementedError


class _Obj:
    __slots__ = ("data", "omap", "attrs")

    def __init__(self):
        self.data = bytearray()
        self.omap: dict[str, bytes] = {}
        self.attrs: dict[str, bytes] = {}

    def clone(self) -> "_Obj":
        o = _Obj()
        o.data = bytearray(self.data)
        o.omap = dict(self.omap)
        o.attrs = dict(self.attrs)
        return o


class MemStore(ObjectStore):
    """In-memory store (src/os/memstore/MemStore.cc analog)."""

    def __init__(self, path: str = ""):
        self.path = path
        self._colls: dict[str, dict[str, _Obj]] = {}
        from ceph_tpu.common.lockdep import make_lock
        self._lock = make_lock(f"ObjectStore::lock({id(self)})")
        self._mounted = False

    def mkfs(self) -> None:
        self._colls.clear()

    def mount(self) -> None:
        self._mounted = True

    def umount(self) -> None:
        self._mounted = False

    # -- transactions ---------------------------------------------------------

    def queue_transactions(self, txns, on_commit=None) -> None:
        # commit span on the calling op's trace (no-op when untraced)
        from ceph_tpu.common import tracing
        with tracing.span("objectstore commit", daemon="objectstore",
                          txns=len(txns)):
            with self._lock:
                for t in txns:
                    self._apply(t)
        if on_commit:
            on_commit()

    def _apply(self, t: Transaction) -> None:
        for op in t.ops:
            self._apply_op(op)

    def _apply_op(self, op) -> None:
        c = self._colls
        if op.op == OP_MKCOLL:
            c.setdefault(op.cid, {})
            return
        if op.op == OP_RMCOLL:
            c.pop(op.cid, None)
            return
        coll = c.get(op.cid)
        if coll is None:
            raise KeyError(f"no collection {op.cid!r}")
        if op.op == OP_TOUCH:
            coll.setdefault(op.oid, _Obj())
        elif op.op == OP_WRITE:
            o = coll.setdefault(op.oid, _Obj())
            end = op.offset + len(op.data)
            if len(o.data) < end:
                o.data.extend(b"\x00" * (end - len(o.data)))
            o.data[op.offset:end] = op.data
        elif op.op == OP_ZERO:
            o = coll.setdefault(op.oid, _Obj())
            end = op.offset + op.length
            if len(o.data) < end:
                o.data.extend(b"\x00" * (end - len(o.data)))
            o.data[op.offset:end] = b"\x00" * op.length
        elif op.op == OP_TRUNCATE:
            o = coll.setdefault(op.oid, _Obj())
            if op.length < len(o.data):
                del o.data[op.length:]
            else:
                o.data.extend(b"\x00" * (op.length - len(o.data)))
        elif op.op == OP_REMOVE:
            coll.pop(op.oid, None)
        elif op.op == OP_OMAP_SETKEYS:
            coll.setdefault(op.oid, _Obj()).omap.update(op.keys)
        elif op.op == OP_OMAP_RMKEYS:
            o = coll.setdefault(op.oid, _Obj())
            for k in op.rmkeys:
                o.omap.pop(k, None)
        elif op.op == OP_CLONE:
            src = coll.get(op.oid)
            if src is not None:
                coll[op.dest] = src.clone()
        elif op.op == OP_SETATTR:
            coll.setdefault(op.oid, _Obj()).attrs[op.name] = op.data
        elif op.op == OP_COLL_MOVE:
            dest = c.get(op.dest)
            if dest is None:
                raise KeyError(f"no collection {op.dest!r}")
            o = coll.pop(op.oid, None)
            if o is not None:
                dest[op.oid] = o
        else:
            raise ValueError(f"unknown transaction op {op.op}")

    # -- reads ----------------------------------------------------------------

    def _get(self, cid: str, oid: str) -> _Obj:
        with self._lock:
            coll = self._colls.get(cid)
            if coll is None:
                raise KeyError(f"no collection {cid!r}")
            o = coll.get(oid)
            if o is None:
                raise KeyError(f"no object {cid}/{oid}")
            return o

    def read(self, cid, oid, offset=0, length=None) -> bytes:
        o = self._get(cid, oid)
        with self._lock:
            if length is None:
                return bytes(o.data[offset:])
            return bytes(o.data[offset:offset + length])

    def stat(self, cid, oid) -> dict:
        o = self._get(cid, oid)
        with self._lock:
            return {"size": len(o.data), "omap_keys": len(o.omap)}

    def exists(self, cid, oid) -> bool:
        with self._lock:
            return oid in self._colls.get(cid, {})

    def list_objects(self, cid) -> list[str]:
        with self._lock:
            if cid not in self._colls:
                raise KeyError(f"no collection {cid!r}")
            return sorted(self._colls[cid])

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._colls)

    def omap_get(self, cid, oid) -> dict:
        o = self._get(cid, oid)
        with self._lock:
            return dict(o.omap)

    def getattr(self, cid, oid, name) -> bytes | None:
        o = self._get(cid, oid)
        with self._lock:
            return o.attrs.get(name)


_JHDR = struct.Struct("<II")  # length, crc32


class FileStore(MemStore):
    """Durable store: state lives in memory (indexes and small objects are a
    Python dict, like MemStore) and every transaction is appended to a
    crc-framed journal before ack (FileJournal analog); mount replays the
    journal over the last checkpoint; checkpoint() compacts.

    Layout under path/: journal (frames), checkpoint (full-state dump).
    """

    def __init__(self, path: str):
        super().__init__(path)
        self._journal_f = None
        self._journal_path = os.path.join(path, "journal")
        self._checkpoint_path = os.path.join(path, "checkpoint")

    def mkfs(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        for p in (self._journal_path, self._checkpoint_path):
            if os.path.exists(p):
                os.unlink(p)
        super().mkfs()

    def mkfs_if_needed(self) -> None:
        if not (os.path.exists(self._journal_path)
                or os.path.exists(self._checkpoint_path)):
            self.mkfs()

    def mount(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        self._colls.clear()
        if os.path.exists(self._checkpoint_path):
            self._load_checkpoint()
        if os.path.exists(self._journal_path):
            self._replay_journal()
        self._journal_f = open(self._journal_path, "ab")
        self._mounted = True

    def umount(self) -> None:
        if self._journal_f:
            self._journal_f.flush()
            os.fsync(self._journal_f.fileno())
            self._journal_f.close()
            self._journal_f = None
        self._mounted = False

    def queue_transactions(self, txns, on_commit=None) -> None:
        from ceph_tpu.common import tracing
        frames = []
        for t in txns:
            blob = t.encode()
            frames.append(_JHDR.pack(len(blob), zlib.crc32(blob)) + blob)
        with tracing.span("objectstore commit", daemon="objectstore",
                          txns=len(txns)):
            with self._lock:
                assert self._journal_f is not None, "not mounted"
                self._journal_f.write(b"".join(frames))
                self._journal_f.flush()
                os.fsync(self._journal_f.fileno())  # durability point
                for t in txns:
                    self._apply(t)
        if on_commit:
            on_commit()

    def checkpoint(self) -> None:
        """Dump full state and truncate the journal (journal compaction)."""
        from ceph_tpu.msg.encoding import Encoder
        enc = Encoder()

        def enc_obj(e, o: _Obj):
            e.bytes(bytes(o.data))
            e.map(o.omap, lambda e2, k: e2.str(k), lambda e2, v: e2.bytes(v))
            e.map(o.attrs, lambda e2, k: e2.str(k), lambda e2, v: e2.bytes(v))

        with self._lock:
            enc.map(self._colls, lambda e, k: e.str(k),
                    lambda e, coll: e.map(coll, lambda e2, k: e2.str(k),
                                          enc_obj))
            tmp = self._checkpoint_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(enc.tobytes())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._checkpoint_path)
            self._journal_f.close()
            self._journal_f = open(self._journal_path, "wb")

    def _load_checkpoint(self) -> None:
        from ceph_tpu.msg.encoding import Decoder
        with open(self._checkpoint_path, "rb") as f:
            dec = Decoder(f.read())

        def dec_obj(d) -> _Obj:
            o = _Obj()
            o.data = bytearray(d.bytes())
            o.omap = d.map(lambda d2: d2.str(), lambda d2: d2.bytes())
            o.attrs = d.map(lambda d2: d2.str(), lambda d2: d2.bytes())
            return o

        self._colls = dec.map(
            lambda d: d.str(),
            lambda d: d.map(lambda d2: d2.str(), dec_obj))

    def _replay_journal(self) -> None:
        with open(self._journal_path, "rb") as f:
            data = f.read()
        off = 0
        while off + _JHDR.size <= len(data):
            length, crc = _JHDR.unpack_from(data, off)
            start = off + _JHDR.size
            if start + length > len(data):
                break  # torn tail write: stop replay (journal semantics)
            blob = data[start:start + length]
            if zlib.crc32(blob) != crc:
                break
            self._apply(Transaction.decode(blob))
            off = start + length


def create(store_type: str, path: str = "", ctx=None) -> ObjectStore:
    """ObjectStore::create (os/ObjectStore.h:85) analog.  ``ctx``
    (optional CephTpuContext) lets bluestore batch its write-time
    checksums through the device dispatch engines and read conf knobs;
    the other backends ignore it."""
    if store_type == "memstore":
        return MemStore(path)
    if store_type == "filestore":
        return FileStore(path)
    if store_type == "bluestore":
        from .bluestore import BlueStoreLite
        return BlueStoreLite(path, ctx=ctx)
    raise ValueError(f"unknown objectstore type {store_type!r}")
