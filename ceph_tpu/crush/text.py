"""crushtool text-map grammar: compile and decompile
(src/crush/CrushCompiler.cc compile/decompile).

The text format is the operator-facing surface of CRUSH — `crushtool
-d` emits it, admins edit it, `crushtool -c` compiles it back.  It
carries the names the binary map doesn't: device names, type names,
bucket names, rule names, device classes.  Those live here in
CrushNames (the CrushWrapper type_map/name_map/rule_name_map analog)
so the core CrushMap stays the pure algorithmic structure the mapper
and kernels consume.

Grammar subset (matching what the reference emits for real clusters):

    tunable <name> <value>
    device <num> osd.<num> [class <class>]
    type <id> <name>
    <typename> <bucketname> {
        id <negative-int>
        alg uniform|list|tree|straw|straw2
        hash 0
        item <name> weight <float>
    }
    rule <name> {
        id <int>                      # also: ruleset <int>
        type replicated|erasure
        min_size <int>
        max_size <int>
        step take <bucketname>
        step set_choose_tries <n>     # and the other set_* steps
        step choose|chooseleaf firstn|indep <n> type <typename>
        step emit
    }

Class-qualified `step take <bucket> class <c>` requires the shadow
hierarchy; it is rejected with a clear error rather than silently
mis-compiled.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .builder import make_bucket
from .types import (
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM, RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP, RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP,
    RULE_EMIT, RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    RULE_SET_CHOOSE_LOCAL_TRIES, RULE_SET_CHOOSE_TRIES,
    RULE_SET_CHOOSELEAF_STABLE, RULE_SET_CHOOSELEAF_TRIES,
    RULE_SET_CHOOSELEAF_VARY_R, RULE_TAKE, CrushMap, Rule, RuleStep,
    Tunables)

_ALG_NAMES = {CRUSH_BUCKET_UNIFORM: "uniform", CRUSH_BUCKET_LIST: "list",
              CRUSH_BUCKET_TREE: "tree", CRUSH_BUCKET_STRAW: "straw",
              CRUSH_BUCKET_STRAW2: "straw2"}
_ALG_IDS = {v: k for k, v in _ALG_NAMES.items()}

_SET_STEPS = {
    "set_choose_tries": RULE_SET_CHOOSE_TRIES,
    "set_chooseleaf_tries": RULE_SET_CHOOSELEAF_TRIES,
    "set_choose_local_tries": RULE_SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries": RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_vary_r": RULE_SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": RULE_SET_CHOOSELEAF_STABLE,
}
_SET_NAMES = {v: k for k, v in _SET_STEPS.items()}

_RULE_TYPE_NAMES = {1: "replicated", 3: "erasure"}
_RULE_TYPE_IDS = {v: k for k, v in _RULE_TYPE_NAMES.items()}

#: tunable fields the text format carries (CrushCompiler.cc:44-57)
_TUNABLES = ("choose_local_tries", "choose_local_fallback_tries",
             "choose_total_tries", "chooseleaf_descend_once",
             "chooseleaf_vary_r", "chooseleaf_stable",
             "straw_calc_version")


@dataclass
class CrushNames:
    """The naming side-tables (CrushWrapper type_map / name_map /
    rule_name_map / class_map)."""

    types: dict[int, str] = field(default_factory=dict)
    items: dict[int, str] = field(default_factory=dict)   # devices+buckets
    rules: dict[int, str] = field(default_factory=dict)
    classes: dict[int, str] = field(default_factory=dict)  # device -> class

    def item_id(self, name: str) -> int:
        for i, n in self.items.items():
            if n == name:
                return i
        raise ValueError(f"unknown item {name!r}")

    def type_id(self, name: str) -> int:
        for i, n in self.types.items():
            if n == name:
                return i
        raise ValueError(f"unknown type {name!r}")


class CompileError(ValueError):
    pass


def _tokens(text: str):
    """Token stream with '{' / '}' as their own tokens, comments dropped."""
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.split("#", 1)[0]
        for tok in line.replace("{", " { ").replace("}", " } ").split():
            yield lineno, tok


def compile_text(text: str) -> tuple[CrushMap, CrushNames]:
    """CrushCompiler::compile — text -> (CrushMap, CrushNames)."""
    m = CrushMap()
    names = CrushNames()
    toks = list(_tokens(text))
    pos = 0

    def peek():
        return toks[pos][1] if pos < len(toks) else None

    def take(expect: str | None = None) -> str:
        nonlocal pos
        if pos >= len(toks):
            raise CompileError("unexpected end of input")
        lineno, tok = toks[pos]
        pos += 1
        if expect is not None and tok != expect:
            raise CompileError(f"line {lineno}: expected {expect!r}, "
                               f"got {tok!r}")
        return tok

    def take_int() -> int:
        tok = take()
        try:
            return int(tok)
        except ValueError:
            raise CompileError(f"expected integer, got {tok!r}")

    #: bucket blocks parsed but not yet built (children may come later
    #: in any order; the reference requires children first, we don't)
    pending: list[dict] = []

    while pos < len(toks):
        word = take()
        if word == "tunable":
            name, val = take(), take_int()
            if name not in _TUNABLES:
                raise CompileError(f"unknown tunable {name!r}")
            setattr(m.tunables, name, val)
        elif word == "device":
            num = take_int()
            dname = take()
            names.items[num] = dname
            m.max_devices = max(m.max_devices, num + 1)
            if peek() == "class":
                take()
                names.classes[num] = take()
        elif word == "type":
            tid = take_int()
            names.types[tid] = take()
        elif word == "rule":
            rname = take()
            take("{")
            rid = None
            rtype, mn, mx = 1, 1, 10
            steps: list[RuleStep] = []
            while peek() != "}":
                kw = take()
                if kw in ("id", "ruleset"):
                    rid = take_int()
                elif kw == "type":
                    t = take()
                    if t not in _RULE_TYPE_IDS:
                        raise CompileError(f"unknown rule type {t!r}")
                    rtype = _RULE_TYPE_IDS[t]
                elif kw == "min_size":
                    mn = take_int()
                elif kw == "max_size":
                    mx = take_int()
                elif kw == "step":
                    op = take()
                    if op == "take":
                        target = take()
                        if peek() == "class":
                            take()
                            cname = take()
                            steps.append(RuleStep(
                                RULE_TAKE,
                                ("__name_class__", target, cname)))
                        else:
                            steps.append(RuleStep(RULE_TAKE,
                                                  ("__name__", target)))
                    elif op == "emit":
                        steps.append(RuleStep(RULE_EMIT))
                    elif op in _SET_STEPS:
                        steps.append(RuleStep(_SET_STEPS[op], take_int()))
                    elif op in ("choose", "chooseleaf"):
                        mode = take()
                        n = take_int()
                        take("type")
                        tname = take()
                        opid = {
                            ("choose", "firstn"): RULE_CHOOSE_FIRSTN,
                            ("choose", "indep"): RULE_CHOOSE_INDEP,
                            ("chooseleaf", "firstn"):
                                RULE_CHOOSELEAF_FIRSTN,
                            ("chooseleaf", "indep"):
                                RULE_CHOOSELEAF_INDEP,
                        }.get((op, mode))
                        if opid is None:
                            raise CompileError(
                                f"unknown step {op} {mode}")
                        steps.append(RuleStep(opid, n,
                                              ("__type__", tname)))
                    else:
                        raise CompileError(f"unknown step {op!r}")
                else:
                    raise CompileError(f"unknown rule keyword {kw!r}")
            take("}")
            if rid is None:
                rid = len(m.rules)
            while len(m.rules) <= rid:
                m.rules.append(None)
            if m.rules[rid] is not None:
                raise CompileError(f"duplicate rule id {rid}")
            m.rules[rid] = Rule(ruleset=rid, type=rtype, min_size=mn,
                                max_size=mx, steps=steps)
            names.rules[rid] = rname
        else:
            # bucket block: <typename> <bucketname> { ... }
            tname = word
            bname = take()
            take("{")
            spec = {"type_name": tname, "name": bname, "id": None,
                    "alg": "straw2", "hash": 0, "items": []}
            while peek() != "}":
                kw = take()
                if kw == "id":
                    spec["id"] = take_int()
                    if peek() == "class":   # shadow-bucket id line
                        take()
                        take()              # class name; shadow ignored
                elif kw == "alg":
                    spec["alg"] = take()
                elif kw == "hash":
                    spec["hash"] = take_int()
                elif kw == "weight":        # bucket total; recomputed
                    take()
                elif kw == "item":
                    iname = take()
                    w = 0x10000
                    while peek() in ("weight", "pos"):
                        k = take()
                        v = take()
                        if k == "weight":
                            w = int(round(float(v) * 0x10000))
                    spec["items"].append((iname, w))
                else:
                    raise CompileError(f"unknown bucket keyword {kw!r}")
            take("}")
            if spec["alg"] not in _ALG_IDS:
                raise CompileError(f"unknown alg {spec['alg']!r}")
            if spec["id"] is not None and spec["id"] >= 0:
                raise CompileError(
                    f"bucket {bname!r}: id must be negative "
                    f"(got {spec['id']})")
            if any(s["name"] == bname for s in pending) \
                    or bname in names.items.values():
                raise CompileError(f"duplicate name {bname!r}")
            pending.append(spec)

    # build buckets children-first so list/tree/straw derived tables see
    # final child ids regardless of declaration order
    by_name = {s["name"]: s for s in pending}
    built: dict[str, int] = {}

    def build(spec) -> int:
        if spec["name"] in built:
            return built[spec["name"]]
        items, weights = [], []
        for iname, w in spec["items"]:
            if iname in by_name:
                items.append(build(by_name[iname]))
            else:
                items.append(names.item_id(iname))
            weights.append(w)
        bid = spec["id"] if spec["id"] is not None else m.next_bucket_id()
        b = make_bucket(bid, _ALG_IDS[spec["alg"]],
                        names.type_id(spec["type_name"]), items, weights)
        b.hash = spec["hash"]
        m.add_bucket(b)
        names.items[bid] = spec["name"]
        built[spec["name"]] = bid
        return bid

    for spec in pending:
        build(spec)

    # device classes: build the shadow hierarchies (populate_classes)
    # so class-qualified takes resolve to their shadow roots
    if names.classes:
        from .classes import populate_classes
        populate_classes(m, dict(names.classes))

    # resolve deferred name references in rule steps
    for r in m.rules:
        if r is None:
            continue
        for s in r.steps:
            if isinstance(s.arg1, tuple) and s.arg1[0] == "__name__":
                s.arg1 = names.item_id(s.arg1[1])
            elif isinstance(s.arg1, tuple) \
                    and s.arg1[0] == "__name_class__":
                orig = names.item_id(s.arg1[1])
                shadow = m.class_bucket.get((orig, s.arg1[2]))
                if shadow is None:
                    raise CompileError(
                        f"no devices of class {s.arg1[2]!r} under "
                        f"{s.arg1[1]!r}")
                s.arg1 = shadow
            if isinstance(s.arg2, tuple) and s.arg2[0] == "__type__":
                s.arg2 = names.type_id(s.arg2[1])
    return m, names


def _wfmt(w: int) -> str:
    return f"{w / 0x10000:.5f}"


def item_name(names: CrushNames, i: int) -> str:
    """Name for a device/bucket id, with crushtool's synthesized
    defaults (osd.N / bucketN) when the table has no entry."""
    if i in names.items:
        return names.items[i]
    return f"osd.{i}" if i >= 0 else f"bucket{-1 - i}"


def type_name(names: CrushNames, t: int) -> str:
    return names.types.get(t, f"type{t}")


def decompile(m: CrushMap, names: CrushNames | None = None) -> str:
    """CrushCompiler::decompile — (CrushMap, names) -> text.  Without
    names, synthesizes crushtool's defaults (osd.N, bucketN, typeN)."""
    names = names or CrushNames()

    def iname(i: int) -> str:
        return item_name(names, i)

    def tname(t: int) -> str:
        return type_name(names, t)

    out = ["# begin crush map"]
    for f in _TUNABLES:
        out.append(f"tunable {f} {getattr(m.tunables, f)}")
    out.append("\n# devices")
    for d in range(m.max_devices):
        line = f"device {d} {iname(d)}"
        if d in names.classes:
            line += f" class {names.classes[d]}"
        out.append(line)
    out.append("\n# types")
    tids = set(names.types) | {b.type for b in m.buckets
                               if b is not None} | {0}
    for t in sorted(tids):
        out.append(f"type {t} {tname(t)}")
    out.append("\n# buckets")
    # children before parents (the compiler requires it); shadow buckets
    # (device-class clones) are not listed — crushtool hides them and
    # the compiler rebuilds them from the device class tags
    from .classes import shadow_to_class
    shadows = shadow_to_class(m)
    emitted: set[int] = set(shadows)

    def emit_bucket(b) -> None:
        if b is None or b.id in emitted:
            return
        emitted.add(b.id)
        for it in b.items:
            if it < 0:
                emit_bucket(m.bucket(it))
        out.append(f"{tname(b.type)} {iname(b.id)} {{")
        out.append(f"\tid {b.id}")
        out.append(f"\talg {_ALG_NAMES[b.alg]}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for k, it in enumerate(b.items):
            if b.alg == CRUSH_BUCKET_UNIFORM:
                w = b.item_weight
            else:
                w = b.item_weights[k] if k < len(b.item_weights) else 0
            out.append(f"\titem {iname(it)} weight {_wfmt(w)}")
        out.append("}")

    for b in m.buckets:
        emit_bucket(b)
    out.append("\n# rules")
    for rid, r in enumerate(m.rules):
        if r is None:
            continue
        out.append(f"rule {names.rules.get(rid, f'rule{rid}')} {{")
        out.append(f"\tid {rid}")
        out.append(f"\ttype {_RULE_TYPE_NAMES.get(r.type, 'replicated')}")
        out.append(f"\tmin_size {r.min_size}")
        out.append(f"\tmax_size {r.max_size}")
        for s in r.steps:
            if s.op == RULE_TAKE:
                if s.arg1 in shadows:
                    orig, cname = shadows[s.arg1]
                    out.append(f"\tstep take {iname(orig)} "
                               f"class {cname}")
                else:
                    out.append(f"\tstep take {iname(s.arg1)}")
            elif s.op == RULE_EMIT:
                out.append("\tstep emit")
            elif s.op in _SET_NAMES:
                out.append(f"\tstep {_SET_NAMES[s.op]} {s.arg1}")
            elif s.op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP,
                          RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP):
                op = "choose" if s.op in (RULE_CHOOSE_FIRSTN,
                                          RULE_CHOOSE_INDEP) \
                    else "chooseleaf"
                mode = "firstn" if s.op in (RULE_CHOOSE_FIRSTN,
                                            RULE_CHOOSELEAF_FIRSTN) \
                    else "indep"
                out.append(f"\tstep {op} {mode} {s.arg1} "
                           f"type {tname(s.arg2)}")
            else:
                out.append(f"\t# unsupported step op {s.op}")
        out.append("}")
    out.append("\n# end crush map")
    return "\n".join(out) + "\n"
