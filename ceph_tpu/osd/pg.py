"""Per-PG consistency machinery: ordered op log, missing sets, peering.

This is the TPU-repo analog of the reference's correctness backbone
(src/osd/PGLog.h:549 ordered log + missing sets, src/osd/PG.h:1958 peering
statechart, src/osd/PG.cc merge_log / proc_replica_log).  The design keeps the
reference's *semantics* — every mutation appends a (epoch, seq) versioned log
entry, replicas converge by adopting the authoritative log and recovering the
objects they are missing — while collapsing the boost::statechart into a small
explicit state machine suited to this codebase's thread-per-daemon runtime:

    inactive -> getinfo -> getlog -> recovering -> active

Logs are untrimmed at this scale (tail == (0,0)), which gives a useful
invariant: any object referenced by a divergent entry with a non-zero
prior_version also appears in the authoritative log (shared history), so
divergent-entry rollback never needs missing-from-log reconstruction
(the hard cases of PGLog::_merge_object_divergent_entries).

Versions are (epoch, seq) tuples compared lexicographically, exactly
eversion_t (src/osd/osd_types.h).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.msg.encoding import Decoder, Encoder

# eversion_t: (epoch, seq), lexicographic order
EVERSION_ZERO = (0, 0)

# log entry ops (pg_log_entry_t::Op, src/osd/osd_types.h)
LOG_MODIFY = 1
LOG_DELETE = 2

# PG states (simplified peering statechart)
STATE_INACTIVE = "inactive"
STATE_GETINFO = "getinfo"
STATE_GETLOG = "getlog"
STATE_RECOVERING = "recovering"
STATE_ACTIVE = "active"
STATE_REPLICA = "replica"


def enc_ev(e: Encoder, v: tuple[int, int]) -> None:
    e.u32(v[0]).u64(v[1])


def dec_ev(d: Decoder) -> tuple[int, int]:
    return (d.u32(), d.u64())


@dataclass
class LogEntry:
    """One mutation in a PG's ordered history (pg_log_entry_t)."""

    op: int
    oid: str
    version: tuple[int, int]
    prior_version: tuple[int, int] = EVERSION_ZERO
    reqid: tuple[int, int] = (0, 0)

    def is_delete(self) -> bool:
        return self.op == LOG_DELETE

    def encode(self, e: Encoder) -> None:
        e.u8(self.op)
        e.str(self.oid)
        enc_ev(e, self.version)
        enc_ev(e, self.prior_version)
        e.u64(self.reqid[0]).u64(self.reqid[1])

    @staticmethod
    def decode(d: Decoder) -> "LogEntry":
        return LogEntry(op=d.u8(), oid=d.str(), version=dec_ev(d),
                        prior_version=dec_ev(d), reqid=(d.u64(), d.u64()))


@dataclass
class PGInfo:
    """Summary a peer advertises during peering (pg_info_t).

    past_up records prior up sets (PastIntervals, src/osd/osd_types.h):
    after a remap, EC shard chunks still live on their *old* positional
    holders, and a freshly-booted primary can only learn those intervals
    from its peers' infos — exactly why the reference exchanges
    past_intervals during peering.
    """

    pgid: tuple[int, int] = (0, 0)
    last_update: tuple[int, int] = EVERSION_ZERO
    last_complete: tuple[int, int] = EVERSION_ZERO
    last_epoch_started: int = 0
    past_up: list[list[int]] = field(default_factory=list)

    def encode(self, e: Encoder) -> None:
        e.s64(self.pgid[0]).u32(self.pgid[1])
        enc_ev(e, self.last_update)
        enc_ev(e, self.last_complete)
        e.u32(self.last_epoch_started)
        e.list(self.past_up,
               lambda e2, iv: e2.list(iv, lambda e3, o: e3.s32(o)))

    @staticmethod
    def decode(d: Decoder) -> "PGInfo":
        return PGInfo(pgid=(d.s64(), d.u32()), last_update=dec_ev(d),
                      last_complete=dec_ev(d), last_epoch_started=d.u32(),
                      past_up=d.list(
                          lambda d2: d2.list(lambda d3: d3.s32())))


@dataclass
class MissingItem:
    need: tuple[int, int]
    have: tuple[int, int] = EVERSION_ZERO


class PGLog:
    """Ordered, indexed per-PG op log (src/osd/PGLog.h IndexedLog)."""

    def __init__(self):
        self.entries: list[LogEntry] = []
        self.head: tuple[int, int] = EVERSION_ZERO
        #: oid -> latest LogEntry for that object
        self.index: dict[str, LogEntry] = {}
        #: reqid -> version (dup op detection on client resend)
        self.reqids: dict[tuple[int, int], tuple[int, int]] = {}

    def __len__(self) -> int:
        return len(self.entries)

    def append(self, entry: LogEntry) -> None:
        assert entry.version > self.head, (entry.version, self.head)
        self.entries.append(entry)
        self.head = entry.version
        self.index[entry.oid] = entry
        if entry.reqid != (0, 0):
            self.reqids[entry.reqid] = entry.version

    def entries_since(self, v: tuple[int, int]) -> list[LogEntry]:
        # entries are version-ordered; binary search would do, linear is fine
        return [e for e in self.entries if e.version > v]

    def latest_since(self, v: tuple[int, int]) -> dict[str, LogEntry]:
        """oid -> newest entry newer than v (the missing-set seed)."""
        out: dict[str, LogEntry] = {}
        for e in self.entries_since(v):
            out[e.oid] = e
        return out

    def has_reqid(self, reqid) -> bool:
        return reqid in self.reqids

    def rewind(self, to: tuple[int, int]) -> list[LogEntry]:
        """Drop entries newer than `to`; returns them oldest-first
        (PGLog::rewind_divergent_log)."""
        divergent = [e for e in self.entries if e.version > to]
        if divergent:
            self.entries = [e for e in self.entries if e.version <= to]
            self.head = self.entries[-1].version if self.entries \
                else EVERSION_ZERO
            self._reindex()
        return divergent

    def _reindex(self) -> None:
        self.index = {}
        self.reqids = {}
        for e in self.entries:
            self.index[e.oid] = e
            if e.reqid != (0, 0):
                self.reqids[e.reqid] = e.version

    def copy_from(self, entries: list[LogEntry]) -> None:
        self.entries = list(entries)
        self.head = entries[-1].version if entries else EVERSION_ZERO
        self._reindex()

    def encode(self, e: Encoder) -> None:
        e.list(self.entries, lambda e2, ent: ent.encode(e2))

    @staticmethod
    def decode(d: Decoder) -> "PGLog":
        log = PGLog()
        log.copy_from(d.list(LogEntry.decode))
        return log


@dataclass
class PeerState:
    """What the primary knows about one peer (peer_info / peer_missing)."""

    info: PGInfo | None = None
    missing: dict[str, MissingItem] = field(default_factory=dict)


class PG:
    """One placement group's in-memory state on one OSD.

    Collapses PG + PrimaryLogPG responsibilities relevant at this scale:
    peering bookkeeping, the op log, missing-set recovery tracking, and
    op queuing while inactive.
    """

    PGMETA = "_pgmeta_"

    def __init__(self, pgid: tuple[int, int]):
        self.pgid = pgid
        self.info = PGInfo(pgid=pgid)
        self.log = PGLog()
        self.state = STATE_INACTIVE
        #: epoch the current peering round started (interval guard)
        self.peering_epoch = 0
        self.up: list[int] = []
        self.primary: int = -1
        #: my own missing objects (oid -> MissingItem)
        self.missing: dict[str, MissingItem] = {}
        #: primary only: per-peer peering state
        self.peers: dict[int, PeerState] = {}
        #: primary only: infos from STRAY osds — holders outside the up
        #: set that announced data via notify (PG stray semantics).  A
        #: remap with a disjoint new up set (e.g. children after
        #: pgp_num growth) recovers from these.
        self.strays: dict[int, "PGInfo"] = {}
        #: ops queued while not active / while an object recovers
        self.waiting_for_active: list = []
        self.waiting_for_missing: dict[str, list] = {}
        #: objects currently being recovered: oid -> pull-issue timestamp
        #: (lets the tick re-issue pulls that were lost in flight)
        self.recovering: dict[str, float] = {}
        #: objects with an EC read-modify-write in flight, oid -> the
        #: owning gather id.  Ownership keeps an orphaned pre-peering
        #: gather from releasing or bypassing a newer gather's gate.
        #: Later writes to a gated object do NOT serialize on it: they
        #: join the gather state's "queue" and overlay in order onto its
        #: projected content (the ExtentCache pipeline reduced,
        #: src/osd/ExtentCache.h:1-491)
        self.rmw: dict[str, tuple] = {}
        #: when the current peering round started (tick watchdog)
        self.peering_started = 0.0
        self.next_seq = 0
        #: pool pg_num this PG's collection was last created/split at —
        #: persisted in pgmeta ("pg_num"); drives boot-time splits
        self.split_num = 0

    # -- version allocation (primary) ------------------------------------

    def next_version(self, epoch: int) -> tuple[int, int]:
        self.next_seq = max(self.next_seq, self.log.head[1]) + 1
        return (epoch, self.next_seq)

    # -- log application --------------------------------------------------

    def record(self, entry: LogEntry) -> None:
        """Append to the log and advance info (PG::add_log_entry)."""
        self.log.append(entry)
        self.info.last_update = entry.version
        if not self.missing:
            self.info.last_complete = entry.version

    def complete_to(self) -> tuple[int, int]:
        """last_complete given the current missing set."""
        if not self.missing:
            return self.info.last_update
        oldest_need = min(m.need for m in self.missing.values())
        # complete through the entry just before the oldest need
        best = EVERSION_ZERO
        for e in self.log.entries:
            if e.version < oldest_need:
                best = e.version
            else:
                break
        return best

    # -- merge (replica receiving authoritative log, or primary adopting
    #    a peer's better log): PGLog::merge_log semantics -----------------

    def merge_log(self, auth_entries: list[LogEntry],
                  local_has) -> tuple[list[str], list[str]]:
        """Adopt `auth_entries` as the authoritative history.

        `local_has(oid) -> version|None` reports what version of an object
        this OSD's store holds (from the per-object version attr).

        Returns (to_remove, to_recover): objects whose local copy must be
        deleted outright, and objects now in the missing set.
        """
        auth = PGLog()
        auth.copy_from(auth_entries)
        to_remove: list[str] = []

        # 1. find the divergence point: the last entry the two histories
        # share.  A revived primary's divergent entries can carry *lower*
        # versions than the auth head (its epoch predates the new
        # primary's), so comparing heads is not enough — walk the shared
        # prefix (PGLog::merge_log's log.head vs olog divergence scan).
        mine = self.log.entries
        i = 0
        while (i < len(mine) and i < len(auth.entries)
               and mine[i].version == auth.entries[i].version):
            i += 1
        div_point = mine[i - 1].version if i > 0 else EVERSION_ZERO

        # 2. rollback my entries past the divergence point
        divergent = self.log.rewind(div_point)
        seen: set[str] = set()
        for e in reversed(divergent):   # newest first, once per oid
            if e.oid in seen:
                continue
            seen.add(e.oid)
            ae = auth.index.get(e.oid)
            if ae is None or ae.is_delete():
                # object exists only on the divergent branch (untrimmed-log
                # invariant: shared history would appear in auth)
                to_remove.append(e.oid)
                self.missing.pop(e.oid, None)
            else:
                self.missing[e.oid] = MissingItem(need=ae.version)

        # 3. adopt entries newer than my (rewound) head
        for oid, ae in auth.latest_since(self.log.head).items():
            if ae.is_delete():
                self.missing.pop(oid, None)
                to_remove.append(oid)
                continue
            have = local_has(oid)
            if have == ae.version:
                self.missing.pop(oid, None)
                continue
            self.missing[oid] = MissingItem(
                need=ae.version, have=have or EVERSION_ZERO)

        self.log = auth
        self.info.last_update = auth.head
        self.info.last_complete = self.complete_to()
        to_recover = sorted(self.missing)
        return to_remove, to_recover

    def peer_missing_from_log(self, peer_last_update) -> dict[str, MissingItem]:
        """Primary: what a peer at `peer_last_update` is missing
        (PGLog::proc_replica_log, simplified: peer logs never run ahead of
        the authoritative log once merge_log pruned them)."""
        out: dict[str, MissingItem] = {}
        for oid, e in self.log.latest_since(peer_last_update).items():
            if not e.is_delete():
                out[oid] = MissingItem(need=e.version)
        return out

    # -- persistence -------------------------------------------------------

    @staticmethod
    def log_key(v: tuple[int, int]) -> str:
        return f"log.{v[0]:010d}.{v[1]:020d}"

    def encode_info(self) -> bytes:
        e = Encoder()
        self.info.encode(e)
        return e.tobytes()

    def encode_missing(self) -> bytes:
        """Persisted with the merged log: an OSD that crashes mid-recovery
        must not restart claiming a complete history (its info already
        advertises the merged last_update)."""
        e = Encoder()
        e.map(self.missing,
              lambda e2, k: e2.str(k),
              lambda e2, m: (enc_ev(e2, m.need), enc_ev(e2, m.have)))
        return e.tobytes()

    def decode_missing(self, blob: bytes) -> None:
        d = Decoder(blob)
        self.missing = d.map(
            lambda d2: d2.str(),
            lambda d2: MissingItem(need=dec_ev(d2), have=dec_ev(d2)))

    @staticmethod
    def decode_info(blob: bytes) -> PGInfo:
        return PGInfo.decode(Decoder(blob))

    @staticmethod
    def encode_entry(entry: LogEntry) -> bytes:
        e = Encoder()
        entry.encode(e)
        return e.tobytes()

    @staticmethod
    def decode_entry(blob: bytes) -> LogEntry:
        return LogEntry.decode(Decoder(blob))
