"""ObjectStore micro-benchmark — the fio ObjectStore engine analog
(src/test/fio/fio_ceph_objectstore.cc): drive a store backend directly
(no cluster) with write/read workloads and report IOPS + MB/s.

Usage: python -m ceph_tpu.tools.objectstore_bench --type bluestore \
          --path DIR [--objects N] [--size BYTES] [--threads T]
"""

from __future__ import annotations

import argparse
import json
import threading
import time

from ceph_tpu.objectstore import Transaction, create_objectstore


def run(store, n_objects: int, obj_size: int, n_threads: int) -> dict:
    cid = "bench.0"
    if cid not in store.list_collections():
        store.apply_transaction(Transaction().create_collection(cid))
    payload = (b"\xa5" * obj_size)
    results = {}

    def phase(name, fn, bytes_per_op=None):
        per_op = obj_size if bytes_per_op is None else bytes_per_op
        errs = [0] * n_threads

        def worker(t):
            for i in range(t, n_objects, n_threads):
                try:
                    fn(i)
                except Exception:
                    errs[t] += 1

        t0 = time.perf_counter()
        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.perf_counter() - t0
        results[name] = {
            "seconds": round(dt, 3),
            "iops": round(n_objects / dt, 1),
            "mb_s": round(n_objects * per_op / dt / 1e6, 2),
            "errors": sum(errs),
        }

    phase("write", lambda i: store.apply_transaction(
        Transaction().write(cid, f"o{i}", 0, payload)))
    phase("read", lambda i: store.read(cid, f"o{i}"))
    phase("overwrite", lambda i: store.apply_transaction(
        Transaction().write(cid, f"o{i}", obj_size // 2,
                            payload[:obj_size // 2])),
          bytes_per_op=obj_size // 2)
    # small sub-block overwrites: the deferred-write (WAL) fast path on
    # bluestore — a 512 B patch inside an existing block
    phase("small_overwrite", lambda i: store.apply_transaction(
        Transaction().write(cid, f"o{i}", 1024, payload[:512])),
          bytes_per_op=512)
    phase("delete", lambda i: store.apply_transaction(
        Transaction().remove(cid, f"o{i}")))
    results["config"] = {"objects": n_objects, "size": obj_size,
                         "threads": n_threads}
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="objectstore-bench")
    ap.add_argument("--type", default="bluestore",
                    choices=["memstore", "filestore", "bluestore"])
    ap.add_argument("--path", required=True)
    ap.add_argument("--objects", type=int, default=1024)
    ap.add_argument("--size", type=int, default=65536)
    ap.add_argument("--threads", type=int, default=4)
    args = ap.parse_args(argv)
    store = create_objectstore(args.type, args.path)
    store.mkfs_if_needed()
    store.mount()
    try:
        print(json.dumps(run(store, args.objects, args.size,
                             args.threads)))
        return 0
    finally:
        store.umount()


if __name__ == "__main__":
    raise SystemExit(main())
