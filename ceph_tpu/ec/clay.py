"""CLAY — coupled-layer MSR regenerating code
(src/erasure-code/clay/ErasureCodeClay.cc analog; the reason the plugin
interface carries sub-chunks, ErasureCodeInterface.h:259).

Construction (Clay codes, FAST'18): n = k + m nodes on a q x t grid
(q = m, t = n/q), each chunk split into alpha = q^t sub-chunks indexed
by z in Z_q^t.  A virtual UNCOUPLED code U is MDS per z-plane (an [n,k]
RS codeword across the nodes); the physical chunks C couple sub-chunk
PAIRS across planes with an invertible 2x2 GF(2^8) transform:

    pair of (x, y, z) with x != z_y  is  (z_y, y, z(y->x))
    C1 = U1 + g*U2        C2 = g*U1 + U2        (g = 2; 1+g^2 != 0)
    x == z_y: C = U (fixed points)

Encode treats the m parities as erasures and runs the generic decoder.
Decode walks planes by INTERSECTION SCORE s(z) = |{y : (z_y, y) is
erased}|: in score order, every surviving node's U is computable (its
partner is either surviving, or an erased node in a lower-score plane
already recovered), the plane's RS codeword is then decoded for the
erased nodes, and finally erased C values come back through the pair
transform.

Single-node repair is the headline: only the q^(t-1) planes S =
{z : z_{y0} = x0} are read from each of the d = n-1 helpers — alpha/q
sub-chunks instead of whole chunks, the MSR repair-bandwidth optimum.
On each S-plane the y != y0 rows uncouple internally (their partners
stay inside S), the y0 row's q unknowns fall to the plane's m = q RS
parity equations, and the pair algebra then yields the failed node's
off-S sub-chunks from helper row y0's coupled values.  All transforms
are elementwise table lookups over the sub-chunk byte axis — batched,
vectorized compute, no per-byte loops.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.gf.matrix import gen_cauchy1_matrix
from ceph_tpu.gf.tables import gf_inv, gf_mul, mul_table

from .base import ErasureCode
from .interface import ErasureCodeProfile
from .registry import register

GAMMA = 2


def _mul(coef: int, arr: np.ndarray) -> np.ndarray:
    """scalar * vector over GF(2^8), one table-row gather."""
    return mul_table()[coef][arr]


class ErasureCodeClay(ErasureCode):
    supports_rmw_striping = False

    def __init__(self):
        super().__init__()
        self.q = 0
        self.t = 0

    def _default_k(self) -> int:
        return 4

    def _default_m(self) -> int:
        return 2

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        n = self.k + self.m
        if n % self.m != 0:
            raise ValueError(
                f"clay requires m | (k+m); got k={self.k} m={self.m} "
                f"(the reference shortens instead; not implemented)")
        self.q = self.m
        self.t = n // self.q

    def _build_generator(self) -> np.ndarray:
        return gen_cauchy1_matrix(self.k, self.m)

    # -- geometry -------------------------------------------------------------

    def get_sub_chunk_count(self) -> int:
        return self.q ** self.t

    def get_alignment(self) -> int:
        return self.k * self.get_sub_chunk_count()

    def node_xy(self, i: int) -> tuple[int, int]:
        return i % self.q, i // self.q

    def node_id(self, x: int, y: int) -> int:
        return y * self.q + x

    def _planes(self):
        """All z vectors (alpha of them), as tuples."""
        import itertools
        return list(itertools.product(range(self.q), repeat=self.t))

    @staticmethod
    def _zset(z: tuple, y: int, x: int) -> tuple:
        return z[:y] + (x,) + z[y + 1:]

    # -- pair transforms (vectorized over the sub-chunk byte axis).
    # the forward coupling C1 = U1 ^ g*U2 lives inline in _decode_planes
    # and repair; only the inverse needs a helper.

    @staticmethod
    def _uncouple(c1, c2):
        inv = gf_inv(1 ^ gf_mul(GAMMA, GAMMA))
        u1 = _mul(inv, c1 ^ _mul(GAMMA, c2))
        u2 = _mul(inv, _mul(GAMMA, c1) ^ c2)
        return u1, u2

    # -- the generic layered decoder ------------------------------------------

    def _decode_planes(self, C: dict, erased: list[int]):
        """C: {(node, z): uint8 array} for all surviving nodes and all
        planes.  Returns (U, C) completed for every node and plane
        (ErasureCodeClay recover: intersection-score order)."""
        n = self.k + self.m
        planes = self._planes()
        er = set(erased)
        surv = [i for i in range(n) if i not in er]
        if len(surv) < self.k:
            raise IOError(f"clay cannot decode {sorted(er)}")
        U: dict = {}

        def score(z):
            return sum(1 for y in range(self.t)
                       if self.node_id(z[y], y) in er)

        for z in sorted(planes, key=score):
            # uncouple every surviving node on this plane
            for i in surv:
                x, y = self.node_xy(i)
                if z[y] == x:
                    U[(i, z)] = C[(i, z)]
                    continue
                partner = self.node_id(z[y], y)
                zp = self._zset(z, y, x)
                if partner in er:
                    # partner plane has lower score: its U is recovered
                    U[(i, z)] = C[(i, z)] ^ _mul(GAMMA, U[(partner, zp)])
                else:
                    u1, _u2 = self._uncouple(C[(i, z)], C[(partner, zp)])
                    U[(i, z)] = u1
            # plane RS decode for the erased nodes
            chosen = surv[:self.k]
            arr = np.stack([U[(i, z)] for i in chosen])
            rmat = self._recovery(tuple(chosen), tuple(sorted(er)))
            rebuilt = self._apply(rmat, arr)
            for idx, i in enumerate(sorted(er)):
                U[(i, z)] = rebuilt[idx]
        # couple the erased nodes' C back from U
        for z in planes:
            for i in sorted(er):
                x, y = self.node_xy(i)
                if z[y] == x:
                    C[(i, z)] = U[(i, z)]
                else:
                    partner = self.node_id(z[y], y)
                    zp = self._zset(z, y, x)
                    C[(i, z)] = U[(i, z)] ^ _mul(GAMMA, U[(partner, zp)])
        return U, C

    def _apply(self, mat: np.ndarray, arr: np.ndarray) -> np.ndarray:
        """(r, c) GF matrix times (c, B) rows, on the selected runtime."""
        if self.runtime == "cpu":
            from ceph_tpu.ops.gf_kernel import ec_encode_ref
            return ec_encode_ref(mat, arr[None])[0]
        from ceph_tpu.ops.gf_kernel import ec_encode_jax
        return np.asarray(ec_encode_jax(mat, arr[None]))[0]

    # -- chunk <-> sub-chunk plumbing -----------------------------------------

    def _split(self, chunk: np.ndarray) -> dict:
        alpha = self.get_sub_chunk_count()
        sub = len(chunk) // alpha
        planes = self._planes()
        return {z: chunk[i * sub:(i + 1) * sub]
                for i, z in enumerate(planes)}

    def _join(self, per_plane: dict) -> bytes:
        return b"".join(per_plane[z].tobytes() for z in self._planes())

    # -- encode: parities are erasures of the generic decoder -----------------

    def encode(self, want_to_encode: set, data: bytes) -> dict:
        chunks = self.encode_prepare(data)     # (k, chunk)
        C: dict = {}
        for i in range(self.k):
            for z, sub in self._split(chunks[i]).items():
                C[(i, z)] = sub.copy()
        erased = list(range(self.k, self.k + self.m))
        _U, C = self._decode_planes(C, erased)
        out = {}
        for i in want_to_encode:
            per_plane = {z: C[(i, z)] for z in self._planes()}
            out[i] = self._join(per_plane)
        return out

    def encode_chunks(self, data_chunks):
        raise NotImplementedError("clay encodes via its coupled layers")

    # -- decode ---------------------------------------------------------------

    def decode(self, want_to_read: set, chunks: dict) -> dict:
        available = set(chunks)
        missing = sorted(want_to_read - available)
        if not missing:
            return {i: chunks[i] for i in want_to_read}
        C: dict = {}
        for i in available:
            arr = np.frombuffer(chunks[i], dtype=np.uint8)
            for z, sub in self._split(arr).items():
                C[(i, z)] = sub.copy()
        erased = [i for i in range(self.k + self.m) if i not in available]
        _U, C = self._decode_planes(C, erased)
        out = {}
        for i in want_to_read:
            if i in available:
                out[i] = chunks[i]
            else:
                out[i] = self._join({z: C[(i, z)]
                                     for z in self._planes()})
        return out

    # -- repair-bandwidth-optimal single-node repair --------------------------

    def repair_subchunks(self, lost: int) -> list[int]:
        """Sub-chunk indices each helper must send to repair `lost` —
        the q^(t-1) planes with z_{y0} = x0 (minimum_to_decode's
        sub-chunk range payload, ErasureCodeInterface.h:297-300)."""
        x0, y0 = self.node_xy(lost)
        return [i for i, z in enumerate(self._planes()) if z[y0] == x0]

    def repair(self, lost: int, helper_subchunks: dict) -> bytes:
        """Rebuild node `lost` from alpha/q sub-chunks per helper.

        helper_subchunks: {node: {z_tuple: uint8 array}} covering
        exactly the S-planes from every surviving node.
        """
        n = self.k + self.m
        x0, y0 = self.node_xy(lost)
        planes = self._planes()
        S = [z for z in planes if z[y0] == x0]
        surv = [i for i in range(n) if i != lost]
        U: dict = {}
        # 1. on each S-plane, uncouple the y != y0 rows (partners stay
        # inside S) and RS-solve the y0 row (q unknowns, m = q checks)
        for z in S:
            known: dict[int, np.ndarray] = {}
            for i in surv:
                x, y = self.node_xy(i)
                if y == y0:
                    continue
                if z[y] == x:
                    known[i] = helper_subchunks[i][z]
                else:
                    partner = self.node_id(z[y], y)
                    zp = self._zset(z, y, x)
                    u1, _ = self._uncouple(helper_subchunks[i][z],
                                           helper_subchunks[partner][zp])
                    known[i] = u1
            chosen = sorted(known)[:self.k]
            targets = [self.node_id(x, y0) for x in range(self.q)]
            rmat = self._recovery(tuple(chosen), tuple(targets))
            rebuilt = self._apply(rmat, np.stack([known[i]
                                                  for i in chosen]))
            for idx, i in enumerate(targets):
                U[(i, z)] = rebuilt[idx]
        # 2. the failed node's S sub-chunks are fixed points: C = U
        out_planes: dict = {z: U[(lost, z)] for z in S}
        # 3. off-S sub-chunks via the pair algebra through row y0:
        #    for zt in S and x != x0:  z = zt(y0->x)  pairs (lost, z)
        #    with helper (x, y0, zt):
        #      C_helper = g*U(lost, z) + U(helper, zt)
        ginv = gf_inv(GAMMA)
        for zt in S:
            for x in range(self.q):
                if x == x0:
                    continue
                helper = self.node_id(x, y0)
                z = self._zset(zt, y0, x)
                u_lost_z = _mul(ginv, helper_subchunks[helper][zt]
                                ^ U[(helper, zt)])
                out_planes[z] = u_lost_z ^ _mul(GAMMA, U[(helper, zt)])
        return self._join(out_planes)


register("clay", lambda profile: ErasureCodeClay())
