"""dmClock: the distributed mClock QoS algebra (src/dmclock analog).

mClock (Gulati et al., OSDI '10) arbitrates one server's queue between
classes by (reservation, weight, limit) tag streams.  dmClock is its
distributed extension: when a client spreads ops over many servers,
each request carries two small integers —

  delta  ops of this client completed ANYWHERE (any server, any phase)
         between the previous request to this server and this one;
  rho    the subset of those completed in RESERVATION phase.

The server then advances tags by ``rho / r`` and ``delta / w`` instead
of ``1 / r`` and ``1 / w``, so a client already receiving reservation
service elsewhere consumes its reservation cluster-wide: the floors and
caps hold for the TENANT across all OSDs, not once per daemon.  With a
single server every op reports delta = rho = 1 and the algebra reduces
exactly to mClock.

This module is the transport-neutral core the rest of the tree builds
on:

  * phase constants — which phase a dequeue was served in (rides the
    MOSDOpReply so clients can count rho);
  * ``QosProfile`` — the per-tenant (reservation, weight, limit)
    record distributed in the OSDMap's ``qos_db`` and pushed to every
    OSD's scheduler (``ceph qos set/rm/ls``);
  * ``ServiceTracker`` — the client-side counter state producing
    (delta, rho) per outgoing op (dmclock_client.h ServiceTracker).

The server half lives in ``ceph_tpu.osd.op_queue`` (MClockQueue), which
imports the phase constants from here.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ceph_tpu.common.lockdep import make_lock

#: dequeue phases (dmclock PhaseType).  LIMIT marks the work-conserving
#: fallback — every backlogged class was over its cap, so the server
#: served the earliest limit tag rather than idle; it still counts as
#: non-reservation service for rho purposes.
PHASE_NONE = 0          # not scheduled by mClock (direct queue, old peer)
PHASE_RESERVATION = 1
PHASE_WEIGHT = 2
PHASE_LIMIT = 3

PHASE_NAMES = {PHASE_NONE: "none", PHASE_RESERVATION: "reservation",
               PHASE_WEIGHT: "weight", PHASE_LIMIT: "limit"}

#: op-class name for background housekeeping work — deep scrub chunks
#: and their replica map-building ops schedule here (the reference
#: runs scrub under ``background_best_effort`` in
#: src/osd/scheduler/mClockScheduler): no reservation, a small weight,
#: an optional cap, so a full-cluster scrub storm only ever consumes
#: excess capacity and tenant reservation floors hold untouched.
BACKGROUND_BEST_EFFORT = "background_best_effort"


@dataclass
class QosProfile:
    """Per-tenant dmclock ClientInfo: the record ``ceph qos set``
    commits into the OSDMap's qos_db and every OSD folds into its
    scheduler.  reservation/limit are ops/s (0 = none/unlimited);
    weight is the share of excess capacity."""

    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0

    def to_dict(self) -> dict:
        return {"reservation": self.reservation, "weight": self.weight,
                "limit": self.limit}

    @staticmethod
    def from_dict(d: dict) -> "QosProfile":
        return QosProfile(
            reservation=float(d.get("reservation", 0.0)),
            weight=float(d.get("weight", 1.0)),
            limit=float(d.get("limit", 0.0)))

    def validate(self) -> None:
        if self.reservation < 0 or self.limit < 0:
            raise ValueError("reservation/limit must be >= 0")
        if self.weight <= 0:
            raise ValueError("weight must be > 0")
        if self.limit and self.reservation > self.limit:
            raise ValueError("reservation exceeds limit")


class ServiceTracker:
    """Client-side dmClock state (dmclock_client.h ServiceTracker).

    Two global counters — completions total and completions served in
    reservation phase — plus a per-server snapshot of both taken at the
    moment of the last request to that server.  ``get_params(server)``
    returns the counter deltas since that snapshot (the op's (delta,
    rho) wire tags) and refreshes the snapshot.

    A server never seen before gets (1, 1): the op itself is its own
    first completion, which is exactly the mClock single-server
    increment.  delta has a floor of 1 (each op counts itself); rho
    floors at 0 — zero reservation service since the last request to
    this server is precisely the signal that lets this server honor
    the tenant's reservation locally.

    Per-server records age out after ``idle_age`` seconds so a client
    that brushed thousands of OSDs once does not hold a record per
    OSD forever.
    """

    #: prune cadence: records checked every this-many get_params calls
    _PRUNE_EVERY = 256

    def __init__(self, idle_age: float = 300.0):
        self._lock = make_lock("ServiceTracker::lock")
        self._total = 0          # completions, any phase, any server
        self._reserved = 0       # completions served in reservation phase
        #: server -> [total_at_last_req, reserved_at_last_req, stamp]
        self._servers: dict[int, list] = {}
        self._idle_age = idle_age
        self._calls = 0

    def get_params(self, server: int,
                   now: float | None = None) -> tuple[int, int]:
        """(delta, rho) for an op about to be sent to ``server``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            rec = self._servers.get(server)
            if rec is None:
                delta, rho = 1, 1
            else:
                delta = max(1, self._total - rec[0])
                rho = max(0, self._reserved - rec[1])
            self._servers[server] = [self._total, self._reserved, now]
            self._calls += 1
            if self._calls % self._PRUNE_EVERY == 0:
                self._prune(now)
            return delta, rho

    def track_resp(self, phase: int) -> None:
        """Account one completed op (any server) by its served phase."""
        with self._lock:
            self._total += 1
            if phase == PHASE_RESERVATION:
                self._reserved += 1

    def _prune(self, now: float) -> None:
        stale = [s for s, rec in self._servers.items()
                 if now - rec[2] > self._idle_age]
        for s in stale:
            del self._servers[s]

    def server_count(self) -> int:
        with self._lock:
            return len(self._servers)

    def dump(self) -> dict:
        with self._lock:
            return {"completions": self._total,
                    "reservation_completions": self._reserved,
                    "tracked_servers": len(self._servers)}


#: SLO objective kinds (the slo_db record schema + the ``objective``
#: label of the ceph_slo_burn_rate prometheus family)
SLO_ATTAINMENT = "reservation_attainment"   # floor: fraction in [0, 1]
SLO_P99_LATENCY = "p99_latency_s"           # ceiling: seconds
SLO_DEVICE_SHARE = "device_share"           # ceiling: fraction in [0, 1]

SLO_OBJECTIVES = (SLO_ATTAINMENT, SLO_P99_LATENCY, SLO_DEVICE_SHARE)


@dataclass
class SloObjective:
    """Per-tenant SLO record ``ceph qos slo set`` commits into the
    OSDMap's slo_db (alongside qos_db) and the mgr slo module evaluates
    as multi-window burn rates.  Any objective left at 0 is undeclared
    and never evaluated:

      reservation_attainment  floor on the fraction of the tenant's
                              dmclock reservation actually attained
                              (reservation-phase service rate / r)
      p99_latency_s           ceiling on the tenant lane's p99 queue
                              wait, seconds
      device_share            ceiling on the tenant's share of total
                              attributed device-seconds
    """

    reservation_attainment: float = 0.0
    p99_latency_s: float = 0.0
    device_share: float = 0.0

    def to_dict(self) -> dict:
        return {SLO_ATTAINMENT: self.reservation_attainment,
                SLO_P99_LATENCY: self.p99_latency_s,
                SLO_DEVICE_SHARE: self.device_share}

    @staticmethod
    def from_dict(d: dict) -> "SloObjective":
        return SloObjective(
            reservation_attainment=float(d.get(SLO_ATTAINMENT, 0.0)),
            p99_latency_s=float(d.get(SLO_P99_LATENCY, 0.0)),
            device_share=float(d.get(SLO_DEVICE_SHARE, 0.0)))

    def validate(self) -> None:
        if not 0.0 <= self.reservation_attainment <= 1.0:
            raise ValueError(
                "reservation_attainment must be within [0, 1]")
        if self.p99_latency_s < 0:
            raise ValueError("p99_latency_s must be >= 0")
        if not 0.0 <= self.device_share <= 1.0:
            raise ValueError("device_share must be within [0, 1]")
        if not any((self.reservation_attainment, self.p99_latency_s,
                    self.device_share)):
            raise ValueError("at least one objective must be set")


def slos_from_db(slo_db: dict) -> dict[str, SloObjective]:
    """Decode the OSDMap slo_db (tenant -> plain dict) into objectives;
    malformed entries are skipped rather than wedging map application."""
    out: dict[str, SloObjective] = {}
    for tenant, rec in (slo_db or {}).items():
        try:
            out[str(tenant)] = SloObjective.from_dict(rec)
        except (TypeError, ValueError, AttributeError):
            continue
    return out


def profiles_from_db(qos_db: dict) -> dict[str, QosProfile]:
    """Decode the OSDMap qos_db (tenant -> plain dict) into profiles;
    malformed entries are skipped rather than wedging map application."""
    out: dict[str, QosProfile] = {}
    for tenant, rec in (qos_db or {}).items():
        try:
            out[str(tenant)] = QosProfile.from_dict(rec)
        except (TypeError, ValueError, AttributeError):
            continue
    return out
