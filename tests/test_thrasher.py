"""Thrasher soaks (qa/tasks/ceph_manager.py Thrasher analog): randomized
osd kill/revive/out/in, mon kills, and pg_num growth under a mixed
replicated + EC workload across the messenger stacks; zero lost or
corrupt acked objects after heal, health transitions asserted, and on
the ICI stack zero leaked staged device buffers.

Wall-clock sensitive: heartbeats, the 2s stuck-peering watchdog and the
30s post-heal verify deadline all starve when this suite shares a single
CPU core with other heavy processes (diagnosed round 4: every observed
failure coincided with 3-4 concurrent pytest runs on a 1-core host;
standalone runs are stable).  Run these soaks alone."""

from ceph_tpu.tools.thrasher import run_soak


def _assert_clean(res):
    assert res["corruptions"] == [], res
    assert res["lost_rep"] == [], res
    assert res["lost_ec"] == [], res


def test_thrasher_soak(tmp_path):
    """The long soak: >= 60s, mon kills in the storm (3-mon quorum)."""
    res = run_soak(duration=60.0, seed=11, n_osds=6,
                   base_path=str(tmp_path), n_mons=3, thrash_mons=True)
    assert res["actions"] >= 15, res
    assert res["rep_ops"] > 50, res
    _assert_clean(res)
    # structured health transitioned during the storm and recovered
    assert "HEALTH_WARN" in res["health_seen"], res["health_seen"]
    assert "OSD_DOWN" in res["health_seen"], res["health_seen"]
    assert res["final_health"] == "HEALTH_OK", res["final_health"]
    assert any(a.startswith("kill mon") for a in res["log"]), res["log"]


def test_thrasher_soak_tcp(tmp_path):
    """The same storm over real TCP sockets (event-driven stack)."""
    res = run_soak(duration=25.0, seed=23, n_osds=6,
                   base_path=str(tmp_path), ms_type="async")
    _assert_clean(res)
    assert res["final_health"] == "HEALTH_OK", res["final_health"]


def test_thrasher_soak_ici(tmp_path):
    """The storm over the ICI (device-mesh) stack; every staged shard
    buffer must end redeemed or reaped — the gauge returns to zero."""
    from ceph_tpu.msg.ici import IciTransport
    old_ttl, old_grace = IciTransport.TTL, IciTransport.GRACE
    IciTransport.TTL, IciTransport.GRACE = 6.0, 2.0
    try:
        res = run_soak(duration=25.0, seed=31, n_osds=6,
                       base_path=str(tmp_path), ms_type="ici")
        _assert_clean(res)
        assert res["ici_outstanding"] == (0, 0), res["ici_outstanding"]
    finally:
        IciTransport.TTL, IciTransport.GRACE = old_ttl, old_grace


def test_thrasher_soak_torn_ec_write_seed(tmp_path):
    """Regression: seed 14's storm tears an EC write across a kill (one
    shard lands at version V, the rest stay at V-1); peering must trim
    the authoritative log to the k-th highest holder last_update
    (_ec_trim_log) or recovery livelocks needing an unreconstructable
    version and the object reads as lost."""
    res = run_soak(duration=18.0, seed=14, n_osds=6,
                   base_path=str(tmp_path))
    assert res["corruptions"] == [], res
    assert res["lost_rep"] == [], res
    assert res["lost_ec"] == [], res
