"""Concrete message types (see package docstring for the reference mapping).

Type ids follow the reference's include/msgr.h numbering where one exists
(MSG_OSD_OP=42, MSG_OSD_OPREPLY=43, MSG_OSD_PING=70, ...), so a wire dump is
recognizable to someone who knows the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message

# op codes (rados op subset; include/rados.h CEPH_OSD_OP_*)
OP_READ = 1
OP_WRITE = 2
OP_WRITEFULL = 3
OP_DELETE = 4
OP_STAT = 5
OP_OMAP_GET = 6
OP_OMAP_SET = 7
OP_WATCH = 8          # register this client for notifies on the object
OP_UNWATCH = 9
OP_NOTIFY = 10        # fan a payload out to every watcher, wait for acks
OP_CALL = 11          # in-OSD object class method (cls\0method\0input)
OP_OMAP_RMKEYS = 12   # remove omap keys (Encoder str list in data)
OP_PGLS = 13          # list a PG's logical objects (rados ls / pgls)


@dataclass
class OSDOpField:
    """One sub-op of a client op (OSDOp in osd_types.h)."""

    op: int
    offset: int = 0
    length: int = 0
    data: bytes = b""

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.op).u64(self.offset).u64(self.length).bytes(self.data)

    @staticmethod
    def decode(dec: Decoder) -> "OSDOpField":
        return OSDOpField(op=dec.u8(), offset=dec.u64(), length=dec.u64(),
                          data=dec.bytes())


def _enc_pgid(enc: Encoder, pgid: tuple[int, int]) -> None:
    enc.s64(pgid[0]).u32(pgid[1])


def _dec_pgid(dec: Decoder) -> tuple[int, int]:
    return (dec.s64(), dec.u32())


@register_message
class MOSDOp(Message):
    TYPE = 42  # MSG_OSD_OP
    HEAD_VERSION = 4       # v4: dmclock QoS tags (FEATURE_QOS_TAGS)

    def __init__(self, client_id: int = 0, tid: int = 0,
                 pgid: tuple[int, int] = (0, 0), oid: str = "",
                 ops: list[OSDOpField] | None = None, epoch: int = 0,
                 snapid: int = 0, write_snapc: int = 0,
                 qos_tenant: str = "", qos_delta: int = 1,
                 qos_rho: int = 1):
        super().__init__()
        self.client_id = client_id
        self.tid = tid
        self.pgid = pgid
        self.oid = oid
        self.ops = ops or []
        self.epoch = epoch
        self.snapid = snapid    # v2: read as-of this pool snapshot
        #: v3: pool snap_seq in the WRITER's osdmap (the SnapContext the
        #: reference carries in every MOSDOp, src/messages/MOSDOp.h
        #: snapc) — the OSD clones against max(this, its own map), so a
        #: writer that learned of a snapshot before the serving OSD did
        #: still gets copy-on-write
        self.write_snapc = write_snapc
        #: v4 QoS extension (behind FEATURE_QOS_TAGS; old peers skip
        #: the trailing fields and schedule untagged): the tenant lane
        #: this op bills to (RGW stamps the authenticated tenant; empty
        #: = per-client lane), and the dmClock (delta, rho) pair from
        #: the client's ServiceTracker — completions anywhere / in
        #: reservation phase since the last op to THIS osd — that make
        #: reservations and limits hold cluster-wide
        self.qos_tenant = qos_tenant
        self.qos_delta = qos_delta
        self.qos_rho = qos_rho

    def encode_payload(self, enc):
        enc.versioned(4, 1, lambda e: (
            e.u64(self.client_id), e.u64(self.tid), _enc_pgid(e, self.pgid),
            e.str(self.oid), e.u32(self.epoch),
            e.list(self.ops, lambda e2, op: op.encode(e2)),
            e.u64(self.snapid), e.u64(self.write_snapc),
            e.str(self.qos_tenant), e.u32(self.qos_delta),
            e.u32(self.qos_rho)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.client_id = d.u64()
            self.tid = d.u64()
            self.pgid = _dec_pgid(d)
            self.oid = d.str()
            self.epoch = d.u32()
            self.ops = d.list(OSDOpField.decode)
            self.snapid = d.u64() if v >= 2 else 0
            self.write_snapc = d.u64() if v >= 3 else 0
            if v >= 4:
                self.qos_tenant = d.str()
                self.qos_delta = d.u32()
                self.qos_rho = d.u32()
            else:   # old peer: untagged mClock increments
                self.qos_tenant = ""
                self.qos_delta = 1
                self.qos_rho = 1
        dec.versioned(4, body)


@register_message
class MOSDOpReply(Message):
    TYPE = 43  # MSG_OSD_OPREPLY
    HEAD_VERSION = 2       # v2: dmclock phase-served echo

    def __init__(self, tid: int = 0, result: int = 0, epoch: int = 0,
                 ops: list[OSDOpField] | None = None,
                 qos_phase: int = 0):
        super().__init__()
        self.tid = tid
        self.result = result
        self.epoch = epoch
        self.ops = ops or []   # read results travel back in op fields
        #: v2: which dmclock phase served the op (qos.dmclock.PHASE_*;
        #: 0 = unscheduled/old peer) — the client's ServiceTracker
        #: counts reservation-phase completions (rho) from this
        self.qos_phase = qos_phase

    def encode_payload(self, enc):
        enc.versioned(2, 1, lambda e: (
            e.u64(self.tid), e.s32(self.result), e.u32(self.epoch),
            e.list(self.ops, lambda e2, op: op.encode(e2)),
            e.u8(self.qos_phase)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.tid = d.u64()
            self.result = d.s32()
            self.epoch = d.u32()
            self.ops = d.list(OSDOpField.decode)
            self.qos_phase = d.u8() if v >= 2 else 0
        dec.versioned(2, body)


@register_message
class MOSDRepOp(Message):
    TYPE = 112  # MSG_OSD_REPOP

    def __init__(self, reqid: tuple[int, int] = (0, 0),
                 pgid: tuple[int, int] = (0, 0), oid: str = "",
                 txn: bytes = b"", pg_version: tuple[int, int] = (0, 0),
                 entry: bytes = b""):
        super().__init__()
        self.reqid = reqid          # (client_id, tid)
        self.pgid = pgid
        self.oid = oid
        self.txn = txn              # encoded ObjectStore transaction
        self.pg_version = pg_version
        self.entry = entry          # encoded pg LogEntry (v2+)

    def encode_payload(self, enc):
        enc.versioned(2, 1, lambda e: (
            e.u64(self.reqid[0]), e.u64(self.reqid[1]),
            _enc_pgid(e, self.pgid), e.str(self.oid), e.bytes(self.txn),
            e.u32(self.pg_version[0]), e.u64(self.pg_version[1]),
            e.bytes(self.entry)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.reqid = (d.u64(), d.u64())
            self.pgid = _dec_pgid(d)
            self.oid = d.str()
            self.txn = d.bytes()
            self.pg_version = (d.u32(), d.u64())
            if v >= 2:
                self.entry = d.bytes()
        dec.versioned(2, body)


@register_message
class MOSDRepOpReply(Message):
    TYPE = 113  # MSG_OSD_REPOPREPLY

    def __init__(self, reqid: tuple[int, int] = (0, 0),
                 pgid: tuple[int, int] = (0, 0), from_osd: int = 0,
                 result: int = 0):
        super().__init__()
        self.reqid = reqid
        self.pgid = pgid
        self.from_osd = from_osd
        self.result = result

    def encode_payload(self, enc):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.reqid[0]), e.u64(self.reqid[1]),
            _enc_pgid(e, self.pgid), e.s32(self.from_osd),
            e.s32(self.result)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.reqid = (d.u64(), d.u64())
            self.pgid = _dec_pgid(d)
            self.from_osd = d.s32()
            self.result = d.s32()
        dec.versioned(1, body)


@register_message
class MOSDECSubOpWrite(Message):
    TYPE = 108  # MSG_OSD_EC_WRITE

    def __init__(self, reqid: tuple[int, int] = (0, 0),
                 pgid: tuple[int, int] = (0, 0), oid: str = "",
                 shard: int = 0, chunk: bytes = b"", epoch: int = 0,
                 obj_size: int = 0, entry: bytes = b"",
                 offset: int = 0, shard_len: int = 0,
                 truncate: bool = True):
        super().__init__()
        self.reqid = reqid
        self.pgid = pgid
        self.oid = oid
        self.shard = shard
        self.chunk = chunk
        self.epoch = epoch
        self.obj_size = obj_size  # full (pre-encode) object size
        self.entry = entry        # encoded pg LogEntry (v3+)
        # v4: ranged stripe writes (ECBackend rmw pipeline)
        self.offset = offset      # byte offset within the shard object
        self.shard_len = shard_len  # full shard length after this write
        self.truncate = truncate  # True = replace the shard wholesale

    def encode_payload(self, enc):
        enc.versioned(4, 1, lambda e: (
            e.u64(self.reqid[0]), e.u64(self.reqid[1]),
            _enc_pgid(e, self.pgid), e.str(self.oid), e.u8(self.shard),
            e.bytes(self.chunk), e.u32(self.epoch), e.u64(self.obj_size),
            e.bytes(self.entry),
            e.u64(self.offset), e.u64(self.shard_len),
            e.u8(1 if self.truncate else 0)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.reqid = (d.u64(), d.u64())
            self.pgid = _dec_pgid(d)
            self.oid = d.str()
            self.shard = d.u8()
            self.chunk = d.bytes()
            self.epoch = d.u32()
            if v >= 2:  # v1 smuggled the size in the oid
                self.obj_size = d.u64()
            if v >= 3:
                self.entry = d.bytes()
            if v >= 4:
                self.offset = d.u64()
                self.shard_len = d.u64()
                self.truncate = d.u8() != 0
        dec.versioned(4, body)


@register_message
class MOSDECSubOpWriteReply(Message):
    TYPE = 109

    def __init__(self, reqid: tuple[int, int] = (0, 0), shard: int = 0,
                 from_osd: int = 0, result: int = 0):
        super().__init__()
        self.reqid = reqid
        self.shard = shard
        self.from_osd = from_osd
        self.result = result

    def encode_payload(self, enc):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.reqid[0]), e.u64(self.reqid[1]), e.u8(self.shard),
            e.s32(self.from_osd), e.s32(self.result)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.reqid = (d.u64(), d.u64())
            self.shard = d.u8()
            self.from_osd = d.s32()
            self.result = d.s32()
        dec.versioned(1, body)


@register_message
class MOSDECSubOpRead(Message):
    TYPE = 110

    def __init__(self, reqid: tuple[int, int] = (0, 0),
                 pgid: tuple[int, int] = (0, 0), oid: str = "",
                 shard: int = 0):
        super().__init__()
        self.reqid = reqid
        self.pgid = pgid
        self.oid = oid
        self.shard = shard

    def encode_payload(self, enc):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.reqid[0]), e.u64(self.reqid[1]),
            _enc_pgid(e, self.pgid), e.str(self.oid), e.u8(self.shard)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.reqid = (d.u64(), d.u64())
            self.pgid = _dec_pgid(d)
            self.oid = d.str()
            self.shard = d.u8()
        dec.versioned(1, body)


@register_message
class MOSDECSubOpReadReply(Message):
    TYPE = 111

    def __init__(self, reqid: tuple[int, int] = (0, 0), shard: int = 0,
                 from_osd: int = 0, result: int = 0, chunk: bytes = b"",
                 ver: tuple[int, int] = (0, 0)):
        super().__init__()
        self.reqid = reqid
        self.shard = shard
        self.from_osd = from_osd
        self.result = result
        self.chunk = chunk
        self.ver = ver          # shard's object version (v2+; recovery reads)

    def encode_payload(self, enc):
        enc.versioned(2, 1, lambda e: (
            e.u64(self.reqid[0]), e.u64(self.reqid[1]), e.u8(self.shard),
            e.s32(self.from_osd), e.s32(self.result), e.bytes(self.chunk),
            e.u32(self.ver[0]), e.u64(self.ver[1])))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.reqid = (d.u64(), d.u64())
            self.shard = d.u8()
            self.from_osd = d.s32()
            self.result = d.s32()
            self.chunk = d.bytes()
            if v >= 2:
                self.ver = (d.u32(), d.u64())
        dec.versioned(2, body)


@register_message
class MOSDPing(Message):
    TYPE = 70  # MSG_OSD_PING

    PING = 0
    PING_REPLY = 1

    def __init__(self, from_osd: int = 0, op: int = 0, stamp: float = 0.0,
                 epoch: int = 0):
        super().__init__()
        self.from_osd = from_osd
        self.op = op
        self.stamp = stamp
        self.epoch = epoch

    def encode_payload(self, enc):
        enc.versioned(1, 1, lambda e: (
            e.s32(self.from_osd), e.u8(self.op), e.f64(self.stamp),
            e.u32(self.epoch)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.from_osd = d.s32()
            self.op = d.u8()
            self.stamp = d.f64()
            self.epoch = d.u32()
        dec.versioned(1, body)


@register_message
class MOSDFailure(Message):
    TYPE = 51  # MSG_OSD_FAILURE

    def __init__(self, reporter: int = 0, failed_osd: int = 0,
                 failed_for: float = 0.0, epoch: int = 0,
                 alive: bool = False):
        super().__init__()
        self.reporter = reporter
        self.failed_osd = failed_osd
        self.failed_for = failed_for
        self.epoch = epoch
        #: v2: FLAG_ALIVE cancellation (messages/MOSDFailure.h if_osd_alive)
        #: — the reporter heard from the peer again; retract my report
        self.alive = alive

    def encode_payload(self, enc):
        enc.versioned(2, 1, lambda e: (
            e.s32(self.reporter), e.s32(self.failed_osd),
            e.f64(self.failed_for), e.u32(self.epoch),
            e.u8(1 if self.alive else 0)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.reporter = d.s32()
            self.failed_osd = d.s32()
            self.failed_for = d.f64()
            self.epoch = d.u32()
            self.alive = bool(d.u8()) if v >= 2 else False
        dec.versioned(2, body)


@register_message
class MOSDMapMsg(Message):
    """Map distribution (messages/MOSDMap.h): carries EITHER a full map
    blob OR a contiguous run of incremental blobs [(epoch, inc)] — the
    reference's maps/incremental_maps pair, reduced to one-or-the-other
    (full maps only on backfill/gap, deltas for normal churn)."""

    TYPE = 41  # MSG_OSD_MAP
    HEAD_VERSION = 2       # v2: incremental blobs ride along

    def __init__(self, epoch: int = 0, map_blob: bytes = b"",
                 incs: list | None = None):
        super().__init__()
        self.epoch = epoch
        self.map_blob = map_blob  # OSDMap encoded via osd.map_codec
        #: [(epoch, inc_blob)] ascending, contiguous; applies to a map
        #: at incs[0][0] - 1
        self.incs = incs or []

    def encode_payload(self, enc):
        def body(e):
            e.u32(self.epoch)
            e.bytes(self.map_blob)
            e.list(self.incs, lambda e2, p: (e2.u32(p[0]),
                                             e2.bytes(p[1])))
        enc.versioned(2, 1, body)

    def decode_payload(self, dec, version):
        def body(d, v):
            self.epoch = d.u32()
            self.map_blob = d.bytes()
            self.incs = (d.list(lambda d2: (d2.u32(), d2.bytes()))
                         if v >= 2 else [])
        dec.versioned(2, body)


@register_message
class MPGStats(Message):
    """Per-OSD PG state summary for mon health (the pre-luminous
    MPGStats / PGMonitor flow: primaries report, the mon aggregates
    PG_DEGRADED-class checks from it)."""

    TYPE = 87  # MSG_PGSTATS

    def __init__(self, osd_id: int = 0, states: dict | None = None,
                 degraded_objects: int = 0, stamp: float = 0.0):
        super().__init__()
        self.osd_id = osd_id
        self.states = states or {}      # pg state -> count (primary pgs)
        self.degraded_objects = degraded_objects
        self.stamp = stamp

    def encode_payload(self, enc):
        enc.versioned(1, 1, lambda e: (
            e.u32(self.osd_id),
            e.map(self.states, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.u32(v)),
            e.u64(self.degraded_objects), e.f64(self.stamp)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.osd_id = d.u32()
            self.states = d.map(lambda d2: d2.str(), lambda d2: d2.u32())
            self.degraded_objects = d.u64()
            self.stamp = d.f64()
        dec.versioned(1, body)


@register_message
class MMonCommand(Message):
    TYPE = 50  # MSG_MON_COMMAND

    def __init__(self, tid: int = 0, cmd: dict | None = None):
        super().__init__()
        self.tid = tid
        self.cmd = cmd or {}

    def encode_payload(self, enc):
        import json
        enc.versioned(1, 1, lambda e: (e.u64(self.tid),
                                       e.str(json.dumps(self.cmd))))

    def decode_payload(self, dec, version):
        import json

        def body(d, v):
            self.tid = d.u64()
            self.cmd = json.loads(d.str())
        dec.versioned(1, body)


@register_message
class MMonCommandAck(Message):
    TYPE = 52  # MSG_MON_COMMAND_ACK

    def __init__(self, tid: int = 0, result: int = 0, output: str = ""):
        super().__init__()
        self.tid = tid
        self.result = result
        self.output = output

    def encode_payload(self, enc):
        enc.versioned(1, 1, lambda e: (e.u64(self.tid), e.s32(self.result),
                                       e.str(self.output)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.tid = d.u64()
            self.result = d.s32()
            self.output = d.str()
        dec.versioned(1, body)


@register_message
class MWatchNotify(Message):
    """osd -> watching client: a notify fired on an object
    (messages/MWatchNotify.h; CEPH_MSG_WATCH_NOTIFY)."""

    TYPE = 44

    def __init__(self, pool: int = 0, oid: str = "", notify_id: int = 0,
                 payload: bytes = b""):
        super().__init__()
        self.pool = pool
        self.oid = oid
        self.notify_id = notify_id
        self.payload = payload

    def encode_payload(self, enc):
        enc.versioned(1, 1, lambda e: (
            e.s64(self.pool), e.str(self.oid), e.u64(self.notify_id),
            e.bytes(self.payload)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.pool = d.s64()
            self.oid = d.str()
            self.notify_id = d.u64()
            self.payload = d.bytes()
        dec.versioned(1, body)


@register_message
class MWatchNotifyAck(Message):
    TYPE = 45

    def __init__(self, pool: int = 0, oid: str = "", notify_id: int = 0):
        super().__init__()
        self.pool = pool
        self.oid = oid
        self.notify_id = notify_id

    def encode_payload(self, enc):
        enc.versioned(1, 1, lambda e: (
            e.s64(self.pool), e.str(self.oid), e.u64(self.notify_id)))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.pool = d.s64()
            self.oid = d.str()
            self.notify_id = d.u64()
        dec.versioned(1, body)


@register_message
class MOSDScrub(Message):
    """primary -> replica: send your scrub map for this PG
    (MOSDRepScrub analog).  v2 adds an optional oid filter so the
    verified-repair pass can re-fetch JUST the repaired objects'
    digests instead of re-scrubbing the whole collection; old peers
    (compat 1) skip the field and reply with the full map, which the
    primary filters — correct either way."""

    TYPE = 120
    HEAD_VERSION = 2

    def __init__(self, pgid: tuple[int, int] = (0, 0), scrub_id: int = 0,
                 from_osd: int = 0, oids: list[str] | None = None):
        super().__init__()
        self.pgid = pgid
        self.scrub_id = scrub_id
        self.from_osd = from_osd
        #: None = map the whole collection; a list restricts the map
        #: to exactly these store oids (repair verification)
        self.oids = oids

    def encode_payload(self, enc):
        enc.versioned(2, 1, lambda e: (
            _enc_pgid(e, self.pgid), e.u64(self.scrub_id),
            e.s32(self.from_osd),
            e.u8(0 if self.oids is None else 1),
            e.list(self.oids or [], lambda e2, o: e2.str(o))))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.pgid = _dec_pgid(d)
            self.scrub_id = d.u64()
            self.from_osd = d.s32()
            self.oids = None
            if v >= 2:
                has = d.u8()
                lst = d.list(lambda d2: d2.str())
                self.oids = lst if has else None
        dec.versioned(2, body)


@register_message
class MOSDScrubReply(Message):
    """replica -> primary: {oid: (size, data_crc, omap_crc)}.  v2 adds
    the per-oid version blobs ("_v" attrs): scrub maps are gathered
    seconds apart under load, so the primary must distinguish
    SAME-VERSION divergence (corruption — repair it) from
    version-skewed divergence (an in-flight write or recovery — the
    replication machinery owns it; a scrub repair there would push a
    stale copy over an acked newer write)."""

    TYPE = 121
    HEAD_VERSION = 2

    def __init__(self, pgid: tuple[int, int] = (0, 0), scrub_id: int = 0,
                 from_osd: int = 0, scrub_map: dict | None = None,
                 versions: dict | None = None):
        super().__init__()
        self.pgid = pgid
        self.scrub_id = scrub_id
        self.from_osd = from_osd
        self.scrub_map = scrub_map or {}
        #: oid -> raw "_v" blob (b"" for objects without one)
        self.versions = versions or {}

    def encode_payload(self, enc):
        enc.versioned(2, 1, lambda e: (
            _enc_pgid(e, self.pgid), e.u64(self.scrub_id),
            e.s32(self.from_osd),
            e.map(self.scrub_map, lambda e2, k: e2.str(k),
                  lambda e2, t: (e2.u64(t[0]), e2.u32(t[1]),
                                 e2.u32(t[2]))),
            e.map(self.versions, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.bytes(v))))

    def decode_payload(self, dec, version):
        def body(d, v):
            self.pgid = _dec_pgid(d)
            self.scrub_id = d.u64()
            self.from_osd = d.s32()
            self.scrub_map = d.map(
                lambda d2: d2.str(),
                lambda d2: (d2.u64(), d2.u32(), d2.u32()))
            self.versions = {}
            if v >= 2:
                self.versions = d.map(lambda d2: d2.str(),
                                      lambda d2: d2.bytes())
        dec.versioned(2, body)
