"""Erasure-code non-regression corpus tool
(src/test/erasure-code/ceph_erasure_code_non_regression.cc:113,304-328
analog).

--create writes, for every plugin x technique x (k, m) configuration, the
chunks produced from a fixed PRNG payload into an .npz archive;
--check re-encodes and byte-compares.  The committed corpus
(tests/golden/ec_corpus/) pins every kernel's output bytes forever: any
change to the GF math, the generator constructions, shec windows, lrc
layering, or clay coupling fails CI.

    python -m ceph_tpu.tools.ec_non_regression --create
    python -m ceph_tpu.tools.ec_non_regression --check
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "golden", "ec_corpus")

PAYLOAD_LEN = 2111    # deliberately unaligned: pins padding semantics too
SEED = 20260730

LRC_LAYERS = json.dumps([
    ["cDDD____", {"plugin": "jerasure", "technique": "reed_sol_van"}],
    ["____cDDD", {"plugin": "jerasure", "technique": "reed_sol_van"}],
])

#: (name, plugin, profile)
CONFIGS = [
    ("jerasure_rsvan_k4m2", "jerasure",
     {"k": "4", "m": "2", "technique": "reed_sol_van"}),
    ("jerasure_rsvan_k7m3", "jerasure",
     {"k": "7", "m": "3", "technique": "reed_sol_van"}),
    ("jerasure_rsr6_k4m2", "jerasure",
     {"k": "4", "m": "2", "technique": "reed_sol_r6_op"}),
    ("jerasure_cauchy_good_k4m2", "jerasure",
     {"k": "4", "m": "2", "technique": "cauchy_good"}),
    ("jerasure_cauchy_orig_k4m2", "jerasure",
     {"k": "4", "m": "2", "technique": "cauchy_orig"}),
    ("jerasure_liberation_k4m2", "jerasure",
     {"k": "4", "m": "2", "technique": "liberation"}),
    ("jerasure_blaum_roth_k4m2", "jerasure",
     {"k": "4", "m": "2", "technique": "blaum_roth"}),
    ("jerasure_liber8tion_k4m2", "jerasure",
     {"k": "4", "m": "2", "technique": "liber8tion"}),
    ("isa_cauchy_k8m4", "isa",
     {"k": "8", "m": "4", "technique": "cauchy"}),
    ("isa_vand_k4m2", "isa",
     {"k": "4", "m": "2", "technique": "reed_sol_van"}),
    ("shec_k4m3c2", "shec", {"k": "4", "m": "3", "c": "2"}),
    ("lrc_2x3", "lrc", {"mapping": "_DDD_DDD", "layers": LRC_LAYERS}),
    ("clay_k4m2", "clay", {"k": "4", "m": "2"}),
    ("clay_k2m2", "clay", {"k": "2", "m": "2"}),
]


def _payload() -> bytes:
    rng = np.random.default_rng(SEED)
    return rng.integers(0, 256, PAYLOAD_LEN, dtype=np.uint8).tobytes()


def _encode_all(plugin: str, profile: dict) -> dict[int, bytes]:
    from ceph_tpu.ec import registry_instance
    prof = dict(profile)
    prof.setdefault("runtime", "cpu")   # the oracle path pins the bytes;
    # kernel-vs-oracle equality is covered by the unit tests
    codec = registry_instance().factory(plugin, prof)
    n = codec.get_chunk_count()
    return codec.encode(set(range(n)), _payload())


def create(directory: str) -> int:
    os.makedirs(directory, exist_ok=True)
    for name, plugin, profile in CONFIGS:
        enc = _encode_all(plugin, profile)
        arrays = {f"chunk_{i}": np.frombuffer(v, dtype=np.uint8)
                  for i, v in enc.items()}
        np.savez_compressed(os.path.join(directory, f"{name}.npz"),
                            **arrays)
        print(f"created {name}: {len(enc)} chunks")
    return 0


def check(directory: str) -> int:
    failures = 0
    for name, plugin, profile in CONFIGS:
        path = os.path.join(directory, f"{name}.npz")
        if not os.path.exists(path):
            print(f"MISSING corpus {name}")
            failures += 1
            continue
        stored = np.load(path)
        enc = _encode_all(plugin, profile)
        for i, blob in enc.items():
            want = stored[f"chunk_{i}"].tobytes()
            if blob != want:
                print(f"MISMATCH {name} chunk {i}")
                failures += 1
    if failures == 0:
        print(f"all {len(CONFIGS)} corpus configs bit-identical")
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--create", action="store_true")
    g.add_argument("--check", action="store_true")
    ap.add_argument("--directory", default=DEFAULT_DIR)
    args = ap.parse_args(argv)
    return create(args.directory) if args.create else check(args.directory)


if __name__ == "__main__":
    sys.exit(main())
