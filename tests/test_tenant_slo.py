"""Tenant-attributed device time + SLO burn rates: the cost_tag
ledger's conservation property, slo_db map distribution (full +
incremental codec) and the mon `qos slo` command tier, the slo
module's multi-window burn-rate math, the ceph_tenant_* /
ceph_slo_burn_rate prometheus families (including a hostile tenant
name through the real scrape parser), profile_report's per-tenant
table, and the e2e gate: a hog violating its SLO on a live
MiniCluster raises QOS_SLO_BURN for exactly that tenant and clears
once the pressure stops."""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_kernel_telemetry import parse_exposition            # noqa: E402
from test_qos_fairness import (                               # noqa: E402
    _install_service_delay, _Pump, _set_profiles,
    _wait_profiles_applied)

from ceph_tpu.msg.encoding import Decoder, Encoder            # noqa: E402
from ceph_tpu.ops.telemetry import LATENCY_BOUNDS             # noqa: E402
from ceph_tpu.osd.map_codec import (                          # noqa: E402
    apply_incremental, decode_incremental, decode_osdmap, diff_osdmap,
    encode_incremental, encode_osdmap)
from ceph_tpu.osd.osdmap import OSDMap                        # noqa: E402
from ceph_tpu.tools.vstart import MiniCluster                 # noqa: E402

pytestmark = pytest.mark.filterwarnings("ignore")

EVIL_TENANT = 'evil"tenant\n\\'


# -- slo_db distribution ------------------------------------------------------

def test_osdmap_codec_carries_slo_db():
    m = OSDMap(epoch=3)
    m.set_max_osd(2)
    m.slo_db = {"gold": {"reservation_attainment": 0.95,
                         "p99_latency_s": 0.05, "device_share": 0.0}}
    got = decode_osdmap(encode_osdmap(m))
    assert got.slo_db == m.slo_db
    # copy() duplicates the db (mon _mutate mutates the copy)
    c = m.copy()
    c.slo_db["hog"] = {"reservation_attainment": 0.0,
                       "p99_latency_s": 0.01, "device_share": 0.0}
    assert "hog" not in m.slo_db


def test_incremental_carries_slo_db():
    old = OSDMap(epoch=5)
    old.set_max_osd(2)
    new = old.copy()
    new.epoch = 6
    new.slo_db = {"gold": {"reservation_attainment": 0.9,
                           "p99_latency_s": 0.0, "device_share": 0.5}}
    inc = diff_osdmap(old, new)
    assert "slo_db" in inc
    dec = decode_incremental(encode_incremental(inc))
    m = old.copy()
    apply_incremental(m, dec)
    assert m.epoch == 6 and m.slo_db == new.slo_db
    # removal distributes too
    newer = new.copy()
    newer.epoch = 7
    newer.slo_db = {}
    inc2 = decode_incremental(encode_incremental(
        diff_osdmap(new, newer)))
    apply_incremental(m, inc2)
    assert m.slo_db == {}


def test_mon_qos_slo_commands():
    cluster = MiniCluster(n_osds=1, ms_type="loopback").start()
    try:
        cluster.wait_for_osd_count(1)
        client = cluster.client(timeout=15.0)
        rc, out = client.mon_command(
            {"prefix": "qos slo set", "tenant": "gold",
             "reservation_attainment": 0.95, "p99_latency_s": 0.05})
        assert rc == 0, out
        # validation: fractions in [0,1], at least one objective set
        rc, _ = client.mon_command(
            {"prefix": "qos slo set", "tenant": "bad",
             "reservation_attainment": 1.5})
        assert rc == -22
        rc, _ = client.mon_command(
            {"prefix": "qos slo set", "tenant": "bad"})
        assert rc == -22
        rc, out = client.mon_command({"prefix": "qos slo ls"})
        assert rc == 0
        db = json.loads(out)
        assert set(db) == {"gold"}
        assert db["gold"]["reservation_attainment"] == 0.95
        assert db["gold"]["p99_latency_s"] == 0.05
        rc, _ = client.mon_command({"prefix": "qos slo rm",
                                    "tenant": "gold"})
        assert rc == 0
        rc, _ = client.mon_command({"prefix": "qos slo rm",
                                    "tenant": "gold"})
        assert rc == -2
        rc, out = client.mon_command({"prefix": "qos slo ls"})
        assert json.loads(out) == {}
    finally:
        cluster.stop()


# -- conservation property ----------------------------------------------------

def test_tenant_ledger_conserves_busy_seconds():
    """Sum over tenant rows equals the engines' busy-seconds integral
    (within 5%), with untagged traffic visible in _untagged and scrub
    riding as background_best_effort — nothing silently vanishes."""
    from ceph_tpu.common.context import CephTpuContext
    from ceph_tpu.ec import registry_instance
    from ceph_tpu.ops import telemetry
    from ceph_tpu.ops.dispatch import BACKGROUND_BEST_EFFORT

    telemetry.tenant_stats().clear()
    b0 = (telemetry.dispatch_stats().phases.busy_seconds
          + telemetry.decode_dispatch_stats().phases.busy_seconds)
    k, m = 4, 2
    codec = registry_instance().factory(
        "isa", {"technique": "cauchy", "k": str(k), "m": str(m)})
    ctx = CephTpuContext("test-tenant-ledger")
    eng = ctx.dispatch_engine()
    deng = ctx.decode_dispatch_engine()
    rng = np.random.default_rng(11)
    op = rng.integers(0, 256, (8, k, 512), dtype=np.uint8)
    futs = []
    for tenant in ("hog", "gold", "silver", "bronze"):
        futs.extend(codec.submit_chunks(eng, op,
                                        cost_tag=(tenant, "client"))
                    for _ in range(3))
    # scrub-style background work and an untagged straggler
    futs.append(codec.submit_chunks(
        eng, op,
        cost_tag=(BACKGROUND_BEST_EFFORT, BACKGROUND_BEST_EFFORT)))
    futs.append(codec.submit_chunks(eng, op))
    chosen = tuple(c for c in range(k + m) if c != 0)[:k]
    futs.append(codec.submit_decode_chunks(
        deng, chosen, op, (0,), cost_tag=("gold", "client")))
    for f in futs:
        f.result(timeout=120)
    eng.flush()
    deng.flush()
    eng.stop()
    deng.stop()
    busy = (telemetry.dispatch_stats().phases.busy_seconds
            + telemetry.decode_dispatch_stats().phases.busy_seconds
            - b0)
    ledger = telemetry.tenant_stats().total_device_seconds()
    assert busy > 0
    assert abs(ledger - busy) <= 0.05 * busy, (ledger, busy)
    digest = telemetry.tenant_usage_digest()
    tenants = digest["tenants"]
    assert {"hog", "gold", "silver", "bronze",
            BACKGROUND_BEST_EFFORT, "_untagged"} <= set(tenants)
    # shares sum to ~1 (the _untagged bucket keeps the total honest)
    assert abs(sum(t["share"] for t in tenants.values()) - 1.0) < 0.01
    # the decode channel shows up under its own engine for gold
    assert "decode" in tenants["gold"]["engines"]
    # the full dump carries queue-wait histograms per channel
    dump = telemetry.tenant_dump()
    row = dump["tenants"]["gold"]["engines"]["encode"]
    ch = next(iter(row.values()))
    assert "queue_wait" in ch and ch["queue_wait"]["count"] >= 3


# -- burn-rate engine (unit) --------------------------------------------------

class _SloStubMgr:
    """Controllable feeds for the slo module: mutate .tenant_feed /
    .qos_feed / .osdmap between ticks."""

    class _Map:
        def __init__(self):
            self.slo_db = {}
            self.qos_db = {}

    def __init__(self):
        self.osdmap = self._Map()
        self.tenant_feed = {}
        self.qos_feed = {}

    def get(self, name):
        return {"tenant_feed": self.tenant_feed,
                "qos_feed": self.qos_feed}[name]

    def get_store(self, key, default=None):
        return default


def _lane(served_res, served_weight, backlog=0, buckets=None):
    return {"served": {"reservation": served_res,
                       "weight": served_weight, "limit": 0},
            "backlog": backlog,
            "wait_buckets": buckets or [0] * (len(LATENCY_BOUNDS) + 1)}


def _bucket_counts(value_s, n):
    """n samples all landing in the bucket covering value_s."""
    counts = [0] * (len(LATENCY_BOUNDS) + 1)
    for i, b in enumerate(LATENCY_BOUNDS):
        if value_s <= b:
            counts[i] = n
            return counts
    counts[-1] = n
    return counts


def test_slo_burn_math_and_multi_window_rule():
    from ceph_tpu.mgr.modules.slo import Module

    stub = _SloStubMgr()
    stub.osdmap.slo_db = {
        "gold": {"reservation_attainment": 0.9, "p99_latency_s": 0.0,
                 "device_share": 0.0},
        "hog": {"reservation_attainment": 0.0, "p99_latency_s": 0.01,
                "device_share": 0.0},
        "pig": {"reservation_attainment": 0.0, "p99_latency_s": 0.0,
                "device_share": 0.5},
        "idle": {"reservation_attainment": 0.9, "p99_latency_s": 0.0,
                 "device_share": 0.0},
    }
    stub.osdmap.qos_db = {
        "gold": {"reservation": 100.0, "weight": 1.0, "limit": 0.0},
        "idle": {"reservation": 100.0, "weight": 1.0, "limit": 0.0}}
    mod = Module(stub)
    t0 = 1000.0
    stub.qos_feed = {0: {"lanes": {
        "client.gold": _lane(0, 0), "client.hog": _lane(0, 0),
        "client.idle": _lane(0, 0)}}}
    stub.tenant_feed = {0: {"tenants": {}, "total_device_seconds": 0.0}}
    mod.tick(t0)
    # 10 s later: gold attained 20% of its floor, hog's window p99 sits
    # at 50 ms vs a 10 ms ceiling, pig took 80% of the device vs 50%
    stub.qos_feed = {0: {"lanes": {
        "client.gold": _lane(200, 800, backlog=5),
        "client.hog": _lane(0, 500,
                            buckets=_bucket_counts(0.05, 100)),
        "client.idle": _lane(0, 0)}}}
    stub.tenant_feed = {0: {
        "tenants": {"pig": {"device_seconds": 8.0, "share": 0.8,
                            "engines": {}},
                    "_untagged": {"device_seconds": 2.0, "share": 0.2,
                                  "engines": {}}},
        "total_device_seconds": 10.0}}
    mod.tick(t0 + 10.0)
    st = mod.status(now=t0 + 10.0)
    gold = st["tenants"]["gold"]["burn"]["reservation_attainment"]
    # attained 0.2 against a 0.9 floor: burn = 0.8 / 0.1 = 8
    assert abs(gold["fast"] - 8.0) < 0.1, gold
    hog = st["tenants"]["hog"]["burn"]["p99_latency_s"]
    assert abs(hog["fast"] - 5.0) < 0.1, hog       # 0.05 / 0.01
    pig = st["tenants"]["pig"]["burn"]["device_share"]
    assert abs(pig["fast"] - 1.6) < 0.01, pig      # 0.8 / 0.5
    # demand gate: idle declared a floor but had no traffic -> vacuous
    idle = st["tenants"]["idle"]["burn"]["reservation_attainment"]
    assert idle["fast"] == 0.0
    assert st["tenants"]["idle"]["burning"] == []
    # both windows cover the damage interval -> burning
    assert st["tenants"]["gold"]["burning"] == ["reservation_attainment"]
    assert st["tenants"]["hog"]["burning"] == ["p99_latency_s"]
    checks = mod.health_checks()
    assert checks and checks[0]["check"] == "QOS_SLO_BURN"
    assert set(checks[0]["tenants"]) == {"gold", "hog", "pig"}
    # gauges mirror the fast burns
    g = mod.burn_gauges()
    assert abs(g["hog"]["p99_latency_s"] - 5.0) < 0.1
    # pressure stops: counters freeze and gold's backlog drains (a
    # standing backlog would rightly keep its attainment floor
    # burning).  Once the fast window's base is a post-damage sample
    # the fast burn drops to 0 and the alert clears even though the
    # slow window still covers the violation.
    stub.qos_feed = {0: {"lanes": {
        "client.gold": _lane(200, 800),
        "client.hog": _lane(0, 500,
                            buckets=_bucket_counts(0.05, 100)),
        "client.idle": _lane(0, 0)}}}
    mod.tick(t0 + 400.0)
    mod.tick(t0 + 800.0)
    st2 = mod.status(now=t0 + 800.0)
    assert st2["tenants"]["hog"]["burn"]["p99_latency_s"]["fast"] == 0.0
    assert all(not rec["burning"] for rec in st2["tenants"].values())
    assert mod.health_checks() == []


def test_slo_module_merges_feeds_by_insights_rule():
    """Byte-identical tenant digests (shared in-process registry)
    contribute ONCE with every reporter listed; distinct digests and
    qos lanes SUM across OSDs."""
    from ceph_tpu.mgr.modules.slo import Module

    stub = _SloStubMgr()
    same = {"tenants": {"gold": {"device_seconds": 4.0, "share": 1.0,
                                 "engines": {}}},
            "total_device_seconds": 4.0}
    stub.tenant_feed = {0: json.loads(json.dumps(same)),
                        1: json.loads(json.dumps(same)),
                        2: {"tenants": {"gold": {"device_seconds": 1.0,
                                                 "share": 1.0,
                                                 "engines": {}}},
                            "total_device_seconds": 1.0}}
    stub.qos_feed = {0: {"lanes": {"client.gold": _lane(5, 10)}},
                     1: {"lanes": {"client.gold": _lane(7, 20)}}}
    mod = Module(stub)
    merged = mod._tenant_usage_merged()
    # 4.0 once (dedup) + 1.0 distinct = 5.0, NOT 9.0
    assert abs(merged["total_device_seconds"] - 5.0) < 1e-9
    assert merged["tenants"]["gold"]["device_seconds"] == 5.0
    assert merged["reported_by"] == [0, 1, 2]
    lanes = mod._lanes_merged()
    assert lanes["gold"]["served_res"] == 12
    assert lanes["gold"]["served_total"] == 42
    top = mod.usage_top()
    assert top["tenants"][0]["tenant"] == "gold"
    assert set(top["tenants"][0]["reported_by"]) == {0, 1, 2}


# -- exporter surfaces --------------------------------------------------------

def _unescape_label(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(
                v[i + 1], v[i + 1]))
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def test_prometheus_tenant_and_slo_families_survive_evil_names():
    from ceph_tpu.mgr.modules.prometheus import Module

    class _SloStub:
        def burn_gauges(self):
            return {EVIL_TENANT: {"p99_latency_s": 2.5}}

    class _Mgr:
        class _Map:
            max_osd = 1
            epoch = 1
            osd_weight = [0x10000]
            slo_db = {EVIL_TENANT: {"p99_latency_s": 0.01}}

            def is_up(self, o):
                return True

            def exists(self, o):
                return True

        osdmap = _Map()

        def get(self, name):
            return {
                "health": {"status": "HEALTH_OK"},
                "pg_summary": {},
                "df": {"total_objects": 0, "total_bytes_used": 0},
                "counters": {},
                "perf_reports": {},
                "tenant_feed": {0: {
                    "tenants": {EVIL_TENANT: {
                        "device_seconds": 1.5, "share": 0.75,
                        "engines": {"encode": {"ec_encode": {
                            "qos_class": "client",
                            "device_seconds": 1.5, "batches": 2,
                            "requests": 9}}}}},
                    "total_device_seconds": 2.0}},
            }[name]

        def get_store(self, key, default=None):
            return default

        def _module(self, name):
            assert name == "slo"
            return _SloStub()

    mod = Module.__new__(Module)
    mod.mgr = _Mgr()
    text = mod.scrape_text()
    fams = parse_exposition(text)     # raises on any malformed line
    for fam, typ in (("ceph_tenant_device_share", "gauge"),
                     ("ceph_tenant_device_seconds_total", "counter"),
                     ("ceph_tenant_requests_total", "counter"),
                     ("ceph_slo_burn_rate", "gauge")):
        assert fam in fams and fams[fam]["type"] == typ, fam
    share = fams["ceph_tenant_device_share"]["samples"][0]
    assert _unescape_label(share[1]["tenant"]) == EVIL_TENANT
    assert share[2] == 0.75
    ds = {(_unescape_label(s[1]["tenant"]), s[1]["engine"],
           s[1]["channel"]): s[2]
          for s in fams["ceph_tenant_device_seconds_total"]["samples"]}
    assert ds[(EVIL_TENANT, "encode", "ec_encode")] == 1.5
    burn = fams["ceph_slo_burn_rate"]["samples"][0]
    assert _unescape_label(burn[1]["tenant"]) == EVIL_TENANT
    assert burn[1]["objective"] == "p99_latency_s"
    assert burn[2] == 2.5


def test_profile_report_renders_tenant_table():
    from ceph_tpu.tools.profile_report import render, render_tenant

    digest = {"tenants": {
        "gold": {"device_seconds": 0.12, "share": 0.6,
                 "engines": {"encode": {"ec_encode": {
                     "qos_class": "client", "device_seconds": 0.12,
                     "batches": 4, "requests": 9}}}},
        "_untagged": {"device_seconds": 0.08, "share": 0.4,
                      "engines": {}}},
        "total_device_seconds": 0.2}
    # admin-dump / MMgrReport digest shape
    out = render_tenant(digest)
    assert "gold" in out and "_untagged" in out and "ec_encode" in out
    # bench JSON line wrapper
    assert "gold" in render({"tenant_usage": digest})
    # `usage top` ranked-rows shape
    top = {"tenants": [{"tenant": "gold", "device_seconds": 0.12,
                        "engines": {}}],
           "total_device_seconds": 0.2}
    assert "gold" in render_tenant(top)
    # no ledger -> no table, and render() omits the section
    assert render_tenant({"engines": {}}) is None


# -- e2e: burn fires, names the right tenant, clears --------------------------

def _wait(cond, timeout=20.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_slo_burn_e2e_fires_for_the_violated_tenant_and_clears():
    """The acceptance gate: 4 tenants over an EC pool on 3 OSDs with a
    live mgr; the hog floods past its own p99 objective and
    QOS_SLO_BURN names exactly the hog within the (shrunken) fast
    window; `slo status` and `usage top` tell the same story from at
    least two OSDs' merged feeds; stopping the hog clears the alert."""
    cluster = MiniCluster(
        n_osds=3, ms_type="loopback",
        osd_conf={"osd_op_num_shards": 1}).start()
    try:
        mgr = cluster.run_mgr()
        for oid in list(cluster.osds):
            cluster.kill_osd(oid)
            cluster.run_osd(oid)
        cluster.wait_for_osd_count(3)
        client = cluster.client(timeout=30.0)
        pool = cluster.create_pool(client, pg_num=8,
                                   pool_type="erasure", k=2, m=1)
        profiles = {"hog": {"weight": 8.0},
                    "gold": {"reservation": 50.0, "weight": 0.01},
                    "silver": {"weight": 2.0},
                    "bronze": {"weight": 4.0}}
        _set_profiles(client, profiles)
        _wait_profiles_applied(cluster, profiles)
        for osd in cluster.osds.values():
            _install_service_delay(osd, 0.002)
        # hog: a p99 queue-wait ceiling its own flood tramples;
        # gold: a generous ceiling nobody can violate (bounds cap 1 s)
        rc, out = client.mon_command(
            {"prefix": "qos slo set", "tenant": "hog",
             "p99_latency_s": 0.0001})
        assert rc == 0, out
        rc, out = client.mon_command(
            {"prefix": "qos slo set", "tenant": "gold",
             "p99_latency_s": 10.0})
        assert rc == 0, out
        assert _wait(lambda: "hog" in (mgr.osdmap.slo_db or {})), \
            mgr.osdmap.slo_db
        # shrink the windows so the gate runs in seconds; the module
        # reads these through the mon config-key store
        mgr.set_store("mgr/slo/mgr_slo_fast_window_s", 1.5)
        mgr.set_store("mgr/slo/mgr_slo_slow_window_s", 4.0)
        slo = mgr._module("slo")
        slo.tick(time.time())            # pre-flood baseline
        pumps = {t: _Pump(client, pool, t, n).start()
                 for t, n in (("hog", 8), ("gold", 2),
                              ("silver", 2), ("bronze", 2))}
        try:
            def burning_hog():
                slo.tick(time.time())
                st = slo.status()
                return st["tenants"]["hog"]["burning"] == \
                    ["p99_latency_s"]
            # fires within the fast window (plus report latency)
            assert _wait(burning_hog, timeout=20.0, interval=0.4)
            st = slo.status()
            # exactly the violated tenant: gold's generous objective
            # never burns
            assert st["tenants"]["gold"]["burning"] == [], st
            health = mgr.health()
            slo_checks = [c for c in health["checks"]
                          if c["check"] == "QOS_SLO_BURN"]
            assert slo_checks, health
            assert set(slo_checks[0]["tenants"]) == {"hog"}
            assert health["status"] in ("HEALTH_WARN", "HEALTH_ERR")
            # the command tier tells the same story
            out, rc = mgr._handle_command({"prefix": "slo status"})
            assert rc == 0
            assert json.loads(out)["tenants"]["hog"]["burning"] == \
                ["p99_latency_s"]
            out, rc = mgr._handle_command({"prefix": "usage top"})
            assert rc == 0
            top = json.loads(out)
            names = [r["tenant"] for r in top["tenants"]]
            assert "hog" in names, top
            # merged from at least two OSDs' feeds (byte-identical
            # in-process digests dedup but list every reporter)
            assert len(top["reported_by"]) >= 2, top
            hog_row = next(r for r in top["tenants"]
                           if r["tenant"] == "hog")
            assert len(hog_row["reported_by"]) >= 2, hog_row
            assert hog_row["device_seconds"] > 0
        finally:
            for p in pumps.values():
                p.halt()
            for p in pumps.values():
                p.join()

        def cleared():
            slo.tick(time.time())
            return not slo.health_checks()
        # once the fast window's base post-dates the flood the burn
        # drops to 0 and the warning clears
        assert _wait(cleared, timeout=20.0, interval=0.4)
        st = slo.status()
        assert st["tenants"]["hog"]["burning"] == [], st
    finally:
        cluster.stop()
