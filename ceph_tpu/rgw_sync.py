"""RGW multisite sync — one-way zone replication over bucket datalogs
(src/rgw/rgw_data_sync.cc + rgw_datalog.h, reduced to the pull model).

Every mutating gateway op appends a record to the bucket's DATALOG
(omap keys ``log.<ns-timestamp>`` in the bucket index, so the log rides
the same replicated/EC pool as the data).  A ``ZoneSyncAgent`` on the
SECONDARY zone polls the primary's registry + datalogs and replays:

  * full sync on first contact (no marker): copy every current object
  * incremental after: apply each log record past the stored marker —
    put re-reads the object from the source, delete deletes; markers
    persist in the secondary's ``.sync.status`` omap object, so a
    restarted agent resumes where it left off (sync-status markers,
    rgw_data_sync.cc's incremental marker window)
  * processed log entries are trimmed on the PRIMARY only below the
    MINIMUM marker across every registered peer zone: each agent
    publishes its per-bucket progress into the primary's ``.sync.peers``
    omap (the reference's per-shard sync-status objects,
    rgw_data_sync.cc), so a second secondary syncing slower never loses
    records to the faster one's trim

Replays are idempotent (puts overwrite, deletes tolerate absence), so
crash-and-restart in mid-window is safe: the marker only advances after
the record applied."""

from __future__ import annotations

import itertools
import json
import threading
import time
import uuid

from ceph_tpu.rgw_rest import S3Error, S3Gateway

DATALOG_PREFIX = "log."
_APPEND_SEQ = itertools.count()
SYNC_STATUS_OID = ".sync.status"
#: PRIMARY-side per-peer progress registry: "<zone>\x00<bucket>" ->
#: marker; trim floors at the minimum across peers
SYNC_PEERS_OID = ".sync.peers"


def datalog_append(gateway: S3Gateway, bucket: str, op: str, key: str,
                   clock=time.time) -> None:
    """One mutation record.  Keys order by the INJECTED clock (so a
    simulated clock controls ordering and trim windows in tests) with a
    wall-clock ns tiebreaker for uniqueness under a frozen clock."""
    rec = {"op": op, "key": key, "t": clock()}
    # tiebreaker is a process-monotonic counter: a wall-clock-derived
    # one wraps and can reorder records sharing the primary key
    k = (f"{DATALOG_PREFIX}{int(clock() * 1e9):020d}"
         f".{next(_APPEND_SEQ) % 1_000_000_000:09d}")
    gateway.io.set_omap(f".bucket.index.{bucket}",
                        {k: json.dumps(rec).encode()})


def datalog_entries(gateway: S3Gateway, bucket: str,
                    marker: str = "") -> list[tuple[str, dict]]:
    """Ordered (log_key, record) past `marker`."""
    try:
        omap = gateway.io.get_omap(f".bucket.index.{bucket}")
    except OSError:
        return []
    out = []
    for k, v in omap.items():
        if k.startswith(DATALOG_PREFIX) and v and k > marker:
            out.append((k, json.loads(v.decode())))
    out.sort()
    return out


def datalog_trim(gateway: S3Gateway, bucket: str, upto: str) -> int:
    """Drop log records with key <= upto; returns how many."""
    try:
        omap = gateway.io.get_omap(f".bucket.index.{bucket}")
    except OSError:
        return 0
    dead = [k for k in omap
            if k.startswith(DATALOG_PREFIX) and k <= upto]
    if dead:
        gateway.io.rm_omap_keys(f".bucket.index.{bucket}", dead)
    return len(dead)


def remove_peer(source: S3Gateway, zone_id: str) -> int:
    """Drop every .sync.peers row of a zone (decommission); returns
    rows removed.  Run against the PRIMARY when a secondary is retired
    so its frozen markers stop pinning the trim floor."""
    try:
        omap = source.io.get_omap(SYNC_PEERS_OID)
    except OSError:
        return 0
    dead = [k for k in omap if k.split("\x00", 1)[0] == zone_id]
    if dead:
        source.io.rm_omap_keys(SYNC_PEERS_OID, dead)
    return len(dead)


class ZoneSyncAgent:
    """Pull-replays a primary zone's buckets into a secondary zone."""

    def __init__(self, source: S3Gateway, target: S3Gateway,
                 interval: float = 1.0, trim: bool = True,
                 zone_id: str | None = None):
        self.src = source
        self.dst = target
        self.interval = interval
        self.trim = trim
        #: unique per secondary zone: keys this agent's rows in the
        #: primary's peer-progress registry.  MUST be stable across
        #: agent restarts for real deployments (pass it explicitly);
        #: the default is unique so two anonymous agents can never
        #: share a row and trim each other's unapplied records
        self.zone_id = zone_id or f"zone-{uuid.uuid4().hex[:12]}"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- markers --------------------------------------------------------------

    def _markers(self) -> dict:
        try:
            omap = self.dst.io.get_omap(SYNC_STATUS_OID)
        except OSError:
            return {}
        return {k: v.decode() for k, v in omap.items()}

    def _set_marker(self, bucket: str, marker: str) -> None:
        self.dst.io.set_omap(SYNC_STATUS_OID, {bucket: marker.encode()})

    def _publish_progress(self, bucket: str, marker: str) -> None:
        """Report this zone's marker to the PRIMARY (rgw_data_sync's
        sync-status objects): the trim floor for every peer."""
        try:
            self.src.io.set_omap(
                SYNC_PEERS_OID,
                {f"{self.zone_id}\x00{bucket}": marker.encode()})
        except OSError:
            pass    # progress publication is advisory; retried next pass

    def _peer_rows(self) -> dict[str, str]:
        """The whole peer registry, ONE omap fetch per pass."""
        try:
            omap = self.src.io.get_omap(SYNC_PEERS_OID)
        except OSError:
            return {}
        return {k: v.decode() for k, v in omap.items()}

    @staticmethod
    def _peer_trim_floor(peers: dict[str, str],
                         bucket: str) -> str | None:
        """Minimum marker across every peer registered for the bucket —
        trimming above it would lose records a slower secondary still
        needs.  None = no peer registered (no trim)."""
        markers = [v for k, v in peers.items()
                   if k.split("\x00", 1)[1:] == [bucket]]
        return min(markers) if markers else None

    def deregister(self) -> None:
        """Retire this zone from the primary's peer registry (the
        operator's decommission step): a dead peer's rows would
        otherwise pin every bucket's trim floor forever and the
        primary datalogs would grow without bound."""
        remove_peer(self.src, self.zone_id)

    # -- one pass -------------------------------------------------------------

    def sync_once(self) -> dict:
        """One full poll over the source registry.  Returns counters."""
        stats = {"buckets": 0, "full_copied": 0, "applied": 0,
                 "trimmed": 0, "errors": 0}
        try:
            names = sorted(self.src.io.get_omap(self.src.REGISTRY))
        except OSError:
            return stats
        markers = self._markers()
        peers = self._peer_rows()
        for name in names:
            try:
                stats["buckets"] += 1
                self._sync_bucket(name, markers.get(name), stats,
                                  peers)
            except (S3Error, OSError):
                stats["errors"] += 1
        # a bucket we have a marker for that vanished from the source
        # registry was deleted on the primary: propagate the removal
        for name in set(markers) - set(names):
            try:
                self._remove_bucket(name)
                stats["applied"] += 1
            except (S3Error, OSError):
                stats["errors"] += 1
        return stats

    def _remove_bucket(self, name: str) -> None:
        try:
            b = self.dst._bucket(name)
        except S3Error:
            b = None
        if b is not None:
            for key in b.list():
                try:
                    b.delete_object(key, unversioned=True)
                except KeyError:
                    pass
            self.dst.delete_bucket(name)
        try:
            self.dst.io.rm_omap_keys(SYNC_STATUS_OID, [name])
        except OSError:
            pass
        try:
            self.src.io.rm_omap_keys(
                SYNC_PEERS_OID, [f"{self.zone_id}\x00{name}"])
        except OSError:
            pass

    def _ensure_bucket(self, name: str) -> None:
        # the source meta read must SUCCEED before we create: replicating
        # a bucket with owner "" would leave it unowned on the secondary
        # (authorize treats an empty owner as matching nobody, so the
        # bucket's config ops would be dead) — propagate instead; the
        # per-bucket sync loop retries next cycle
        meta = self.src._bucket(name).meta_all()
        owner = meta.get("owner", "")
        try:
            self.dst.create_bucket(name, owner=owner)
        except S3Error as e:
            if e.code != "BucketAlreadyExists":
                raise
            # repair path: a bucket replicated before its owner was
            # known (or whose owner changed at the source) gets the
            # source's owner backfilled — an empty owner matches nobody
            # in authorize, so leaving it would strand the bucket's
            # config ops forever
            b = self.dst._bucket(name)
            if b.meta_all().get("owner", "") != owner:
                b.set_meta("owner", owner)

    def _copy_object(self, bucket: str, key: str) -> bool:
        try:
            data, head = self.src.get_object(bucket, key)
        except S3Error:
            return False    # deleted since the log record: skip
        import hashlib
        b = self.dst._bucket(bucket)
        b.put(key, data, metadata=dict(head.get("meta") or {}),
              clock=self.dst.clock, unversioned=True,
              etag=head.get("etag")
              or hashlib.md5(data).hexdigest())
        return True

    def _sync_bucket(self, name: str, marker: str | None,
                     stats: dict, peers: dict[str, str]) -> None:
        self._ensure_bucket(name)
        if marker is None:
            # FULL SYNC: snapshot the log head first — records landing
            # during the copy replay afterwards, none are lost.
            # Register with the primary BEFORE copying: a concurrent
            # fast peer computing its trim floor during our copy must
            # already see us, or it trims records our post-head replay
            # still needs
            entries = datalog_entries(self.src, name)
            head = entries[-1][0] if entries else ""
            self._publish_progress(name, head or "log.")
            src_b = self.src._bucket(name)
            for key in src_b.list():
                if key.startswith(self.src.MP_PREFIX + "."):
                    continue
                if self._copy_object(name, key):
                    stats["full_copied"] += 1
            self._set_marker(name, head or "log.")
            marker = head or "log."
            return
        for log_key, rec in datalog_entries(self.src, name, marker):
            op, key = rec.get("op"), rec.get("key", "")
            if op == "put":
                if self._copy_object(name, key):
                    stats["applied"] += 1
            elif op == "delete":
                try:
                    self.dst._bucket(name).delete_object(
                        key, unversioned=True)
                except (KeyError, S3Error):
                    pass
                stats["applied"] += 1
            # marker advances only AFTER the record applied: a crash
            # here replays this record again (idempotent), never skips
            self._set_marker(name, log_key)
            marker = log_key
        # publish ONCE per pass (a lagging published marker only makes
        # the trim floor conservative, never lossy)
        self._publish_progress(name, marker)
        if self.trim and marker and marker != "log.":
            # overlay our fresh marker on the pass-start snapshot: the
            # floor always reflects OUR true progress, peers' may lag
            # one pass (conservative, never lossy)
            peers = dict(peers)
            peers[f"{self.zone_id}\x00{name}"] = marker
            floor = self._peer_trim_floor(peers, name)
            if floor and floor != "log.":
                stats["trimmed"] += datalog_trim(self.src, name, floor)

    # -- background loop ------------------------------------------------------

    def start(self) -> "ZoneSyncAgent":
        self._thread = threading.Thread(target=self._loop,
                                        name="rgw-zone-sync",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
            except Exception:    # survive transient pool errors
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
