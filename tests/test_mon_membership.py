"""Runtime monitor membership (Monitor.cc:1186-1400 probe, :1560-1740
store sync, MonmapMonitor reduced): growing 1→3 mons on a live cluster
under I/O, killing + wiping a mon and watching it probe + store-sync +
rejoin quorum, and removing a mon."""

from __future__ import annotations

import json
import threading
import time

import pytest

from ceph_tpu.tools.vstart import MiniCluster


def _wait(pred, timeout=30.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_grow_one_to_three_mons_under_io():
    c = MiniCluster(n_osds=3, n_mons=1, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=4, size=2)
        io = client.open_ioctx(pool)
        io.write_full("pre-grow", b"written before the grow")

        # background I/O across the whole membership change
        stop = threading.Event()
        errors: list = []
        written = [0]

        def writer():
            i = 0
            while not stop.is_set():
                try:
                    io.write_full(f"grow-{i}", f"v{i}".encode())
                    written[0] = i
                    i += 1
                except Exception as e:   # noqa: BLE001
                    errors.append(e)
                    return
                time.sleep(0.02)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            c.add_mon(1)
            c.add_mon(2)
        finally:
            stop.set()
            t.join(timeout=10)
        assert not errors, errors
        assert written[0] > 0

        # all three mons agree on membership and quorum
        rc, out = client.mon_command({"prefix": "mon dump"})
        assert rc == 0
        dump = json.loads(out)
        assert set(dump["mons"]) == {"0", "1", "2"}
        assert _wait(lambda: all(
            sorted(m.quorum()) == [0, 1, 2] for m in c.mons.values()))
        # data written during the grow is all there
        for i in range(0, written[0] + 1, max(written[0] // 5, 1)):
            assert io.read(f"grow-{i}", 32) == f"v{i}".encode()

        # paxos survives losing the original mon: 2 of 3 is quorum
        c.kill_mon(0)
        client2 = c.client(timeout=20.0)
        assert _wait(lambda: client2.mon_command(
            {"prefix": "quorum_status"})[0] == 0
            and set(json.loads(client2.mon_command(
                {"prefix": "quorum_status"})[1])["quorum"]) == {1, 2})
        # a paxos MUTATION still commits on the survivor quorum
        pool2 = c.create_pool(client2, pg_num=4, size=2)
        io2 = client2.open_ioctx(pool2)
        io2.write_full("post-kill", b"quorum of two")
        assert io2.read("post-kill", 32) == b"quorum of two"
    finally:
        c.stop()


def test_wiped_mon_store_syncs_and_rejoins():
    c = MiniCluster(n_osds=3, n_mons=3, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=4, size=2)
        io = client.open_ioctx(pool)
        io.write_full("durable", b"survives the wipe")
        # build paxos history beyond the sync tail, so the rejoin is a
        # genuine JUMP sync (tail only), not a full-history replay
        for m in c.mons.values():
            m.SYNC_TAIL = 5
        for i in range(8):
            client.mon_command({"prefix": "config-key set",
                                "key": f"churn/{i}", "value": str(i)})
        lead = next(m for m in c.mons.values() if m.is_leader())
        lc_before = lead.paxos.last_committed
        assert lc_before > 8

        replaced = c.replace_mon(2)
        # the wiped store pulled the tail: its history STARTS above v1
        assert replaced.paxos.last_committed >= lc_before
        assert replaced.db.get("paxos", "v_1") is None
        assert _wait(lambda: sorted(replaced.quorum()) == [0, 1, 2])
        # and it serves the synced state
        assert _wait(lambda: replaced.osdmap.epoch
                     >= lead.osdmap.epoch - 1)
        assert replaced.osdmap.mon_db.get("mons", {}).keys() \
            == {"0", "1", "2"}
        # cluster still fully functional incl. the replaced mon as a
        # paxos participant: kill a DIFFERENT mon; {replaced, other}
        # must still commit mutations
        c.kill_mon(0)
        client2 = c.client(timeout=20.0)
        assert _wait(lambda: client2.mon_command(
            {"prefix": "config-key set", "key": "after",
             "value": "wipe"})[0] == 0)
        assert io.read("durable", 32) == b"survives the wipe"
    finally:
        c.stop()


def test_mon_rm_shrinks_quorum():
    c = MiniCluster(n_osds=0, n_mons=3, ms_type="loopback").start()
    try:
        client = c.client(timeout=20.0)
        assert _wait(lambda: client.mon_command(
            {"prefix": "quorum_status"})[0] == 0)
        rc, out = client.mon_command({"prefix": "mon rm", "id": 2})
        assert rc == 0, out
        # survivors reconfigure to {0,1}; the removed mon goes quiet
        assert _wait(lambda: all(
            sorted(c.mons[i].monmap) == [0, 1] for i in (0, 1)))
        assert _wait(lambda: c.mons[2].elector is None)
        assert _wait(lambda: sorted(c.mons[0].quorum()) == [0, 1])
        # removing the last-but-one is allowed; removing the LAST is not
        rc, _ = client.mon_command({"prefix": "mon rm", "id": 1})
        assert rc == 0
        assert _wait(lambda: c.mons[0].quorum() == [0])
        rc, out = client.mon_command({"prefix": "mon rm", "id": 0})
        assert rc == -22
    finally:
        c.stop()
