"""Central cluster log (mon/LogMonitor.cc:120-260 + messages/MLog.h:21
analog).

Every daemon holds a ``ClusterLogClient`` and calls ``clog.info/warn/
error`` for operator-significant events (osd marked down, pg recovery
done, mgr failover, mon membership changes, health transitions).
Entries batch per daemon and fan out to EVERY monitor, each of which
persists them in its own store and serves ``ceph log last N``.

Replication choice vs the reference: LogMonitor batches log entries
through paxos so the quorum holds one agreed sequence.  Here the
SENDER fans the same entries out to all mons (exactly like MPGStats /
MOSDFailure reports) and each mon stores them keyed by
``(stamp, name, seq)`` — every quorum member converges on the same
multiset without spending a consensus round per log line, and
``log last`` output is identical on any mon that received the traffic.
The trade: a mon that was down while an entry fanned out misses it
(the reference would backfill via paxos); the operator reads any
surviving mon, which is the one that watched the outage anyway.
"""

from __future__ import annotations

import json
import threading
import time

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import EntityName

PRIO_DEBUG = 0
PRIO_INFO = 1
PRIO_SEC = 2
PRIO_WARN = 3
PRIO_ERROR = 4

_PRIO_NAMES = {PRIO_DEBUG: "DBG", PRIO_INFO: "INF", PRIO_SEC: "SEC",
               PRIO_WARN: "WRN", PRIO_ERROR: "ERR"}


def prio_name(prio: int) -> str:
    return _PRIO_NAMES.get(prio, str(prio))


def make_entry(seq: int, prio: int, message: str,
               channel: str = "cluster") -> dict:
    """The one place the log-entry schema is built (clients and the
    mon's own logging share it; MLog.encode_payload mirrors it)."""
    return {"stamp": time.time(), "seq": seq, "prio": prio,
            "channel": channel, "message": message}


@register_message
class MLog(Message):
    """daemon -> mon: a batch of cluster-log entries (MLog.h:21)."""

    TYPE = 68  # MSG_LOG

    def __init__(self, name: str = "",
                 entries: list[dict] | None = None):
        super().__init__()
        self.name = name
        #: [{"stamp": float, "seq": int, "prio": int, "channel": str,
        #:   "message": str}]
        self.entries = entries or []

    def encode_payload(self, enc: Encoder):
        def one(e: Encoder, ent: dict):
            e.f64(ent["stamp"])
            e.u64(ent["seq"])
            e.u8(ent["prio"])
            e.str(ent.get("channel", "cluster"))
            e.str(ent["message"])

        enc.versioned(1, 1, lambda e: (
            e.str(self.name), e.list(self.entries, one)))

    def decode_payload(self, dec: Decoder, version: int):
        def one(d: Decoder) -> dict:
            return {"stamp": d.f64(), "seq": d.u64(), "prio": d.u8(),
                    "channel": d.str(), "message": d.str()}

        def body(d, v):
            self.name = d.str()
            self.entries = d.list(one)
        dec.versioned(1, body)


class ClusterLogClient:
    """Per-daemon clog handle (common/LogClient.h analog): buffer
    entries, flush a batch to every monitor on the owner's tick (or
    when the buffer grows).  ``targets_fn`` returns the (rank, addr)
    mon list — pass ``moncmd.mon_targets`` output so the log follows
    runtime monmap changes."""

    MAX_BUFFER = 64

    def __init__(self, msgr, targets_fn, name: str):
        self.msgr = msgr
        self.targets_fn = targets_fn
        self.name = name
        # analysis: allow[bare-lock] -- cluster-log ring leaf lock
        self._lock = threading.Lock()
        self._seq = 0
        self._buf: list[dict] = []

    def log(self, prio: int, fmt: str, *args,
            channel: str = "cluster") -> None:
        msg = (fmt % args) if args else fmt
        with self._lock:
            self._seq += 1
            self._buf.append(make_entry(self._seq, prio, msg, channel))
            full = len(self._buf) >= self.MAX_BUFFER
        if full:
            self.flush()

    def debug(self, fmt, *a):
        self.log(PRIO_DEBUG, fmt, *a)

    def info(self, fmt, *a):
        self.log(PRIO_INFO, fmt, *a)

    def warn(self, fmt, *a):
        self.log(PRIO_WARN, fmt, *a)

    def error(self, fmt, *a):
        self.log(PRIO_ERROR, fmt, *a)

    def flush(self) -> None:
        """Send the buffered batch to every mon (idempotent receiver
        keying by (name, seq) — resends after a flush error are safe)."""
        with self._lock:
            if not self._buf:
                return
            batch = list(self._buf)
        sent_any = False
        try:
            for rank, addr in self.targets_fn():
                try:
                    con = self.msgr.connect_to(
                        addr, EntityName("mon", rank))
                    con.send_message(MLog(name=self.name,
                                          entries=batch))
                    sent_any = True
                except OSError:
                    continue
        finally:
            if sent_any:
                with self._lock:
                    # drop exactly what was sent; entries logged during
                    # the send stay for the next flush
                    self._buf = [e for e in self._buf
                                 if e["seq"] > batch[-1]["seq"]]


class LogStore:
    """Mon-side persisted log (LogMonitor's store, reduced): entries
    keyed ``(stamp, name, seq)`` in the mon KV store under the "clog"
    prefix, trimmed to a cap, served newest-last like `ceph log last`."""

    CAP = 10000

    def __init__(self, db):
        self.db = db
        # analysis: allow[bare-lock] -- cluster-log ring leaf lock
        self._lock = threading.Lock()
        self._count: int | None = None

    @staticmethod
    def _key(name: str, ent: dict) -> str:
        return f"{ent['stamp']:020.6f}.{name}.{ent['seq']:08d}"

    def append(self, name: str, entries: list[dict]) -> None:
        with self._lock:
            t = self.db.get_transaction()
            added = 0
            for ent in entries:
                key = self._key(name, ent)
                if self.db.get("clog", key) is not None:
                    continue    # duplicate resend
                t.set("clog", key, json.dumps(
                    {**ent, "name": name}).encode())
                added += 1
            if not added:
                return
            self.db.submit_transaction(t)
            # incremental count: trim's full-store scan runs only when
            # the cap is actually exceeded, not on every batch
            if self._count is None:
                self._count = len(self.db.get_range("clog"))
            else:
                self._count += added
            if self._count > self.CAP:
                self._trim()

    def _trim(self) -> None:
        keys = sorted(self.db.get_range("clog"))
        if len(keys) <= self.CAP:
            self._count = len(keys)
            return
        t = self.db.get_transaction()
        for k in keys[:len(keys) - self.CAP]:
            t.rmkey("clog", k)
        self.db.submit_transaction(t)
        self._count = self.CAP

    def last(self, n: int = 100, channel: str | None = None,
             min_prio: int = 0) -> list[dict]:
        if n <= 0:
            return []
        out = []
        rows = self.db.get_range("clog")
        for k in sorted(rows):
            ent = json.loads(rows[k].decode())
            if channel and ent.get("channel") != channel:
                continue
            if ent.get("prio", 0) < min_prio:
                continue
            out.append(ent)
        return out[-n:]
