"""File locking state (src/mds/flock.{h,cc} ceph_lock_state_t analog).

Two lock families, both arbitrated by the MDS that owns the inode:

  * POSIX/fcntl byte-range locks: per (client, owner-token) — a later
    lock by the same owner REPLACES its overlap (split/merge semantics:
    locking [0,10) exclusive then [4,6) shared leaves three segments);
    unlock punches holes.
  * BSD flock: whole-file, per file HANDLE (owner token carries the
    handle id), shared/exclusive, upgrade/downgrade by re-locking.

Blocking waiters queue here as opaque tokens; the server re-runs them
when anything is removed.  All state drops when a session dies —
exactly the reference's behaviour on client eviction.
"""

from __future__ import annotations

from dataclasses import dataclass

F_RDLCK = 0
F_WRLCK = 1
F_UNLCK = 2

EOF = 1 << 62      # "to end of file" sentinel (len=0 in fcntl terms)


@dataclass
class Lock:
    client: int
    owner: str          # fcntl: process-wide token; flock: handle token
    type: int           # F_RDLCK | F_WRLCK
    start: int
    end: int            # exclusive


def _overlap(a: Lock, start: int, end: int) -> bool:
    return a.start < end and start < a.end


class LockState:
    """Lock table for ONE inode."""

    def __init__(self):
        self.posix: list[Lock] = []
        self.flock: list[Lock] = []

    # -- conflict checks -----------------------------------------------------

    def posix_conflict(self, client: int, owner: str, ltype: int,
                       start: int, end: int) -> Lock | None:
        if ltype == F_UNLCK:
            return None
        for lk in self.posix:
            if (lk.client, lk.owner) == (client, owner):
                continue            # own locks never conflict
            if not _overlap(lk, start, end):
                continue
            if ltype == F_WRLCK or lk.type == F_WRLCK:
                return lk
        return None

    def flock_conflict(self, client: int, owner: str,
                       ltype: int) -> Lock | None:
        if ltype == F_UNLCK:
            return None
        for lk in self.flock:
            if (lk.client, lk.owner) == (client, owner):
                continue
            if ltype == F_WRLCK or lk.type == F_WRLCK:
                return lk
        return None

    # -- mutation ------------------------------------------------------------

    def posix_set(self, client: int, owner: str, ltype: int,
                  start: int, end: int) -> bool:
        """Apply F_SETLK once conflicts are clear; returns False on
        conflict (caller decides EAGAIN vs block)."""
        if ltype != F_UNLCK and \
                self.posix_conflict(client, owner, ltype, start, end):
            return False
        # carve the range out of this owner's existing locks (split)
        kept: list[Lock] = []
        for lk in self.posix:
            if (lk.client, lk.owner) != (client, owner) \
                    or not _overlap(lk, start, end):
                kept.append(lk)
                continue
            if lk.start < start:
                kept.append(Lock(client, owner, lk.type, lk.start, start))
            if end < lk.end:
                kept.append(Lock(client, owner, lk.type, end, lk.end))
        if ltype != F_UNLCK:
            kept.append(Lock(client, owner, ltype, start, end))
            # coalesce adjacent same-type segments of this owner
            kept = self._merge(kept, client, owner)
        self.posix = kept
        return True

    @staticmethod
    def _merge(locks: list[Lock], client: int, owner: str) -> list[Lock]:
        mine = sorted((lk for lk in locks
                       if (lk.client, lk.owner) == (client, owner)),
                      key=lambda lk: lk.start)
        rest = [lk for lk in locks
                if (lk.client, lk.owner) != (client, owner)]
        out: list[Lock] = []
        for lk in mine:
            if out and out[-1].type == lk.type and out[-1].end >= lk.start:
                out[-1].end = max(out[-1].end, lk.end)
            else:
                out.append(lk)
        return rest + out

    def flock_set(self, client: int, owner: str, ltype: int) -> bool:
        if ltype != F_UNLCK and \
                self.flock_conflict(client, owner, ltype):
            return False
        self.flock = [lk for lk in self.flock
                      if (lk.client, lk.owner) != (client, owner)]
        if ltype != F_UNLCK:
            self.flock.append(Lock(client, owner, ltype, 0, EOF))
        return True

    def getlk(self, client: int, owner: str, ltype: int,
              start: int, end: int) -> dict | None:
        """F_GETLK: first conflicting lock, or None if it would fit."""
        lk = self.posix_conflict(client, owner, ltype, start, end)
        if lk is None:
            return None
        return {"client": lk.client, "owner": lk.owner, "type": lk.type,
                "start": lk.start,
                "len": 0 if lk.end >= EOF else lk.end - lk.start}

    def drop_client(self, client: int) -> bool:
        """Session death / unmount: every lock evaporates."""
        before = len(self.posix) + len(self.flock)
        self.posix = [lk for lk in self.posix if lk.client != client]
        self.flock = [lk for lk in self.flock if lk.client != client]
        return len(self.posix) + len(self.flock) != before

    def empty(self) -> bool:
        return not self.posix and not self.flock


def fcntl_range(start: int, length: int) -> tuple[int, int]:
    """fcntl's (l_start, l_len) -> [start, end); len 0 = to EOF."""
    return start, (EOF if length == 0 else start + length)
