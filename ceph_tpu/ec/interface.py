"""The erasure-code plugin contract.

Semantics follow src/erasure-code/ErasureCodeInterface.h:170-462: systematic
codes over k data + m coding chunks; an object is padded, split into k chunks,
and m coding chunks are computed; any k of the k+m chunks recover the object.
Chunks may be remapped (get_chunk_mapping) and may have sub-chunks (clay codes,
ErasureCodeInterface.h:259).

Differences from the reference, by design:
  * payloads are ``bytes`` / numpy uint8 arrays, not bufferlists;
  * a first-class batched API (encode_batch/decode_batch over (S, k, B) arrays)
    exposes the TPU batch point that the reference reaches only through
    ECUtil's per-stripe loop (src/osd/ECUtil.cc:120-159).
"""

from __future__ import annotations

from abc import ABC, abstractmethod

ErasureCodeProfile = dict  # name -> str, like the reference's map<string,string>


class ErasureCodeInterface(ABC):
    """Abstract contract every erasure-code plugin implements."""

    @abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Parse and validate the profile; raise ValueError on bad parameters
        (the reference returns -EINVAL and fills an ostream)."""

    @abstractmethod
    def get_chunk_count(self) -> int:
        """k + m (ErasureCodeInterface.h:226)."""

    @abstractmethod
    def get_data_chunk_count(self) -> int:
        """k."""

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """Sub-chunks per chunk; 1 except for regenerating codes like clay
        (ErasureCodeInterface.h:259)."""
        return 1

    @abstractmethod
    def get_chunk_size(self, stripe_width: int) -> int:
        """Chunk size for an object of ``stripe_width`` bytes, including
        padding/alignment (ErasureCodeInterface.h:281)."""

    @abstractmethod
    def minimum_to_decode(self, want_to_read: set, available: set) -> set:
        """Smallest chunk set sufficient to decode ``want_to_read``; raises
        IOError if impossible (ErasureCodeInterface.h:297)."""

    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: dict) -> set:
        """Like minimum_to_decode but available maps chunk -> retrieval cost
        (ErasureCodeInterface.h:336)."""
        return self.minimum_to_decode(want_to_read, set(available))

    @abstractmethod
    def encode(self, want_to_encode: set, data: bytes) -> dict:
        """Pad + split ``data`` into k chunks, compute m coding chunks, return
        {chunk_index: bytes} restricted to want_to_encode
        (ErasureCodeInterface.h:360)."""

    @abstractmethod
    def encode_chunks(self, data_chunks) -> "object":
        """Raw chunk-level encode: (.., k, B) uint8 -> (.., m, B) uint8."""

    @abstractmethod
    def decode(self, want_to_read: set, chunks: dict) -> dict:
        """Recover ``want_to_read`` chunk payloads from available
        {chunk_index: bytes} (ErasureCodeInterface.h:407)."""

    def decode_concat(self, chunks: dict) -> bytes:
        """Recover all data chunks and concatenate in rank order
        (ErasureCodeInterface.h:453)."""
        k = self.get_data_chunk_count()
        want = set(range(k))
        decoded = self.decode(want, chunks)
        return b"".join(decoded[i] for i in range(k))

    def get_chunk_mapping(self) -> list:
        """chunk_index -> raw position map; empty means identity
        (ErasureCodeInterface.h:432)."""
        return []

    def create_rule(self, name: str, crush_map) -> int:
        """Create the CRUSH rule this code's pools should use (indep placement;
        ErasureCode.cc:53-72).  Optional for pure-codec use."""
        raise NotImplementedError
