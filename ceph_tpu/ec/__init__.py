"""Erasure-code plugin framework.

Mirrors the reference's plugin architecture (src/erasure-code/): an abstract
interface contract (ErasureCodeInterface.h:170-462), a base class with shared
chunk math (ErasureCode.{h,cc}), a named-plugin registry (ErasureCodePlugin.cc),
and the plugin families jerasure / isa / shec / lrc / clay.  The compute path is
TPU-first: every plugin's encode/decode lowers to the batched GF(2^8) MXU matmul
in ceph_tpu.ops.gf_kernel (with the numpy oracle as the bit-exactness ground
truth and CPU fallback), instead of per-stripe SIMD calls.
"""

from .interface import ErasureCodeInterface
from .base import ErasureCode
from .registry import ErasureCodePluginRegistry, instance as registry_instance
from . import jerasure as _jerasure  # noqa: F401  (registers plugins on import)
from . import isa as _isa  # noqa: F401
from . import shec as _shec  # noqa: F401
from . import lrc as _lrc  # noqa: F401
from . import clay as _clay  # noqa: F401

__all__ = [
    "ErasureCodeInterface",
    "ErasureCode",
    "ErasureCodePluginRegistry",
    "registry_instance",
]
