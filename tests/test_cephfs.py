"""CephFS-lite: Journaler over RADOS, MDS namespace ops, journal replay
after an MDS crash, and striped file I/O through the FS client
(src/osdc/Journaler.cc, src/mds/, src/client/Client.cc analogs)."""

import time

import pytest

from ceph_tpu.cephfs import CephFS
from ceph_tpu.osdc.journaler import Journaler
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    meta = c.create_pool(client, pg_num=4, size=2)
    data = c.create_pool(client, pg_num=8, size=2)
    c.run_mds(meta, data)
    yield c
    c.stop()


@pytest.fixture
def fs(cluster):
    f = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    f.mount()
    yield f
    f.unmount()


# -- journaler ----------------------------------------------------------------

def test_journaler_append_flush_replay(cluster):
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=4, size=2)
    io = client.open_ioctx(pool)
    j = Journaler(io, "jtest")
    j.create()
    for i in range(20):
        j.append_entry(f"event-{i}".encode())
    j.flush()
    # a fresh journaler on the same stream replays everything
    j2 = Journaler(io, "jtest")
    j2.open()
    assert j2.write_pos == j.write_pos
    got = []
    assert j2.replay(lambda p, _e: got.append(p)) == 20
    assert got == [f"event-{i}".encode() for i in range(20)]
    # trim; replay is now empty
    j2.trim()
    j3 = Journaler(io, "jtest")
    j3.open()
    assert j3.replay(lambda p, _e: got.append(p)) == 0


def test_journaler_torn_tail_replays_short(cluster):
    client = cluster.client(timeout=20.0)
    pool = cluster.create_pool(client, pg_num=4, size=2)
    io = client.open_ioctx(pool)
    j = Journaler(io, "jtorn")
    j.create()
    j.append_entry(b"committed")
    j.flush()
    # simulate a torn flush: stream bytes appended, head never advanced
    j.stream.write(b"\xff\xff\xff\xff garbage", offset=j.write_pos)
    j2 = Journaler(io, "jtorn")
    j2.open()
    got = []
    assert j2.replay(lambda p, _e: got.append(p)) == 1
    assert got == [b"committed"]


# -- namespace ----------------------------------------------------------------

def test_mkdir_create_readdir_stat(fs):
    fs.mkdir("/a")
    fs.mkdir("/a/b")
    with fs.open("/a/b/hello.txt", "w") as f:
        f.write(b"hello fs")
    ents = fs.listdir("/a")
    assert "b" in ents
    ents = fs.listdir("/a/b")
    assert list(ents) == ["hello.txt"]
    st = fs.stat("/a/b/hello.txt")
    assert st["size"] == 8
    assert fs.stat("/a")["mode"] & 0o040000
    with pytest.raises(OSError):
        fs.mkdir("/a")          # EEXIST
    with pytest.raises(OSError):
        fs.stat("/nope/deep")   # ENOENT


def test_file_io_roundtrip_and_append(fs):
    payload = bytes(range(256)) * 1000   # 256 KB crosses stripe units
    with fs.open("/big.bin", "w") as f:
        f.write(payload)
    with fs.open("/big.bin") as f:
        assert f.read() == payload
    with fs.open("/big.bin", "a") as f:
        f.write(b"tail")
    with fs.open("/big.bin") as f:
        data = f.read()
    assert data == payload + b"tail"
    # partial read at offset
    with fs.open("/big.bin") as f:
        f.seek(1000)
        assert f.read(16) == payload[1000:1016]


def test_open_w_truncates(fs):
    with fs.open("/trunc", "w") as f:
        f.write(b"long original content")
    with fs.open("/trunc", "w") as f:
        f.write(b"new")
    st = fs.stat("/trunc")
    assert st["size"] == 3
    with fs.open("/trunc") as f:
        assert f.read() == b"new"


def test_rename_unlink_rmdir(fs):
    fs.mkdir("/mv")
    with fs.open("/mv/one", "w") as f:
        f.write(b"1")
    fs.rename("/mv/one", "/mv/two")
    assert list(fs.listdir("/mv")) == ["two"]
    with fs.open("/mv/two") as f:
        assert f.read() == b"1"
    with pytest.raises(OSError):
        fs.rmdir("/mv")         # ENOTEMPTY
    fs.unlink("/mv/two")
    fs.rmdir("/mv")
    with pytest.raises(OSError):
        fs.stat("/mv")


def test_mds_restart_replays_journal(cluster):
    """Metadata mutations survive an MDS crash: the journal replays on
    startup (up:replay) and the namespace converges."""
    fs = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    fs.mount()
    fs.mkdir("/crash")
    with fs.open("/crash/file", "w") as f:
        f.write(b"survives")
    meta, data = cluster.mds.metadata_pool, cluster.mds.data_pool
    fs.unmount()
    # hard kill: skip the clean-shutdown flush by not calling shutdown's
    # flush path — emulate by discarding dirty state before stopping
    cluster.mds._dirty_dirs.clear()
    cluster.mds._dirty_inodes.clear()
    cluster.mds.journal.trim_on_shutdown = False
    # prevent the shutdown flush+trim from persisting anything
    cluster.mds._flush_dirty = lambda: None
    cluster.mds.journal.trim = lambda *a, **k: None
    cluster.kill_mds()

    cluster.run_mds(meta, data)
    fs2 = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    fs2.mount()
    assert "file" in fs2.listdir("/crash")
    with fs2.open("/crash/file") as f:
        assert f.read() == b"survives"
    fs2.unmount()


def test_segment_boundary_never_loses_acked_mutations(cluster):
    """The 64-event segment roll must trim only AFTER the boundary event
    is applied: every acked mkdir survives a crash right at the roll."""
    fs = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    fs.mount()
    fs.mkdir("/seg")
    for i in range(70):   # crosses the 64-event segment boundary
        fs.mkdir(f"/seg/d{i}")
    meta, data = cluster.mds.metadata_pool, cluster.mds.data_pool
    fs.unmount()
    # hard crash: no clean-shutdown flush/trim
    cluster.mds._flush_dirty = lambda: None
    cluster.mds.journal.trim = lambda *a, **k: None
    cluster.kill_mds()
    cluster.run_mds(meta, data)
    fs2 = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    fs2.mount()
    ents = fs2.listdir("/seg")
    assert sorted(ents) == sorted(f"d{i}" for i in range(70)), \
        "acked mkdirs lost across the segment boundary"
    fs2.unmount()


def test_rename_journals_atomically(cluster):
    """A rename is one journal entry: replay can never leave the inode
    linked at both paths."""
    fs = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    fs.mount()
    fs.mkdir("/atomic")
    with fs.open("/atomic/src", "w") as f:
        f.write(b"x")
    events = []
    orig = cluster.mds._journal
    cluster.mds._journal = lambda ev: (events.append(ev), orig(ev))[1]
    fs.rename("/atomic/src", "/atomic/dst")
    cluster.mds._journal = orig
    renames = [e for e in events if e["e"] == "batch"]
    assert len(renames) == 1, "rename must journal one atomic batch"
    kinds = [s["e"] for s in renames[0]["events"]]
    assert kinds == ["link", "unlink"]
    fs.unmount()


def test_two_clients_share_namespace(cluster):
    a = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    b = CephFS(cluster.mon_host, cluster.mds.addr, ms_type="loopback")
    a.mount()
    b.mount()
    try:
        a.mkdir("/shared")
        with a.open("/shared/x", "w") as f:
            f.write(b"from-a")
        with b.open("/shared/x") as f:
            assert f.read() == b"from-a"
        assert "x" in b.listdir("/shared")
    finally:
        a.unmount()
        b.unmount()
