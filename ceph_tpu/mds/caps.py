"""Client capability logic (src/mds/Locker.cc:1-5357 + Capability.h,
reduced to the coherence-bearing core).

The reference's Locker runs a lock-state machine per inode (simplelock/
filelock/scatterlock) whose OBSERVABLE effect on clients is: which cap
bits each client may hold given who else has the file open.  This module
keeps exactly that observable contract and drops the internal lock-state
gearing:

  bits (CEPH_CAP_* reduced):
    RD      may read file data directly from RADOS
    WR      may write file data directly to RADOS
    CACHE   may trust cached attrs (size/mtime) without asking the MDS
            (Fc — "cache" — plus the As/Fs shared-attr caps folded in)
    BUFFER  may buffer dirty data + size locally and flush lazily
            (Fb — write-back is only legal while held)

  issue rules (Locker::issue_caps / file_eval observable behaviour):
    - a LONE opener gets everything it wants (loner: Fcb granted)
    - multiple openers, all readers -> RD|CACHE for everyone
    - any writer among multiple openers -> RD|WR only (sync mode:
      every read/write hits RADOS, sizes flow through the MDS)

Revocation is a seq-numbered round trip: the table records what each
client must drop; the server sends MClientCaps(revoke) and the request
that needed the revoke waits until every ack lands (clients flush dirty
data BEFORE acking — that ordering is the whole POSIX-coherence story).
"""

from __future__ import annotations

from dataclasses import dataclass, field

RD = 1
WR = 2
CACHE = 4
BUFFER = 8
ALL = RD | WR | CACHE | BUFFER

#: what an opener asks for by mode (Client::get_caps wanted sets)
WANT_READ = RD | CACHE
WANT_WRITE = RD | WR | CACHE | BUFFER


def caps_str(bits: int) -> str:
    """'rwcb'-style render (Capability::string analog) for logs/tests."""
    return "".join(ch for bit, ch in ((RD, "r"), (WR, "w"),
                                      (CACHE, "c"), (BUFFER, "b"))
                   if bits & bit) or "-"


@dataclass
class CapGrant:
    """One client's capability on one inode."""

    issued: int = 0          # bits the client currently holds
    wanted: int = 0          # bits the client asked for (re-eval input)
    pending: int = 0         # bits being revoked DOWN TO (revoke in flight)
    seq: int = 0             # revoke round-trip pairing


@dataclass
class InoCaps:
    grants: dict[int, CapGrant] = field(default_factory=dict)


class CapTable:
    """Pure cap bookkeeping for the MDS (no I/O, unit-testable).

    The server drives it with three calls:
      open_want(ino, client, wanted)  -> (granted | None, revokes)
         None means revokes are in flight: park the request and retry
         after acks.  revokes = [(client, new_caps, seq), ...] to send.
      ack(ino, client, seq)           -> True when that revoke completed
      release(ino, client)            -> regrants for remaining holders
    """

    def __init__(self):
        self._inos: dict[int, InoCaps] = {}

    # -- introspection -------------------------------------------------------

    def holders(self, ino: int) -> dict[int, int]:
        ic = self._inos.get(ino)
        if not ic:
            return {}
        return {c: g.issued for c, g in ic.grants.items()}

    def issued(self, ino: int, client: int) -> int:
        ic = self._inos.get(ino)
        if not ic or client not in ic.grants:
            return 0
        return ic.grants[client].issued

    def grant_seq(self, ino: int, client: int) -> int:
        ic = self._inos.get(ino)
        if not ic or client not in ic.grants:
            return 0
        return ic.grants[client].seq

    # -- the issue rule ------------------------------------------------------

    @staticmethod
    def _allowed(wants: dict[int, int]) -> int:
        """Max bits ANY holder may keep given everyone's wanted mode."""
        if len(wants) <= 1:
            return ALL
        if any(w & WR for w in wants.values()):
            return RD | WR          # mixed access: fully synchronous
        return RD | CACHE           # shared read-only: cacheable

    def _revoke_to(self, ic: InoCaps, client: int,
                   new_caps: int) -> tuple[int, int, int] | None:
        g = ic.grants[client]
        target = g.issued & new_caps
        if not g.issued & ~new_caps:
            return None             # nothing to drop
        if g.pending == target and g.seq:
            return None             # identical revoke already in flight
        g.pending = target
        g.seq += 1
        return (client, target, g.seq)

    def open_want(self, ino: int, client: int, wanted: int
                  ) -> tuple[int | None, list[tuple[int, int, int]]]:
        ic = self._inos.setdefault(ino, InoCaps())
        me = ic.grants.setdefault(client, CapGrant())
        me.wanted |= wanted
        wants = {c: g.wanted for c, g in ic.grants.items()}
        allowed = self._allowed(wants)
        revokes = []
        for c, g in ic.grants.items():
            if c == client:
                continue
            r = self._revoke_to(ic, c, allowed)
            if r:
                revokes.append(r)
        if any(g.seq and g.pending != g.issued
               for c, g in ic.grants.items() if c != client):
            # someone still holds more than allowed: caller parks
            return None, revokes
        if me.seq and me.pending != me.issued:
            # MY OWN earlier revoke is still in flight: granting now
            # would bump the seq and orphan that ack — park until it
            # lands (the ack reruns us)
            return None, revokes
        me.issued = me.wanted & allowed
        me.pending = me.issued
        me.seq += 1     # stamp the grant: the client installs it only
        return me.issued, revokes   # if no NEWER revoke was processed

    def recall(self, ino: int, bits: int, exclude: int | None = None
               ) -> list[tuple[int, int, int]]:
        """Revoke `bits` from every holder (e.g. BUFFER before a stat
        answers, so the size is fresh).  Returns revokes to send; empty
        means nothing outstanding — proceed."""
        ic = self._inos.get(ino)
        if not ic:
            return []
        revokes = []
        for c, g in ic.grants.items():
            if c == exclude or not g.issued & bits:
                continue
            r = self._revoke_to(ic, c, g.issued & ~bits)
            if r:
                revokes.append(r)
        return revokes

    def pending_revokes(self, ino: int, exclude: int | None = None) -> bool:
        ic = self._inos.get(ino)
        if not ic:
            return False
        return any(g.seq and g.pending != g.issued
                   for c, g in ic.grants.items() if c != exclude)

    def ack(self, ino: int, client: int, seq: int) -> bool:
        """Client confirmed the revoke (after flushing).  Stale seqs
        (an older round trip racing a newer revoke) are ignored."""
        ic = self._inos.get(ino)
        if not ic or client not in ic.grants:
            return False
        g = ic.grants[client]
        if seq != g.seq:
            return False
        g.issued = g.pending
        return True

    def reassert(self, ino: int, client: int, caps: int) -> None:
        """Failover rejoin: install a client-asserted grant wholesale
        (the new rank has no cap state; within the reconnect window the
        clients' view IS the truth — reference MDCache::rejoin)."""
        ic = self._inos.setdefault(ino, InoCaps())
        g = ic.grants.setdefault(client, CapGrant())
        g.issued = caps
        g.wanted = caps
        g.pending = caps
        g.seq = max(g.seq, 1)

    def force_drop(self, ino: int, client: int) -> None:
        """Evict one client's grant without an ack (dead session)."""
        ic = self._inos.get(ino)
        if ic:
            ic.grants.pop(client, None)
            if not ic.grants:
                del self._inos[ino]

    def release(self, ino: int, client: int
                ) -> list[tuple[int, int, int]]:
        """Client closed its last handle: drop its grant and compute
        UPGRADES for the remaining holders (a now-lone writer gets its
        buffer/cache back — Locker's eval on cap release).  Returns
        [(client, new_caps, seq)] grants to send (no ack needed:
        granting more never needs a flush)."""
        ic = self._inos.get(ino)
        if not ic:
            return []
        ic.grants.pop(client, None)
        if not ic.grants:
            del self._inos[ino]
            return []
        wants = {c: g.wanted for c, g in ic.grants.items()}
        allowed = self._allowed(wants)
        grants = []
        for c, g in ic.grants.items():
            new = g.wanted & allowed
            if new & ~g.issued and not (g.seq and g.pending != g.issued):
                g.issued = new
                g.pending = new
                g.seq += 1      # cap-change ordering token (clients
                grants.append((c, new, g.seq))  # drop stale installs)
        return grants

    def drop_client(self, client: int) -> list[int]:
        """Session death: drop every grant; returns touched inos (the
        caller re-evals waiters/upgrades on each)."""
        touched = []
        for ino in list(self._inos):
            if client in self._inos[ino].grants:
                touched.append(ino)
                self.force_drop(ino, client)
        return touched
