"""ceph_tpu.analysis — the whole-tree concurrency + jit-boundary
static analyzer.

Per check family: one positive case (the check fires on a fixture
snippet) and one negative (clean idiom passes).  Plus the cycle
witness formatting, the suppression/baseline workflow, and the
tree-wide gate every future PR rides on: the real ``ceph_tpu``
package must produce ZERO unsuppressed findings.
"""

import os
import textwrap

import ceph_tpu
from ceph_tpu import analysis
from ceph_tpu.analysis import core, lock_order


def _tree(tmp_path, files: dict) -> core.TreeIndex:
    pkg = tmp_path / "pkg"
    pkg.mkdir(parents=True)
    for name, src in files.items():
        path = pkg / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return core.TreeIndex.build(str(pkg))


def _run(tmp_path, files, checks):
    pkg = tmp_path / "pkg"
    if not pkg.exists():
        _tree(tmp_path, files)
    return analysis.run(str(pkg), checks=checks)


# -- bare-lock ----------------------------------------------------------------

class TestBareLock:
    def test_fires_on_bare_locks(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading
            L = threading.Lock()
            class A:
                def __init__(self):
                    self.cv = threading.Condition()
            """}, checks=("bare-lock",))
        codes = sorted(f.code for f in rep.findings)
        assert codes == ["condition", "lock"]

    def test_clean_on_make_lock(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            from ceph_tpu.common import lockdep
            L = lockdep.make_lock("M::lock")
            CV = lockdep.make_condition("M::cv")
            """}, checks=("bare-lock",))
        assert rep.findings == []

    def test_inline_suppression(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading
            # analysis: allow[bare-lock] -- import-time leaf lock
            L = threading.Lock()
            """}, checks=("bare-lock",))
        assert rep.findings == []
        assert len(rep.suppressed) == 1
        assert rep.suppressed[0][1] == "import-time leaf lock"


# -- lock-order ---------------------------------------------------------------

_CYCLE_SRC = {"m.py": """
    import threading
    class A:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()
        def f(self):
            with self.a:
                self.helper()
        def helper(self):
            with self.b:
                pass
        def g(self):
            with self.b:
                with self.a:
                    pass
    """}


class TestLockOrder:
    def test_interprocedural_cycle_fires(self, tmp_path):
        rep = _run(tmp_path, _CYCLE_SRC, checks=("lock-order",))
        assert len(rep.findings) == 1
        f = rep.findings[0]
        # distinct cycles keep distinct baseline keys: the node set
        # rides the code
        assert f.code == "cycle:pkg.m.A.a+pkg.m.A.b"
        # both witness directions present, with file:line sites
        assert "pkg.m.A.a" in f.message and "pkg.m.A.b" in f.message
        assert f.message.count("m.py:") >= 2

    def test_consistent_order_clean(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading
            class A:
                def __init__(self):
                    self.a = threading.Lock()
                    self.b = threading.Lock()
                def f(self):
                    with self.a:
                        with self.b:
                            pass
                def g(self):
                    with self.a:
                        self.h()
                def h(self):
                    with self.b:
                        pass
            """}, checks=("lock-order",))
        assert rep.findings == []

    def test_runtime_graph_union(self, tmp_path):
        idx = _tree(tmp_path, {"m.py": """
            import threading
            class A:
                def __init__(self):
                    self.a = threading.Lock()
                    self.b = threading.Lock()
                def f(self):
                    with self.a:
                        with self.b:
                            pass
            """})
        # static a->b alone is clean; a runtime-recorded b->a closes
        # the cycle (the union the analyzer exists for)
        clean = lock_order.check(idx, runtime_graph=None)
        assert clean == []
        runtime = {"edges": [{"a": "pkg.m.A.b", "b": "pkg.m.A.a",
                              "site": "osd/daemon.py tick thread"}]}
        dirty = lock_order.check(idx, runtime_graph=runtime)
        assert len(dirty) == 1
        assert "runtime: osd/daemon.py tick thread" in dirty[0].message

    def test_deferred_closure_definition_is_not_a_hold_edge(
            self, tmp_path):
        """Defining a continuation under lock A whose body later takes
        B must NOT record A->B: the closure runs on another thread
        with an empty held stack (the engine's standard
        define-continuation-under-cv idiom).  A synchronously-CALLED
        local helper still propagates normally."""
        rep = _run(tmp_path, {"m.py": """
            import threading
            class A:
                def __init__(self):
                    self.a = threading.Lock()
                    self.b = threading.Lock()
                def deferred(self, fut):
                    with self.a:
                        def cont(f):
                            with self.b:
                                pass
                        fut.add_done_callback(cont)
                def other(self):
                    with self.b:
                        with self.a:
                            pass
            """}, checks=("lock-order",))
        assert rep.findings == []

        rep2 = _run(tmp_path / "sync", {"m.py": """
            import threading
            class A:
                def __init__(self):
                    self.a = threading.Lock()
                    self.b = threading.Lock()
                def f(self):
                    with self.a:
                        def h():
                            with self.b:
                                pass
                        h()            # called synchronously: a->b
                def other(self):
                    with self.b:
                        with self.a:
                            pass
            """}, checks=("lock-order",))
        assert len(rep2.findings) == 1

    def test_edge_suppression_breaks_cycle(self, tmp_path):
        src = dict(_CYCLE_SRC)
        src["m.py"] = src["m.py"].replace(
            "with self.b:\n                with self.a:",
            "with self.b:\n"
            "                # analysis: allow[lock-order] -- "
            "documented inversion\n"
            "                with self.a:")
        rep = _run(tmp_path, src, checks=("lock-order",))
        assert rep.findings == []

    def test_shared_condition_lock_aliases_one_node(self, tmp_path):
        """make_condition(name, lock=self._lock) shares ONE lock: an
        inversion through the condition must merge with the mutex's
        node, not hide behind a second name."""
        rep = _run(tmp_path, {"m.py": """
            from ceph_tpu.common import lockdep
            import threading
            class A:
                def __init__(self):
                    self.lk = lockdep.make_lock("A::lock")
                    self.cv = lockdep.make_condition("A::cv",
                                                     lock=self.lk)
                    self.b = threading.Lock()
                def f(self):
                    with self.lk:
                        with self.b:
                            pass
                def g(self):
                    with self.b:
                        with self.cv:
                            pass
            """}, checks=("lock-order",))
        assert len(rep.findings) == 1
        assert "A::lock" in rep.findings[0].message

    def test_cycle_witness_formatting(self):
        edges = {("X", "Y"): "a.py:10 in pkg.a.f",
                 ("Y", "X"): "runtime: b.py:20"}
        msg = lock_order.format_cycle(["X", "Y", "X"], edges)
        assert msg.startswith("lock-order cycle: ")
        assert "X -> Y  [a.py:10 in pkg.a.f]" in msg
        assert "Y -> X  [runtime: b.py:20]" in msg


# -- blocking -----------------------------------------------------------------

class TestBlocking:
    def test_fires_in_callback_reachable_code(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import time
            class E:
                def go(self, fut):
                    fut.add_done_callback(self.cb)
                def cb(self, f):
                    self.helper()
                def helper(self):
                    time.sleep(0.1)
                    w = self.make()
                    w.result()
                    self.lk.acquire(timeout=-1)   # block-forever
                    self.lk.acquire(timeout=2.0)  # bounded: exempt
            """}, checks=("blocking",))
        codes = sorted(f.code for f in rep.findings)
        assert codes == ["acquire", "future-wait", "sleep"]
        assert all("completion callback" in f.message
                   for f in rep.findings)

    def test_own_future_read_and_lock_section_clean(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            class E:
                def go(self, fut):
                    fut.add_done_callback(self.cb)
                def cb(self, f):
                    v = f.result()     # already complete: fine
                    with self.lock:    # bounded exclusion: fine
                        self.x = v
            """}, checks=("blocking",))
        assert rep.findings == []

    def test_attr_stored_future_wait_still_flagged(self, tmp_path):
        """The parameter exemption is for DIRECT parameter reads only:
        waiting on a future reached through `self` (create-then-wait
        on the completion thread) is the self-deadlock case."""
        rep = _run(tmp_path, {"m.py": """
            class E:
                def go(self, fut):
                    fut.add_done_callback(self.cb)
                def cb(self, f):
                    self._w = self.eng.submit(("k",), None, None)
                    self._w.result()
            """}, checks=("blocking",))
        assert [f.code for f in rep.findings] == ["future-wait"]

    def test_two_lambdas_one_line_both_scanned(self, tmp_path):
        """Two callbacks registered on one source line must get
        distinct nodes — a clean second lambda must not shadow the
        blocking first one."""
        rep = _run(tmp_path, {"m.py": """
            import time
            class E:
                def go(self, fa, fb):
                    fa.add_done_callback(lambda f: time.sleep(1)); fb.add_done_callback(lambda f: f.done())
            """}, checks=("blocking",))
        assert [f.code for f in rep.findings] == ["sleep"]

    def test_non_callback_code_not_flagged(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import time
            def plain():
                time.sleep(1)      # not on a completion path
            """}, checks=("blocking",))
        assert rep.findings == []


# -- jit-purity ---------------------------------------------------------------

class TestJitPurity:
    def test_fires_on_impure_jitted_fn(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import time, jax
            @jax.jit
            def k(x):
                t = time.time()
                print("tracing", t)
                return x
            """}, checks=("jit-purity",))
        codes = sorted(f.code for f in rep.findings)
        assert codes == ["clock", "logging"]

    def test_fires_on_engine_closure_mutation_and_conf(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            def submit_it(eng, ctx, data, state):
                def fn(batch):
                    state["n"] = 1
                    if ctx.conf.get("kernel_dispatch_depth"):
                        pass
                    return batch
                return eng.submit(("k",), fn, data)
            """}, checks=("jit-purity",))
        codes = sorted(f.code for f in rep.findings)
        assert codes == ["conf", "mutation"]
        assert "dispatch engine" in rep.findings[0].message

    def test_pure_kernel_clean(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import jax, jax.numpy as jnp
            @jax.jit
            def k(x):
                acc = {}
                acc["y"] = jnp.dot(x, x)   # local scaffolding: fine
                return acc["y"]
            """}, checks=("jit-purity",))
        assert rep.findings == []

    def test_placement_scaffolding_store_exempt(self, tmp_path):
        """The mesh-dispatch idiom: an engine closure caching a
        jax.device_put/NamedSharding placement into captured state is
        host-side sharding scaffolding (it runs on the engine thread
        outside any trace), NOT a tracer leak — no mutation finding."""
        rep = _run(tmp_path, {"m.py": """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            def submit_it(eng, data, tables, ids):
                def fn(batch):
                    mesh = batch.sharding.mesh
                    ops = tables.get("ops")
                    if ops is None:
                        ops = tables["ops"] = jax.device_put(
                            ids, NamedSharding(mesh, PartitionSpec()))
                    return ops
                return eng.submit(("k",), fn, data)
            """}, checks=("jit-purity",))
        assert rep.findings == []

    def test_non_placement_store_still_fires(self, tmp_path):
        """The exemption is scoped to placement construction: the same
        captured-store shape WITHOUT device_put/NamedSharding on the
        right-hand side stays a mutation finding."""
        rep = _run(tmp_path, {"m.py": """
            def submit_it(eng, data, tables, ids):
                def fn(batch):
                    ops = tables.get("ops")
                    if ops is None:
                        ops = tables["ops"] = (ids, batch.shape)
                    return ops
                return eng.submit(("k",), fn, data)
            """}, checks=("jit-purity",))
        codes = sorted(f.code for f in rep.findings)
        assert codes == ["mutation"]

    def test_jit_traced_placement_store_still_fires(self, tmp_path):
        """The exemption is scoped to engine submit closures: inside a
        function genuinely TRACED by jax.jit the same device_put store
        runs once at trace time and never on cache hits — it stays a
        mutation finding."""
        rep = _run(tmp_path, {"m.py": """
            import jax
            @jax.jit
            def k(x, cache):
                cache["dev"] = jax.device_put(x)
                return x
            """}, checks=("jit-purity",))
        codes = sorted(f.code for f in rep.findings)
        assert codes == ["mutation"]

    def test_compound_rhs_with_placement_still_fires(self, tmp_path):
        """The exemption covers stores whose WHOLE value is placement
        construction: a compound RHS smuggling other state next to a
        device_put stays a mutation finding."""
        rep = _run(tmp_path, {"m.py": """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            def submit_it(eng, data, tables, ids):
                def fn(batch):
                    if "ops" not in tables:
                        tables["ops"] = (batch.sum(), jax.device_put(
                            ids, NamedSharding(batch.sharding.mesh,
                                               PartitionSpec())))
                    return tables["ops"]
                return eng.submit(("k",), fn, data)
            """}, checks=("jit-purity",))
        codes = sorted(f.code for f in rep.findings)
        assert codes == ["mutation"]


# -- registry -----------------------------------------------------------------

class TestRegistry:
    FILES = {
        "config.py": """
            class Option:
                def __init__(self, name, *a, **k):
                    self.name = name
            OPTIONS = {}
            def register_options(opts):
                pass
            register_options([Option("real_option", "int", 1)])
            """,
        "perf.py": """
            class PerfCountersBuilder:
                def __init__(self, name): ...
            def build():
                return (PerfCountersBuilder("osd")
                        .add_u64("real_counter")
                        .create_perf_counters())
            """,
        "user.py": """
            def f(ctx, perf):
                ctx.conf.get("real_option")
                ctx.conf.get("typo_option")
                perf.inc("real_counter")
                perf.inc("typo_counter")
            """,
    }

    def test_fires_on_unknown_key_and_counter(self, tmp_path):
        rep = _run(tmp_path, self.FILES, checks=("registry",))
        assert sorted(f.code for f in rep.findings) == \
            ["conf-key", "perf-counter"]
        assert "typo_option" in rep.findings[0].message
        assert "typo_counter" in rep.findings[1].message

    def test_known_names_clean(self, tmp_path):
        files = dict(self.FILES)
        files["user.py"] = """
            def f(ctx, perf):
                ctx.conf.get("real_option")
                perf.inc("real_counter")
            """
        rep = _run(tmp_path, files, checks=("registry",))
        assert rep.findings == []


# -- thread-except ------------------------------------------------------------

class TestThreadExcept:
    def test_fires_on_swallowed_base_exception(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading

            def loop():
                while True:
                    try:
                        step()
                    except BaseException:
                        pass

            def step():
                return 1

            def start():
                threading.Thread(target=loop, daemon=True).start()
            """}, checks=("thread-except",))
        assert [f.code for f in rep.findings] == ["swallow"]
        assert "loop" in rep.findings[0].message

    def test_fires_on_bare_except_in_thread_subclass_run(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading

            class Worker(threading.Thread):
                def run(self):
                    while True:
                        try:
                            self.step()
                        except:
                            continue

                def step(self):
                    return 1
            """}, checks=("thread-except",))
        assert [f.code for f in rep.findings] == ["swallow"]

    def test_fires_through_call_graph(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading

            def loop():
                helper()

            def helper():
                try:
                    work()
                except BaseException as e:
                    del e      # bound but never READ: still swallowed

            def work():
                return 1

            def start():
                threading.Thread(target=loop).start()
            """}, checks=("thread-except",))
        assert [f.code for f in rep.findings] == ["swallow"]

    def test_delivering_and_reraising_handlers_clean(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading

            def loop():
                exc = None
                while True:
                    try:
                        step()
                    except BaseException as e:
                        exc = e          # delivered to the waiter
                    try:
                        step()
                    except BaseException:
                        raise            # re-raised to the supervisor
                    try:
                        step()
                    except ValueError:
                        pass             # narrow catch: normal absorb
                    try:
                        step()
                    except Exception:
                        continue         # Exception (not Base): fine
                return exc

            def step():
                return 1

            def start():
                threading.Thread(target=loop).start()
            """}, checks=("thread-except",))
        assert rep.findings == []

    def test_not_flagged_outside_thread_paths(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            def plain_helper():
                try:
                    work()
                except BaseException:
                    pass       # not reachable from any thread body

            def work():
                return 1
            """}, checks=("thread-except",))
        assert rep.findings == []

    def test_inline_suppression(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading

            def loop():
                try:
                    step()
                except BaseException:  # analysis: allow[thread-except] -- fixture
                    pass

            def step():
                return 1

            def start():
                threading.Thread(target=loop).start()
            """}, checks=("thread-except",))
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- baseline workflow --------------------------------------------------------

class TestBaseline:
    def test_diff_and_roundtrip(self, tmp_path):
        rep = _run(tmp_path, {"m.py": """
            import threading
            L = threading.Lock()
            """}, checks=("bare-lock",))
        assert len(rep.findings) == 1
        path = str(tmp_path / "baseline.txt")
        analysis.save_baseline(path, rep.findings)
        baseline = analysis.load_baseline(path)
        new, stale = analysis.diff_baseline(rep, baseline)
        assert new == [] and stale == []
        # a fixed finding becomes a stale entry; a fresh one is new
        empty = analysis.Report()
        new, stale = analysis.diff_baseline(empty, baseline)
        assert new == [] and len(stale) == 1
        new, stale = analysis.diff_baseline(rep, set())
        assert len(new) == 1 and stale == []

    def test_cli_json_and_exit_codes(self, tmp_path, capsys):
        import json
        from ceph_tpu.analysis.__main__ import main
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "m.py").write_text("import threading\n"
                                  "L = threading.Lock()\n")
        bl = str(tmp_path / "bl.txt")
        rc = main([str(pkg), "--json", "--baseline", bl,
                   "--checks", "bare-lock"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1 and out["exit"] == 1
        assert out["findings"][0]["check"] == "bare-lock"
        # accept into the baseline -> clean run exits 0
        assert main([str(pkg), "--write-baseline", "--baseline", bl,
                     "--checks", "bare-lock"]) == 0
        capsys.readouterr()
        assert main([str(pkg), "--baseline", bl,
                     "--checks", "bare-lock"]) == 0


# -- the tree-wide gate -------------------------------------------------------

class TestTreeGate:
    def test_ceph_tpu_is_clean(self):
        """THE gate: the real package, every check, zero unsuppressed
        findings beyond the checked-in baseline (kept empty).  A new
        finding here means fix it or justify an inline suppression —
        see docs/STATIC_ANALYSIS.md."""
        root = os.path.dirname(os.path.abspath(ceph_tpu.__file__))
        rep = analysis.run(root)
        baseline = analysis.load_baseline(
            analysis.default_baseline_path())
        new, _stale = analysis.diff_baseline(rep, baseline)
        assert new == [], (
            "new static-analysis findings:\n"
            + "\n".join(f.render() for f in new))

    def test_every_family_has_runtime_coverage(self):
        """The gate is only meaningful if the checks have real targets
        in this tree: assert the fact extraction still sees jit
        targets, completion callbacks, named locks, and the option
        table (a refactor that silently blinds a check family would
        otherwise pass the gate forever)."""
        from ceph_tpu.analysis import blocking, jit_purity, \
            registry_lint, thread_except
        root = os.path.dirname(os.path.abspath(ceph_tpu.__file__))
        idx = core.TreeIndex.build(root)
        assert len(jit_purity._targets(idx)) >= 4
        assert len(blocking._roots(idx)) >= 3
        # thread run-loop roots: the supervised engine loops, the
        # probe loop, daemon threads, Thread-subclass run()s
        assert len(thread_except._thread_roots(idx)) >= 4
        edges = lock_order.build_graph(idx)
        assert len(edges) >= 10
        assert "osdmap_mapping_shared" in \
            registry_lint._option_names(idx)
        assert "ec_dispatch_submits" in \
            registry_lint._registered_counters(idx)
