"""SHEC — Shingled Erasure Code plugin (src/erasure-code/shec/ analog).

Profile (k, m, c): k data chunks, m local parities, durability goal c.
Each parity covers a sliding window ("shingle") of l = ceil(k*c/m) data
chunks, the windows overlapping around the ring so a SINGLE failure is
repaired from one window — l chunk reads instead of k, the
recovery-bandwidth trade SHEC exists for (ErasureCodeShec.cc).

Window coefficients come from a Cauchy construction restricted to the
window, so any square subsystem drawn from full windows is invertible.
SHEC is not MDS: decode solves the surviving parity equations for ALL
erased data chunks by GF(2^8) Gauss-Jordan and reports cleanly when a
pattern is unrecoverable; erased parities are then re-encoded from the
restored data.  minimum_to_decode prefers the smallest covering window
(ErasureCodeShec::minimum_to_decode semantics: cheapest recovery set).

The batched compute path is shared with every other plugin: encode is
the (S, k, B) MXU matmul (the generator simply has zeros outside the
windows), decode multiplies by the solved recovery matrix.
"""

from __future__ import annotations

from itertools import combinations

import numpy as np

from ceph_tpu.gf.tables import gf_inv, gf_mul, mul_table


def _mul_vec(coef: int, arr: np.ndarray) -> np.ndarray:
    """scalar * vector over GF(2^8), one table-row gather."""
    return mul_table()[coef][arr]

from .base import ErasureCode
from .interface import ErasureCodeProfile
from .registry import register


def _gf_solve(a: np.ndarray, b: np.ndarray):
    """Gauss-Jordan over GF(2^8): solve a x = b; None if singular.
    a (n, n), b (n, w) uint8."""
    n = a.shape[0]
    a = a.astype(np.int64).copy()
    b = b.astype(np.int64).copy()
    for col in range(n):
        piv = None
        for row in range(col, n):
            if a[row, col]:
                piv = row
                break
        if piv is None:
            return None
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            b[[col, piv]] = b[[piv, col]]
        inv = gf_inv(int(a[col, col]))
        a[col] = [gf_mul(int(v), inv) for v in a[col]]
        b[col] = [gf_mul(int(v), inv) for v in b[col]]
        for row in range(n):
            if row != col and a[row, col]:
                f = int(a[row, col])
                a[row] ^= np.array([gf_mul(int(v), f) for v in a[col]],
                                   dtype=np.int64)
                b[row] ^= np.array([gf_mul(int(v), f) for v in b[col]],
                                   dtype=np.int64)
    return b.astype(np.uint8)


class ErasureCodeShec(ErasureCode):
    _PROFILE_KEYS = ErasureCode._PROFILE_KEYS + ("c",)

    supports_rmw_striping = False

    def __init__(self):
        super().__init__()
        self.c = 0
        #: (frozenset targets, frozenset available) -> recovery plan;
        #: the combinatorial search must not re-run per degraded read
        #: (_decode_cache pattern, base.py)
        self._plan_cache: dict = {}

    def _default_k(self) -> int:
        return 4

    def _default_m(self) -> int:
        return 3

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.c = self.to_int("c", profile, 2)
        if not (1 <= self.c <= self.m <= self.k):
            raise ValueError(
                f"shec requires 1 <= c={self.c} <= m={self.m} <= k={self.k}")

    # -- shingle geometry -----------------------------------------------------

    def window(self, j: int) -> list[int]:
        """Data chunks covered by parity j (the j-th shingle)."""
        k, m, c = self.k, self.m, self.c
        length = -(-k * c // m)             # ceil(k*c/m): shingle width
        start = (j * k) // m
        return [(start + i) % k for i in range(length)]

    def _build_generator(self) -> np.ndarray:
        k, m = self.k, self.m
        g = np.zeros((k + m, k), dtype=np.uint8)
        g[:k] = np.eye(k, dtype=np.uint8)
        # Cauchy coefficients 1/(x_j ^ y_i) with disjoint supports: every
        # square submatrix of a Cauchy matrix is invertible, which keeps
        # overlapping-window systems solvable whenever ranks allow
        for j in range(m):
            for i in self.window(j):
                g[k + j, i] = gf_inv((k + j) ^ 255 ^ i)
        return g

    # -- recovery planning ----------------------------------------------------

    def _recovery_plan(self, target_data: set, available: set):
        """(rows, unknowns, rmat): chunks to read (`rows`, in order) and
        the GF matrix mapping them to sorted(unknowns), where unknowns
        is the smallest erased-data set covering `target_data` that the
        chosen parity equations close over; None if unrecoverable.

        Every erased data chunk REFERENCED by a selected parity is an
        unknown — equations are never used "partially" (dropping erased
        terms corrupts output) — but erased chunks outside all selected
        windows stay out of the system entirely, which is what makes
        single-window local repair possible.
        """
        targets = sorted(target_data)
        if not targets:
            return [], [], np.zeros((0, 0), dtype=np.uint8)
        cache_key = (frozenset(targets), frozenset(available))
        if cache_key in self._plan_cache:
            return self._plan_cache[cache_key]
        if len(self._plan_cache) > 256:
            self._plan_cache.clear()
        g = self.generator
        erased_data = {i for i in range(self.k) if i not in available}
        parities = [p for p in sorted(available) if p >= self.k]
        for n_par in range(1, len(parities) + 1):
            for combo in combinations(parities, n_par):
                unknowns = sorted(
                    {d for p in combo for d in self.window(p - self.k)
                     if d in erased_data} | set(targets))
                if len(combo) < len(unknowns):
                    continue
                a = np.array([[g[p, d] for d in unknowns] for p in combo],
                             dtype=np.uint8)
                for eqs in combinations(range(n_par), len(unknowns)):
                    sub = a[list(eqs)]
                    inv = _gf_solve(sub,
                                    np.eye(len(unknowns), dtype=np.uint8))
                    if inv is None:
                        continue
                    sel = [combo[e] for e in eqs]
                    known = sorted({i for p in sel
                                    for i in self.window(p - self.k)
                                    if i not in erased_data})
                    if not all(i in available for i in known):
                        continue
                    rows = known + sel
                    rmat = np.zeros((len(unknowns), len(rows)),
                                    dtype=np.uint8)
                    for out_i in range(len(unknowns)):
                        for eq_i, p in enumerate(sel):
                            coef = int(inv[out_i, eq_i])
                            if not coef:
                                continue
                            rmat[out_i, rows.index(p)] ^= coef
                            for d in known:
                                gpd = int(g[p, d])
                                if gpd:
                                    rmat[out_i, rows.index(d)] ^= gf_mul(
                                        coef, gpd)
                    plan = (rows, unknowns, rmat)
                    self._plan_cache[cache_key] = plan
                    return plan
        self._plan_cache[cache_key] = None
        return None

    # -- minimum_to_decode (shec flavor: cheapest covering set) ---------------

    def _targets_for(self, want_to_read: set, available: set) -> set:
        """Erased data chunks that must be restored to serve the read:
        the wanted ones, plus the window data behind any wanted parity
        (a parity re-encodes from its window only — zeros elsewhere)."""
        targets = {i for i in want_to_read
                   if i < self.k and i not in available}
        for p in want_to_read:
            if p >= self.k and p not in available:
                targets |= {d for d in self.window(p - self.k)
                            if d not in available}
        return targets

    def minimum_to_decode(self, want_to_read: set, available: set) -> set:
        got = want_to_read & available
        missing = want_to_read - available
        if not missing:
            return set(got)
        targets = self._targets_for(want_to_read, available)
        need: set = set()
        if targets:
            plan = self._recovery_plan(targets, available)
            if plan is None:
                raise IOError(f"shec cannot decode {sorted(missing)}")
            need |= set(plan[0])
        # a lost parity additionally reads its surviving window data
        for p in missing:
            if p >= self.k:
                need |= {d for d in self.window(p - self.k)
                         if d in available}
        return (need | got) - missing

    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: dict) -> tuple[set, int]:
        chosen = self.minimum_to_decode(set(want_to_read), set(available))
        return chosen, sum(available.get(i, 1) for i in chosen)

    # -- decode ---------------------------------------------------------------

    def decode(self, want_to_read: set, chunks: dict) -> dict:
        available = set(chunks)
        out = {i: chunks[i] for i in want_to_read & available}
        missing = sorted(want_to_read - available)
        if not missing:
            return out
        data: dict[int, np.ndarray] = {
            i: np.frombuffer(chunks[i], dtype=np.uint8)
            for i in range(self.k) if i in available}
        targets = self._targets_for(set(want_to_read), available)
        if targets:
            plan = self._recovery_plan(targets, available)
            if plan is None:
                raise IOError(f"shec cannot decode {missing}")
            rows, unknowns, rmat = plan
            arr = np.stack([np.frombuffer(chunks[i], dtype=np.uint8)
                            for i in rows])
            if self.runtime == "cpu":
                from ceph_tpu.ops.gf_kernel import ec_encode_ref
                rebuilt = ec_encode_ref(rmat, arr[None])[0]
            else:
                from ceph_tpu.ops.gf_kernel import ec_encode_jax
                rebuilt = np.asarray(ec_encode_jax(rmat, arr[None]))[0]
            for idx, i in enumerate(unknowns):
                data[i] = rebuilt[idx]
        for i in missing:
            if i < self.k:
                out[i] = data[i].tobytes()
        # a lost parity re-encodes from its window (zeros elsewhere)
        g = self.generator
        for p in missing:
            if p < self.k:
                continue
            acc = None
            for d in self.window(p - self.k):
                term_coef = int(g[p, d])
                term = np.zeros_like(next(iter(data.values()))) \
                    if term_coef == 0 else _mul_vec(term_coef, data[d])
                acc = term if acc is None else (acc ^ term)
            out[p] = acc.tobytes()
        return out


register("shec", lambda profile: ErasureCodeShec())
