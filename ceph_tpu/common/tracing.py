"""Cross-daemon trace spans (src/tracing/oprequest.tp +
src/common/zipkin_trace.h analogs, redesigned for this runtime).

A trace id rides the message frame (a flagged header extension, see
msg.message): the client opens a trace around an op, every message the
handling thread sends while dispatching inherits the id, and every
daemon records (trace_id, daemon, event, t) span events into its
process-local ring.  One EC write therefore leaves a reconstructible
client → primary → shard timeline; ``dump(trace_id)`` stitches the
events time-ordered, and daemons expose the same via the admin socket
(``dump_traces``).

Propagation is THREAD-SCOPED: the dispatch loop sets the current trace
for the duration of handling a traced message, so synchronous fan-out
(the op pipeline) is covered; work handed to timers/workers starts
untraced unless it re-enters with trace_ctx.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager

_tls = threading.local()
_lock = threading.Lock()
#: (trace_id, daemon, event, t) ring — per process; every in-process
#: daemon shares it (multi-process daemons each hold their own and the
#: operator stitches admin-socket dumps)
_events: deque = deque(maxlen=20000)


def new_trace_id() -> int:
    return int.from_bytes(os.urandom(8), "big") >> 1 or 1


def current() -> int:
    return getattr(_tls, "trace_id", 0)


def set_current(trace_id: int) -> int:
    """Install trace_id as the thread's current; returns the previous
    (restore it via set_current when done)."""
    prev = getattr(_tls, "trace_id", 0)
    _tls.trace_id = trace_id
    return prev


@contextmanager
def trace_ctx(trace_id: int | None = None):
    """Open (or join) a trace for the calling thread."""
    tid = trace_id or new_trace_id()
    prev = set_current(tid)
    try:
        yield tid
    finally:
        set_current(prev)


def record(daemon: str, event: str, trace_id: int | None = None) -> None:
    tid = trace_id if trace_id is not None else current()
    if not tid:
        return
    with _lock:
        _events.append((tid, daemon, event, time.time()))


def stamp(msg, daemon: str) -> None:
    """Transport send hook: a message sent by a thread holding a trace
    inherits the id (once), and the send is recorded as a span event.
    Runs on the CALLER's thread — transports that encode later on an
    event loop still carry the id because it is stored on the message."""
    if getattr(msg, "trace_id", 0):
        return
    tid = current()
    if not tid:
        return
    msg.trace_id = tid
    record(daemon, f"tx {type(msg).__name__}", tid)


def events(trace_id: int) -> list[dict]:
    with _lock:
        snap = list(_events)
    return [{"daemon": d, "event": e, "t": t}
            for tid, d, e, t in snap if tid == trace_id]


def dump(trace_id: int | None = None) -> list[dict]:
    """Stitched timeline(s), time-ordered — the admin-socket payload."""
    with _lock:
        snap = list(_events)
    rows = [{"trace_id": tid, "daemon": d, "event": e, "t": t}
            for tid, d, e, t in snap
            if trace_id is None or tid == trace_id]
    rows.sort(key=lambda r: r["t"])
    return rows


def trace_ids() -> list[int]:
    with _lock:
        return sorted({tid for tid, *_ in _events})
