"""ceph_tpu.analysis — whole-tree concurrency + jit-boundary static
analyzer.

The reference treats lock-order checking as a first-class subsystem
(src/common/lockdep.{h,cc}): every named mutex feeds one global order
graph and a cycle aborts the process.  Runtime lockdep only sees the
interleavings a run happens to produce; this package complements it
with AST-level *may* analysis over the whole tree, so the concurrency
and jit-purity invariants the dispatch/decode/mapping hot paths
established by convention are mechanically enforced on every PR.

Six check families (one module each):

* ``lock-order``  — static may-hold-A-while-taking-B graph, propagated
  inter-procedurally and unioned with the runtime
  ``common/lockdep.py`` graph; cycles report a witness path per edge.
* ``bare-lock``   — ``threading.Lock/RLock/Condition`` constructed
  outside ``lockdep.make_lock``/``make_condition`` is invisible to
  runtime lockdep and is a finding.
* ``blocking``    — ``.result()``, blocking ``acquire()``,
  ``time.sleep`` and host-sync calls reachable from dispatch/
  completion-thread callbacks (they deadlock or stall the
  double-buffered pipeline).
* ``jit-purity``  — functions handed to ``jax.jit`` or the dispatch
  engines must not read clocks/randomness/config, log, or mutate
  captured state (retrace + correctness hazards).
* ``registry``    — every ``conf.get(key)`` key must exist in
  ``common/config.py``'s option table; every perf-counter mutation
  must name a counter registered in its ``PerfCounters`` set.
* ``thread-except`` — ``except`` handlers catching ``BaseException``
  (or bare) reachable from thread run-loops must re-raise or deliver
  the exception to a waiter/supervisor; a swallowed loop error
  strands every future behind it.

Findings diff against a checked-in baseline (``baseline.txt``, driven
to empty) and per-line suppressions:

    some_flagged_line()   # analysis: allow[check-id] -- justification

Run ``python -m ceph_tpu.analysis`` (no third-party deps; stdlib
``ast`` only — the modules in THIS package must never import jax,
numpy, or the kernel stack, so the gate stays a few seconds of parse
work).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

CHECKS = ("lock-order", "bare-lock", "blocking", "jit-purity",
          "registry", "thread-except")


@dataclass(frozen=True)
class Finding:
    check: str          # check family id ("bare-lock", ...)
    path: str           # repo-relative posix path
    line: int           # 1-based; 0 for tree-level findings (cycles)
    code: str           # stable short code within the family
    message: str

    def key(self) -> str:
        """Baseline identity: check + anchor site + code, WITHOUT the
        message — messages embed volatile detail (a lock-order cycle's
        witness sites carry other files' line numbers), which would
        churn baselined entries on unrelated edits."""
        return f"{self.check}|{self.path}|{self.line}|{self.code}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.check}/{self.code}] {self.message}"


@dataclass
class Report:
    findings: list = field(default_factory=list)
    suppressed: list = field(default_factory=list)  # (Finding, reason)

    def extend(self, other: "Report") -> None:
        self.findings.extend(other.findings)
        self.suppressed.extend(other.suppressed)


def _suppression(index, path: str, line: int, check: str):
    """Return the justification string when ``path:line`` (or the line
    above it) carries ``# analysis: allow[check] -- reason``."""
    mod = index.by_path.get(path)
    if mod is None:
        return None
    for ln in (line, line - 1):
        allow = mod.allows.get(ln)
        if allow:
            for chk, reason in allow:
                if chk in (check, "*"):
                    return reason or "(unjustified)"
    return None


def _emit(report: Report, index, f: Finding) -> None:
    reason = _suppression(index, f.path, f.line, f.check)
    if reason is None:
        report.findings.append(f)
    else:
        report.suppressed.append((f, reason))


def run(root: str, checks=CHECKS, runtime_graph: dict | None = None,
        index=None) -> Report:
    """Analyze every ``*.py`` under ``root`` (a package directory).
    ``runtime_graph`` is a ``lockdep.export_graph()`` dict unioned into
    the static lock-order graph."""
    from ceph_tpu.analysis import core
    if index is None:
        index = core.TreeIndex.build(root)
    report = Report()
    if "bare-lock" in checks:
        from ceph_tpu.analysis import bare_locks
        for f in bare_locks.check(index):
            _emit(report, index, f)
    if "lock-order" in checks:
        from ceph_tpu.analysis import lock_order
        for f in lock_order.check(index, runtime_graph=runtime_graph):
            _emit(report, index, f)
    if "blocking" in checks:
        from ceph_tpu.analysis import blocking
        for f in blocking.check(index):
            _emit(report, index, f)
    if "jit-purity" in checks:
        from ceph_tpu.analysis import jit_purity
        for f in jit_purity.check(index):
            _emit(report, index, f)
    if "registry" in checks:
        from ceph_tpu.analysis import registry_lint
        for f in registry_lint.check(index):
            _emit(report, index, f)
    if "thread-except" in checks:
        from ceph_tpu.analysis import thread_except
        for f in thread_except.check(index):
            _emit(report, index, f)
    report.findings.sort(key=lambda f: (f.path, f.line, f.check, f.code))
    return report


# -- baseline -----------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.txt")


def load_baseline(path: str) -> set[str]:
    keys: set[str] = set()
    if not os.path.exists(path):
        return keys
    with open(path, encoding="utf-8") as f:
        for ln in f:
            ln = ln.strip()
            if ln and not ln.startswith("#"):
                keys.add(ln)
    return keys


def save_baseline(path: str, findings) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("# ceph_tpu.analysis baseline — accepted pre-existing "
                "findings.\n# One Finding.key() per line; keep EMPTY: "
                "new findings should be\n# fixed or suppressed inline "
                "with a justification, not baselined.\n")
        for fi in sorted(findings, key=lambda x: x.key()):
            f.write(fi.key() + "\n")


def diff_baseline(report: Report, baseline: set[str]):
    """-> (new_findings, stale_keys)."""
    current = {f.key() for f in report.findings}
    new = [f for f in report.findings if f.key() not in baseline]
    stale = sorted(baseline - current)
    return new, stale
