"""Byte/op throttle (src/common/Throttle.{h,cc} analog): blocking budget used
by messenger policies and the OSD front door."""

from __future__ import annotations

import threading


class Throttle:
    def __init__(self, name: str, max_amount: int):
        self.name = name
        self._max = max_amount
        self._current = 0
        # analysis: allow[bare-lock] -- bounded byte-throttle condition; waiters hold no other lock (messenger deliver waits before taking any)
        self._cond = threading.Condition()

    @property
    def max_amount(self) -> int:
        return self._max

    @property
    def current(self) -> int:
        with self._cond:
            return self._current

    def get(self, amount: int, timeout: float | None = None) -> bool:
        """Block until ``amount`` fits in the budget (Throttle::get)."""
        with self._cond:
            if self._max == 0:
                return True
            ok = self._cond.wait_for(
                lambda: self._current + amount <= self._max, timeout)
            if not ok:
                return False
            self._current += amount
            return True

    def get_or_fail(self, amount: int) -> bool:
        with self._cond:
            if self._max and self._current + amount > self._max:
                return False
            self._current += amount
            return True

    def put(self, amount: int) -> None:
        with self._cond:
            self._current = max(0, self._current - amount)
            self._cond.notify_all()
