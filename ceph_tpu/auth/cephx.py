"""cephx ticket protocol (src/auth/cephx/CephxProtocol.h:1-546 reduced
to its authentication core).

The reference's shape, kept:

  * every ENTITY (client.admin, osd.3, mds.a ...) has its own secret,
    provisioned by the AuthMonitor
  * a principal authenticates TO THE MON with its own secret and asks
    for a TICKET for a service ("osd", "mds", "mon", "mgr")
  * the mon holds per-service ROTATING KEYS (generations; the reference
    keeps 3 live).  A ticket binds {entity, service, generation, nonce,
    expiry} under an HMAC tag with that generation's service key
  * service daemons hold the current rotating keys (fetched from the
    mon over their own authenticated connection, refreshed on a timer)
    and validate tickets locally — no mon round trip per connection
  * the per-connection session key is DERIVED, not transmitted:
        session_key = HMAC(service_key[gen], entity|nonce|expiry)
    the mon computes it for the principal; the service recomputes it
    from the ticket fields.  A forged/expired/revoked ticket yields no
    usable session key, so the handshake proof fails

What is deliberately reduced: the wire carries no confidentiality
(msgr2 secure-mode encryption is out of scope — as in the reference's
default crc mode); tickets guard AUTHENTICATION, which is what `auth
del` must enforce: a deleted entity cannot get new tickets, so its next
reconnect dies at the mon while live sessions drain.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from dataclasses import dataclass

#: how long one service-key generation signs fresh tickets
ROTATION_PERIOD = 3600.0
#: generations kept valid (current + previous ones still draining)
LIVE_GENERATIONS = 3
#: ticket lifetime (reference auth_service_ticket_ttl)
TICKET_TTL = 3600.0


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s.encode())


def new_secret() -> str:
    """A fresh base64 entity/service secret (CryptoKey::create)."""
    return _b64(os.urandom(16))


def derive_session_key(service_key: str | bytes, entity: str,
                       nonce: str, expiry: float) -> bytes:
    if isinstance(service_key, str):
        service_key = service_key.encode()
    msg = f"{entity}|{nonce}|{expiry:.3f}".encode()
    return hmac.new(service_key, msg, hashlib.sha256).digest()


@dataclass
class Ticket:
    """What the mon hands a principal for one service."""

    service: str
    entity: str
    gen: int
    nonce: str
    expiry: float
    tag: str            # HMAC(service_key[gen], fields) — forgery guard
    session_key: bytes  # derived; NOT part of the wire blob

    def blob(self) -> bytes:
        """The part presented to the service at handshake."""
        return json.dumps({
            "service": self.service, "entity": self.entity,
            "gen": self.gen, "nonce": self.nonce,
            "expiry": self.expiry, "tag": self.tag}).encode()


def ticket_to_json(t: "Ticket") -> str:
    """Wire form for the mon's `auth get-ticket` reply."""
    return json.dumps({
        "service": t.service, "entity": t.entity, "gen": t.gen,
        "nonce": t.nonce, "expiry": t.expiry, "tag": t.tag,
        "session_key": _b64(t.session_key)})


def ticket_from_json(s: str) -> "Ticket":
    d = json.loads(s)
    return Ticket(service=d["service"], entity=d["entity"],
                  gen=d["gen"], nonce=d["nonce"], expiry=d["expiry"],
                  tag=d["tag"], session_key=_unb64(d["session_key"]))


def _tag(service_key: str, service: str, entity: str, gen: int,
         nonce: str, expiry: float) -> str:
    msg = f"{service}|{entity}|{gen}|{nonce}|{expiry:.3f}".encode()
    return hmac.new(service_key.encode(), msg,
                    hashlib.sha256).hexdigest()


def mint_ticket(service: str, entity: str, gen: int, service_key: str,
                ttl: float = TICKET_TTL,
                now: float | None = None) -> Ticket:
    now = time.time() if now is None else now
    nonce = _b64(os.urandom(8))
    expiry = now + ttl
    return Ticket(
        service=service, entity=entity, gen=gen, nonce=nonce,
        expiry=expiry,
        tag=_tag(service_key, service, entity, gen, nonce, expiry),
        session_key=derive_session_key(service_key, entity, nonce,
                                       expiry))


def validate_ticket(blob: bytes, service: str,
                    rotating: dict[int, str],
                    now: float | None = None) -> tuple[str, bytes] | None:
    """Service-side check: returns (entity, session_key) for a genuine,
    unexpired ticket of a live generation; None otherwise."""
    now = time.time() if now is None else now
    try:
        t = json.loads(blob.decode())
        service_key = rotating.get(int(t["gen"]))
        if service_key is None:
            return None                      # rotated out
        if t.get("service") != service:
            return None                      # ticket for someone else
        expiry = float(t["expiry"])
        if expiry < now:
            return None                      # expired
        want = _tag(service_key, service, t["entity"], int(t["gen"]),
                    t["nonce"], expiry)
        if not hmac.compare_digest(want, str(t.get("tag", ""))):
            return None                      # forged / tampered
        return str(t["entity"]), derive_session_key(
            service_key, t["entity"], t["nonce"], expiry)
    except (ValueError, KeyError, TypeError):
        return None


class KeyServer:
    """Mon-side rotating service keys (mon/AuthMonitor KeyServer).

    State lives in a plain dict the caller persists (it rides the
    paxos-replicated auth_db under reserved '__svc__' names, so every
    mon serves identical tickets and a restart keeps generations):

        {"gen": int, "keys": {str(gen): secret}, "rotated_at": float}
    """

    SERVICES = ("mon", "osd", "mds", "mgr")

    def __init__(self, state: dict | None = None,
                 rotation_period: float = ROTATION_PERIOD):
        self.state = state if state is not None else {}
        self.rotation_period = rotation_period

    def _svc(self, service: str) -> dict:
        s = self.state.setdefault(service, {})
        if "gen" not in s:
            # current AND next from day one: services always hold the
            # generation a future rotation will sign with (the
            # reference's prev/current/next rotating-secret triple —
            # this is what makes rotation hitless)
            s["gen"] = 1
            s["keys"] = {"1": new_secret(), "2": new_secret()}
            s["rotated_at"] = time.time()
        return s

    def maybe_rotate(self, now: float | None = None) -> bool:
        """Advance any service whose generation is stale.  The NEXT
        generation is pre-created (services fetch it before it ever
        signs a ticket); generations older than prev stop validating."""
        now = time.time() if now is None else now
        changed = False
        for service in list(self.state) or []:
            s = self._svc(service)
            if now - s["rotated_at"] >= self.rotation_period:
                s["gen"] += 1
                s["keys"].setdefault(str(s["gen"] + 1), new_secret())
                s["rotated_at"] = now
                live = {str(g) for g in
                        range(s["gen"] - 1, s["gen"] + 2)}
                s["keys"] = {g: k for g, k in s["keys"].items()
                             if g in live}
                changed = True
        return changed

    def rotate_now(self, service: str) -> None:
        """Force one rotation (tests / `auth rotate`)."""
        s = self._svc(service)
        s["rotated_at"] = 0.0
        self.maybe_rotate()

    def grant(self, service: str, entity: str,
              ttl: float = TICKET_TTL) -> Ticket:
        s = self._svc(service)
        return mint_ticket(service, entity, s["gen"],
                           s["keys"][str(s["gen"])], ttl=ttl)

    def rotating_keys(self, service: str) -> dict[int, str]:
        """What a service daemon holds to validate tickets."""
        s = self._svc(service)
        return {int(g): k for g, k in s["keys"].items()}


class TicketKeyring:
    """Principal-side ticket cache: one live ticket per service,
    refreshed before expiry via the fetch callback (the client's
    CephxTicketManager).

    ``get`` is the blocking form (caller's thread pays the mon round
    trip).  ``get_nowait`` is for MESSENGER THREADS: fetching there
    would deadlock (the fetch's own reply needs that thread), so it
    returns the cached ticket — triggering a background refresh when
    stale — and the connection's retry machinery redials once the
    fresh ticket lands."""

    #: refresh when less than this fraction of the ttl remains
    REFRESH_AT = 0.25

    def __init__(self, fetch):
        #: fetch(service) -> Ticket | None (a mon round trip)
        self._fetch = fetch
        self._tickets: dict[str, Ticket] = {}
        import threading
        self._lock = threading.Lock()
        self._refreshing: set[str] = set()

    def get(self, service: str,
            now: float | None = None) -> Ticket | None:
        now = time.time() if now is None else now
        t = self._tickets.get(service)
        if t is not None and t.expiry - now > self.REFRESH_AT * TICKET_TTL:
            return t
        fresh = self._fetch(service)
        if fresh is not None:
            self._tickets[service] = fresh
            return fresh
        return t if t is not None and t.expiry > now else None

    def get_nowait(self, service: str,
                   now: float | None = None) -> Ticket | None:
        now = time.time() if now is None else now
        t = self._tickets.get(service)
        if t is not None and t.expiry - now > self.REFRESH_AT * TICKET_TTL:
            return t
        self._spawn_refresh(service)
        return t if t is not None and t.expiry > now else None

    def _spawn_refresh(self, service: str) -> None:
        import threading
        with self._lock:
            if service in self._refreshing:
                return
            self._refreshing.add(service)

        def run():
            try:
                fresh = self._fetch(service)
                if fresh is not None:
                    self._tickets[service] = fresh
            finally:
                with self._lock:
                    self._refreshing.discard(service)

        threading.Thread(target=run, name=f"cephx-ticket-{service}",
                         daemon=True).start()

    def invalidate(self, service: str | None = None) -> None:
        if service is None:
            self._tickets.clear()
        else:
            self._tickets.pop(service, None)
