"""RBD journaling + rbd-mirror: cross-cluster replication, crash-window
resume, promote/demote failover (src/tools/rbd_mirror/,
librbd/Journal.h:43 analog).
"""

from __future__ import annotations

import pytest

from ceph_tpu.rbd import FEATURE_JOURNALING, Image
from ceph_tpu.rbd_mirror import MirrorDaemon, demote, promote
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture()
def two_clusters():
    a = MiniCluster(n_osds=3).start()
    b = MiniCluster(n_osds=3).start()
    try:
        a.wait_for_osd_count(3)
        b.wait_for_osd_count(3)
        ca = a.client()
        cb = b.client()
        pa = a.create_pool(ca, pg_num=8, size=2)
        pb = b.create_pool(cb, pg_num=8, size=2)
        yield ca.open_ioctx(pa), cb.open_ioctx(pb)
    finally:
        a.stop()
        b.stop()


def test_mirror_replay_and_failover(two_clusters):
    src, dst = two_clusters
    img = Image.create(src, "vm-disk", size=1 << 20)
    img.feature_enable(FEATURE_JOURNALING)
    img.write(b"alpha" * 100, 0)
    img.write(b"beta" * 64, 4096)

    md = MirrorDaemon(src, dst)
    assert md.run_once() == {"vm-disk": 2}
    mirror = Image(dst, "vm-disk")
    assert not mirror.is_primary()
    assert mirror.read(0, 500) == b"alpha" * 100
    assert mirror.read(4096, 256) == b"beta" * 64

    # mirror target refuses direct writes until promoted
    with pytest.raises(OSError):
        mirror.write(b"nope", 0)

    # incremental: more writes + snapshot replicate on the next sweep
    img.write(b"gamma" * 10, 8192)
    img.snap_create("s1")
    img.write(b"delta" * 10, 8192)
    assert md.run_once()["vm-disk"] == 3
    assert mirror.read(8192, 50) == b"delta" * 10
    m2 = Image(dst, "vm-disk")
    assert "s1" in m2.snap_list()
    assert m2.read(8192, 50, snap="s1") == b"gamma" * 10

    # failover: demote the old primary, promote the mirror, write there
    demote(src, "vm-disk")
    with pytest.raises(OSError):
        img.write(b"x", 0)
    promote(dst, "vm-disk")
    mirror.write(b"post-failover", 0)
    assert mirror.read(0, 13) == b"post-failover"
    # split-brain guard: replay onto a promoted image is refused
    assert md.run_once()["vm-disk"] == 0


def test_mirror_crash_mid_replay_resumes(two_clusters):
    src, dst = two_clusters
    img = Image.create(src, "crashy", size=1 << 20)
    img.feature_enable(FEATURE_JOURNALING)
    blocks = [(i * 1024, bytes([65 + i]) * 512) for i in range(6)]
    for off, blob in blocks:
        img.write(blob, off)

    md = MirrorDaemon(src, dst)
    # "crash" after 2 events: position persisted per applied event
    assert md.replay_image("crashy", max_events=2) == 2
    # a fresh daemon (new process after the crash) resumes, not restarts
    md2 = MirrorDaemon(src, dst)
    assert md2.replay_image("crashy") == 4
    mirror = Image(dst, "crashy")
    for off, blob in blocks:
        assert mirror.read(off, len(blob)) == blob
    # journal trimmed up to the mirrored position; nothing replays twice
    assert md2.replay_image("crashy") == 0


def test_resize_replicates(two_clusters):
    src, dst = two_clusters
    img = Image.create(src, "grow", size=4096)
    img.feature_enable(FEATURE_JOURNALING)
    img.write(b"z" * 4096, 0)
    img.resize(8192)
    img.write(b"tail" * 4, 8192 - 16)
    md = MirrorDaemon(src, dst)
    md.run_once(["grow"])
    mirror = Image(dst, "grow")
    assert mirror.stat()["size"] == 8192
    assert mirror.read(8192 - 16, 16) == b"tail" * 4
    # shrink replicates too (truncates replicated data)
    img.resize(1024)
    md.run_once(["grow"])
    assert Image(dst, "grow").stat()["size"] == 1024


def test_failback_after_failover(two_clusters):
    """Post-failover writes on the promoted copy journal themselves, so
    failback (a daemon running the other way) replicates them home."""
    src, dst = two_clusters
    img = Image.create(src, "fb", size=1 << 16)
    img.feature_enable(FEATURE_JOURNALING)
    img.write(b"original" * 8, 0)
    MirrorDaemon(src, dst).run_once(["fb"])

    demote(src, "fb")
    promote(dst, "fb")
    mirror = Image(dst, "fb")
    mirror.write(b"failover-write" * 4, 1024)

    back = MirrorDaemon(dst, src)   # the other direction
    assert back.replay_image("fb") >= 1
    home = Image(src, "fb")
    assert home.read(1024, 56) == b"failover-write" * 4
    assert home.read(0, 64) == b"original" * 8


def test_snap_rollback_replicates(two_clusters):
    """A journaled rollback replays on the mirror (advisor r3: an
    unjournaled rollback silently diverged the pair forever)."""
    src, dst = two_clusters
    img = Image.create(src, "rb", size=1 << 16)
    img.feature_enable(FEATURE_JOURNALING)
    img.write(b"keep-this" * 8, 0)
    img.snap_create("good")
    img.write(b"SCRIBBLE!" * 8, 0)
    md = MirrorDaemon(src, dst)
    md.run_once(["rb"])
    mirror = Image(dst, "rb")
    assert mirror.read(0, 72) == b"SCRIBBLE!" * 8

    img.snap_rollback("good")
    assert img.read(0, 72) == b"keep-this" * 8
    md.run_once(["rb"])
    # the mirror rolled back against ITS replicated snapshot
    assert Image(dst, "rb").read(0, 72) == b"keep-this" * 8
    # and subsequent writes land on a converged base
    img.write(b"after-rollback", 4096)
    md.run_once(["rb"])
    assert Image(dst, "rb").read(4096, 14) == b"after-rollback"


def test_poison_event_flags_resync_not_wedge(two_clusters):
    """A rollback to a snapshot the mirror never received (taken before
    journaling was enabled) must not wedge replication: the image is
    flagged for resync, other images keep replicating, and resync
    re-bootstraps the copy."""
    src, dst = two_clusters
    img = Image.create(src, "poison", size=1 << 16)
    img.write(b"pre-journal" * 4, 0)
    img.snap_create("old")          # NOT journaled: feature off
    img.feature_enable(FEATURE_JOURNALING)
    img.write(b"journaled-bytes", 1024)
    healthy = Image.create(src, "healthy", size=1 << 16)
    healthy.feature_enable(FEATURE_JOURNALING)
    healthy.write(b"fine", 0)

    md = MirrorDaemon(src, dst)
    md.run_once()
    img.snap_rollback("old")        # journaled; mirror lacks "old"
    healthy.write(b"more", 512)
    out = md.run_once()
    # the healthy image replicated; the poisoned one flagged, not raised
    assert out["healthy"] == 1
    assert md.needs_resync("poison")
    assert Image(dst, "healthy").read(512, 4) == b"more"
    # paused until resync: further sweeps apply nothing to it
    assert md.run_once()["poison"] == 0

    md.resync_image("poison")
    assert not md.needs_resync("poison")
    assert Image(dst, "poison").read(0, 44) == b"pre-journal" * 4
    # the journaled write that replicated pre-rollback is stale mirror
    # state now (the primary rolled it back): resync must have wiped it
    assert Image(dst, "poison").read(1024, 14) == bytes(14)
    # resync rebuilt the snapshot history: a LATER rollback to the
    # once-missing snap now replays instead of re-poisoning the pair
    assert "old" in Image(dst, "poison").snap_list()
    img.write(b"scribble", 0)
    md.run_once()
    img.snap_rollback("old")
    md.run_once()
    assert not md.needs_resync("poison")
    assert Image(dst, "poison").read(0, 44) == b"pre-journal" * 4
    # replication resumes normally after resync
    img.write(b"back-in-business", 2048)
    md.run_once()
    assert Image(dst, "poison").read(2048, 16) == b"back-in-business"
