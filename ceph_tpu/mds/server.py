"""MDS daemon: journaled filesystem metadata over RADOS (src/mds/).

The reference MDS keeps the namespace in a metadata pool — each
directory fragment is a RADOS object whose omap maps dentry name to the
encoded inode — and journals every mutation through osdc/Journaler
before acking (MDLog EUpdate events), writing dirty dirfrags back
lazily.  Crash recovery = load backing dirfrags + replay the journal
tail (up:replay -> up:active, MDCache::rejoin machinery reduced to the
single-MDS case).  File DATA never touches the MDS: clients stripe it
straight to the data pool (Striper) and report the new size back
(the reference tracks it via client caps; here an explicit setattr).

Wire surface: MClientRequest/MClientReply (messages/MClientRequest.h,
CEPH_MSG_CLIENT_REQUEST=24 / _REPLY=26) carrying json-ish op payloads.

Object naming in the metadata pool:
    dir.<ino:x>      dirfrag omap: name -> encoded dentry {ino, type}
    inode.<ino:x>    omap: encoded inode attrs (mode, size, times)
    mds.table        omap: next_ino
    mdlog.*          the Journaler stream + head
"""

from __future__ import annotations

import json
import threading
import time

from ceph_tpu.client.rados import RadosClient
from ceph_tpu.common.context import CephTpuContext
from ceph_tpu.common.logging import dout
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.osdc.journaler import Journaler

ROOT_INO = 1

S_IFDIR = 0o040000
S_IFREG = 0o100000


@register_message
class MClientRequest(Message):
    """fs client -> mds (CEPH_MSG_CLIENT_REQUEST=24)."""

    TYPE = 24

    def __init__(self, tid: int = 0, op: str = "", args: dict | None = None):
        super().__init__()
        self.tid = tid
        self.op = op
        self.args = args or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.tid), e.str(self.op),
            e.bytes(json.dumps(self.args).encode())))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.tid = d.u64()
            self.op = d.str()
            self.args = json.loads(d.bytes().decode() or "{}")
        dec.versioned(1, body)


@register_message
class MClientReply(Message):
    """mds -> fs client (CEPH_MSG_CLIENT_REPLY=26)."""

    TYPE = 26

    def __init__(self, tid: int = 0, result: int = 0,
                 out: dict | None = None):
        super().__init__()
        self.tid = tid
        self.result = result
        self.out = out or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.u64(self.tid), e.s32(self.result),
            e.bytes(json.dumps(self.out).encode())))

    def decode_payload(self, dec: Decoder, version: int):
        def body(d, v):
            self.tid = d.u64()
            self.result = d.s32()
            self.out = json.loads(d.bytes().decode() or "{}")
        dec.versioned(1, body)


class Inode:
    __slots__ = ("ino", "mode", "size", "mtime")

    def __init__(self, ino: int, mode: int, size: int = 0,
                 mtime: float = 0.0):
        self.ino = ino
        self.mode = mode
        self.size = size
        self.mtime = mtime

    def is_dir(self) -> bool:
        return bool(self.mode & S_IFDIR)

    def to_dict(self) -> dict:
        return {"ino": self.ino, "mode": self.mode, "size": self.size,
                "mtime": self.mtime}

    @staticmethod
    def from_dict(d: dict) -> "Inode":
        return Inode(d["ino"], d["mode"], d.get("size", 0),
                     d.get("mtime", 0.0))


class MDSDaemon(Dispatcher):
    """Single-rank MDS (the reference scales ranks via dirfrag export;
    the namespace model below is rank-count agnostic)."""

    def __init__(self, mon_addr: str, metadata_pool: int, data_pool: int,
                 ctx: CephTpuContext | None = None, ms_type: str = "async",
                 addr: str = "127.0.0.1:0", auth_key=None):
        self.ctx = ctx or CephTpuContext("mds.0")
        self.name = EntityName("mds", 0)
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool
        self._lock = threading.RLock()
        #: ino -> Inode (inode cache; authoritative once loaded)
        self._inodes: dict[int, Inode] = {}
        #: ino -> {name: child_ino} (dirfrag cache)
        self._dirs: dict[int, dict[int, object]] = {}
        self._dirty_dirs: set[int] = set()
        self._dirty_inodes: set[int] = set()
        self._next_ino = ROOT_INO + 1
        self._journaled_since_flush = 0
        self.state = "boot"

        self.objecter = RadosClient(mon_addr, ms_type=ms_type,
                                    auth_key=auth_key)
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        self.msgr.set_policy("client", ConnectionPolicy.lossy_client())
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr
        self._stop = False
        self.journal: Journaler | None = None

    # -- lifecycle ------------------------------------------------------------

    def init(self) -> None:
        self.objecter.connect()
        self.meta_io = self.objecter.open_ioctx(self.metadata_pool)
        self.journal = Journaler(self.meta_io, "mdlog")
        self._load_or_mkfs()
        self.state = "replay"
        n = self.journal.replay(
            lambda payload, _pos: self._replay_entry(payload))
        dout("mds", 5, "mds.0 replayed %d journal events", n)
        if n:
            self._flush_dirty()
            self.journal.trim()
        self.state = "active"
        self.msgr.bind(self._addr)
        self.msgr.start()

    def shutdown(self) -> None:
        self._stop = True
        with self._lock:
            self._flush_dirty()
            if self.journal is not None:
                self.journal.trim()
        self.msgr.shutdown()
        self.objecter.shutdown()

    @property
    def addr(self) -> str:
        return self.msgr.my_addr

    def _load_or_mkfs(self) -> None:
        try:
            table = self.meta_io.get_omap("mds.table")
            self._next_ino = int(table.get("next_ino", b"2").decode())
            self.journal.open()
        except OSError:
            # fresh filesystem: root inode + empty journal
            self._inodes[ROOT_INO] = Inode(ROOT_INO, S_IFDIR | 0o755)
            self._dirs[ROOT_INO] = {}
            self._dirty_dirs.add(ROOT_INO)
            self._dirty_inodes.add(ROOT_INO)
            self.journal.create()
            self._flush_dirty()

    # -- backing store (dirfrag omap objects) ---------------------------------

    def _dir_obj(self, ino: int) -> str:
        return f"dir.{ino:x}"

    def _inode_obj(self, ino: int) -> str:
        return f"inode.{ino:x}"

    def _load_dir(self, ino: int) -> dict:
        d = self._dirs.get(ino)
        if d is not None:
            return d
        try:
            omap = self.meta_io.get_omap(self._dir_obj(ino))
            d = {name: int(v.decode()) for name, v in omap.items()}
        except OSError:
            d = {}
        self._dirs[ino] = d
        return d

    def _load_inode(self, ino: int) -> Inode | None:
        inode = self._inodes.get(ino)
        if inode is not None:
            return inode
        try:
            omap = self.meta_io.get_omap(self._inode_obj(ino))
        except OSError:
            return None
        if "json" not in omap:
            return None
        inode = Inode.from_dict(json.loads(omap["json"].decode()))
        self._inodes[ino] = inode
        return inode

    def _flush_dirty(self) -> None:
        """Write dirty dirfrags/inodes back (MDCache::flush, the lazy
        CDir commit), then persist the ino allocator."""
        for ino in sorted(self._dirty_dirs):
            d = self._dirs.get(ino, {})
            # rewrite wholesale: dirfrags are small omaps here
            try:
                self.meta_io.remove(self._dir_obj(ino))
            except OSError:
                pass
            self.meta_io.set_omap(
                self._dir_obj(ino),
                {name: str(child).encode() for name, child in d.items()})
        self._dirty_dirs.clear()
        for ino in sorted(self._dirty_inodes):
            inode = self._inodes.get(ino)
            if inode is None:
                continue
            self.meta_io.set_omap(
                self._inode_obj(ino),
                {"json": json.dumps(inode.to_dict()).encode()})
        self._dirty_inodes.clear()
        self.meta_io.set_omap(
            "mds.table", {"next_ino": str(self._next_ino).encode()})

    # -- journal (MDLog EUpdate) ----------------------------------------------

    def _journal(self, event: dict) -> None:
        self.journal.append_entry(json.dumps(event).encode())
        self.journal.flush()

    def _maybe_trim(self) -> None:
        """Segment boundary (MDLog trim): write dirty state back, then
        expire the journal.  MUST run only after the current event is
        both journaled AND applied — trimming first would expire an
        acked mutation that is in neither the journal nor the store."""
        self._journaled_since_flush += 1
        if self._journaled_since_flush >= 64:
            self._flush_dirty()
            self.journal.trim()
            self._journaled_since_flush = 0

    def _replay_entry(self, payload: bytes) -> None:
        ev = json.loads(payload.decode())
        self._apply(ev, replay=True)

    # -- namespace mutations (journaled, replayable) --------------------------

    def _apply(self, ev: dict, replay: bool = False) -> None:
        """Apply one journaled event to the cache.  Must be idempotent:
        replay re-applies events the backing store may already hold."""
        kind = ev["e"]
        if kind == "batch":
            # one journal entry, several sub-events: the atomic EUpdate
            # shape (rename's link+unlink must never tear)
            for sub in ev["events"]:
                self._apply(sub, replay=replay)
            return
        if kind == "alloc":
            self._next_ino = max(self._next_ino, ev["next_ino"])
            return
        if kind == "link":
            parent, name, ino = ev["parent"], ev["name"], ev["ino"]
            self._load_dir(parent)[name] = ino
            self._dirty_dirs.add(parent)
            if "mode" in ev:
                self._inodes[ino] = Inode(ino, ev["mode"], ev.get("size", 0),
                                          ev.get("mtime", 0.0))
                if self._inodes[ino].is_dir():
                    self._dirs.setdefault(ino, {})
                    self._dirty_dirs.add(ino)
                self._dirty_inodes.add(ino)
            return
        if kind == "unlink":
            parent, name = ev["parent"], ev["name"]
            d = self._load_dir(parent)
            ino = d.pop(name, None)
            self._dirty_dirs.add(parent)
            if ino is not None and ev.get("drop_inode"):
                self._inodes.pop(ino, None)
                self._dirs.pop(ino, None)
                try:
                    self.meta_io.remove(self._inode_obj(ino))
                except OSError:
                    pass
                try:
                    self.meta_io.remove(self._dir_obj(ino))
                except OSError:
                    pass
            return
        if kind == "setattr":
            inode = self._load_inode(ev["ino"])
            if inode is not None:
                if "size" in ev:
                    inode.size = ev["size"]
                if "mtime" in ev:
                    inode.mtime = ev["mtime"]
                if "mode" in ev:
                    inode.mode = ev["mode"]
                self._dirty_inodes.add(inode.ino)
            return
        raise ValueError(f"unknown journal event {kind!r}")

    def _mutate(self, ev: dict) -> None:
        """Journal-then-apply (the EUpdate ordering: an acked mutation
        is always recoverable), then maybe roll the segment."""
        self._journal(ev)
        self._apply(ev)
        self._maybe_trim()

    # -- path resolution ------------------------------------------------------

    def _resolve(self, path: str) -> tuple[int | None, int | None, str]:
        """path -> (parent_ino, ino, last_name); ino None if the leaf
        does not exist, parent None if an intermediate is missing."""
        parts = [p for p in path.split("/") if p]
        cur = ROOT_INO
        if not parts:
            return None, ROOT_INO, "/"
        for p in parts[:-1]:
            child = self._load_dir(cur).get(p)
            if child is None:
                return None, None, parts[-1]
            inode = self._load_inode(child)
            if inode is None or not inode.is_dir():
                return None, None, parts[-1]
            cur = child
        name = parts[-1]
        return cur, self._load_dir(cur).get(name), name

    # -- request handling -----------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if self._stop:
            return True
        if isinstance(msg, MClientRequest):
            try:
                with self._lock:
                    result, out = self._handle(msg.op, msg.args)
            except Exception:
                from ceph_tpu.common.logging import get_logger
                get_logger("mds").exception("mds request %s failed", msg.op)
                result, out = -5, {}
            msg.connection.send_message(
                MClientReply(tid=msg.tid, result=result, out=out))
            return True
        return False

    def _handle(self, op: str, a: dict) -> tuple[int, dict]:
        if op == "lookup":
            parent, ino, _name = self._resolve(a["path"])
            if ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None:
                return -2, {}
            return 0, {"inode": inode.to_dict()}

        if op == "mkdir":
            parent, ino, name = self._resolve(a["path"])
            if parent is None:
                return -2, {}
            if ino is not None:
                return -17, {}  # EEXIST
            new = self._alloc_ino()
            self._mutate({"e": "link", "parent": parent, "name": name,
                          "ino": new, "mode": S_IFDIR | a.get("mode", 0o755),
                          "mtime": time.time()})
            return 0, {"inode": self._inodes[new].to_dict()}

        if op == "create":
            parent, ino, name = self._resolve(a["path"])
            if parent is None:
                return -2, {}
            if ino is not None:
                inode = self._load_inode(ino)
                if inode is None or inode.is_dir():
                    return -21, {}  # EISDIR
                return 0, {"inode": inode.to_dict(),
                           "data_pool": self.data_pool}
            new = self._alloc_ino()
            self._mutate({"e": "link", "parent": parent, "name": name,
                          "ino": new, "mode": S_IFREG | a.get("mode", 0o644),
                          "size": 0, "mtime": time.time()})
            return 0, {"inode": self._inodes[new].to_dict(),
                       "data_pool": self.data_pool}

        if op == "readdir":
            _parent, ino, _name = self._resolve(a["path"])
            if ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None or not inode.is_dir():
                return -20, {}  # ENOTDIR
            out = {}
            for name, child in sorted(self._load_dir(ino).items()):
                ci = self._load_inode(child)
                if ci is not None:
                    out[name] = ci.to_dict()
            return 0, {"entries": out}

        if op == "unlink":
            parent, ino, name = self._resolve(a["path"])
            if parent is None or ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is not None and inode.is_dir():
                return -21, {}
            self._mutate({"e": "unlink", "parent": parent, "name": name,
                          "drop_inode": True})
            return 0, {"ino": ino}

        if op == "rmdir":
            parent, ino, name = self._resolve(a["path"])
            if parent is None or ino is None:
                return -2, {}
            inode = self._load_inode(ino)
            if inode is None or not inode.is_dir():
                return -20, {}
            if self._load_dir(ino):
                return -39, {}  # ENOTEMPTY
            self._mutate({"e": "unlink", "parent": parent, "name": name,
                          "drop_inode": True})
            return 0, {}

        if op == "rename":
            sp, sino, sname = self._resolve(a["src"])
            if sp is None or sino is None:
                return -2, {}
            dp, dino, dname = self._resolve(a["dst"])
            if dp is None:
                return -2, {}
            if dino is not None:
                return -17, {}
            # one atomic journal entry for link-at-dst + unlink-src (the
            # reference's single EUpdate): a crash can never leave the
            # inode reachable from both paths
            self._mutate({"e": "batch", "events": [
                {"e": "link", "parent": dp, "name": dname, "ino": sino},
                {"e": "unlink", "parent": sp, "name": sname}]})
            return 0, {"ino": sino}

        if op == "setattr":
            ev = {"e": "setattr", "ino": a["ino"]}
            for k in ("size", "mtime", "mode"):
                if k in a:
                    ev[k] = a[k]
            if self._load_inode(a["ino"]) is None:
                return -2, {}
            self._mutate(ev)
            return 0, {"inode": self._inodes[a["ino"]].to_dict()}

        if op == "statfs":
            return 0, {"next_ino": self._next_ino,
                       "data_pool": self.data_pool,
                       "metadata_pool": self.metadata_pool}

        return -22, {}

    def _alloc_ino(self) -> int:
        ino = self._next_ino
        self._next_ino += 1
        # journal the allocation so replay never re-issues a used ino
        self._journal({"e": "alloc", "next_ino": self._next_ino})
        self._maybe_trim()
        return ino
