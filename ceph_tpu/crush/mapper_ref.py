"""Exact scalar CRUSH mapping oracle.

Semantics follow src/crush/mapper.c line by line observable behaviour — bucket choose
methods (mapper.c:73-418), is_out (:424-438), crush_choose_firstn retry ladder
(:460-648), crush_choose_indep breadth-first pass (:655-843), and the crush_do_rule
step interpreter (:900-1105) — expressed in Python as the ground truth that the
batched JAX engine (ops.crush_kernel / mapper_jax) must match bit-for-bit.

All 64-bit arithmetic reproduces C semantics: wrap-around products mod 2^64 and
truncating division (div64_s64).
"""

from __future__ import annotations

from .hashfn import crush_hash32_2, crush_hash32_3, crush_hash32_4
from .ln_table import lh_table, ll_table, rh_table
from .types import (
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    RULE_SET_CHOOSE_LOCAL_TRIES,
    RULE_SET_CHOOSE_TRIES,
    RULE_SET_CHOOSELEAF_STABLE,
    RULE_SET_CHOOSELEAF_TRIES,
    RULE_SET_CHOOSELEAF_VARY_R,
    RULE_TAKE,
    S64_MIN,
    Bucket,
    CrushMap,
)

_M64 = (1 << 64) - 1


def _div_trunc(a: int, b: int) -> int:
    """C integer division: truncate toward zero."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def crush_ln(xin: int) -> int:
    """2^44 * log2(xin + 1) in 48-bit fixed point (mapper.c:248-290)."""
    x = (xin + 1) & 0xFFFFFFFF
    iexpon = 15
    if not (x & 0x18000):
        bits = 16 - (x & 0x1FFFF).bit_length()
        x = (x << bits) & 0xFFFFFFFF
        iexpon = 15 - bits
    index1 = (x >> 8) << 1
    k = (index1 - 256) >> 1
    rh = int(rh_table()[k])
    lh = int(lh_table()[k])
    # u64 wrap-around product; only bits [48..56) are consumed
    xl64 = ((x * rh) & _M64) >> 48
    index2 = xl64 & 0xFF
    ll = int(ll_table()[index2])
    result = iexpon << 44
    result += (lh + ll) >> 4
    return result


def _generate_exponential_distribution(x: int, y: int, z: int, weight: int) -> int:
    u = crush_hash32_3(x, y, z) & 0xFFFF
    ln = crush_ln(u) - 0x1000000000000
    return _div_trunc(ln, weight)


class _Work:
    """Per-invocation bucket permutation state (crush_work_bucket, crush.h;
    initialized by crush_init_workspace, mapper.c:858-887).  Each bucket's state
    is the mutable triple [perm_x, perm_n, perm]."""

    def __init__(self):
        self._by_bucket: dict[int, list] = {}

    def get(self, bucket_id: int) -> list:
        return self._by_bucket.setdefault(bucket_id, [0, 0, []])


def _bucket_perm_choose(bucket: Bucket, work: list, x: int, r: int) -> int:
    """mapper.c:73-131."""
    size = bucket.size
    pr = r % size
    if work[0] != (x & 0xFFFFFFFF) or work[1] == 0:
        work[0] = x & 0xFFFFFFFF
        if pr == 0:
            s = crush_hash32_3(x, bucket.id, 0) % size
            work[2] = [0] * size
            work[2][0] = s
            work[1] = 0xFFFF
            return bucket.items[s]
        work[2] = list(range(size))
        work[1] = 0
    elif work[1] == 0xFFFF:
        perm = work[2]
        for i in range(1, size):
            perm[i] = i
        perm[perm[0]] = 0
        work[1] = 1
    perm = work[2]
    while work[1] <= pr:
        p = work[1]
        if p < size - 1:
            i = crush_hash32_3(x, bucket.id, p) % (size - p)
            if i:
                perm[p + i], perm[p] = perm[p], perm[p + i]
        work[1] += 1
    return bucket.items[perm[pr]]


def _bucket_list_choose(bucket: Bucket, x: int, r: int) -> int:
    """mapper.c:141-164."""
    for i in range(bucket.size - 1, -1, -1):
        w = crush_hash32_4(x, bucket.items[i], r, bucket.id) & 0xFFFF
        w = (w * bucket.sum_weights[i]) >> 16
        if w < bucket.item_weights[i]:
            return bucket.items[i]
    return bucket.items[0]


def _bucket_tree_choose(bucket: Bucket, x: int, r: int) -> int:
    """mapper.c:195-222."""
    n = len(bucket.node_weights) >> 1
    while not (n & 1):
        w = bucket.node_weights[n]
        t = (crush_hash32_4(x, n, r, bucket.id) * w) >> 32
        h = 0
        nn = n
        while not (nn & 1):
            h += 1
            nn >>= 1
        left = n - (1 << (h - 1))
        if t < bucket.node_weights[left]:
            n = left
        else:
            n = n + (1 << (h - 1))
    return bucket.items[n >> 1]


def _bucket_straw_choose(bucket: Bucket, x: int, r: int) -> int:
    """mapper.c:227-245."""
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        draw = (crush_hash32_3(x, bucket.items[i], r) & 0xFFFF) * bucket.straws[i]
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _bucket_straw2_choose(bucket: Bucket, x: int, r: int, arg, position: int) -> int:
    """mapper.c:361-384 with choose_args weight/id overrides (:309-326)."""
    if arg is None or arg.weight_set is None:
        weights = bucket.item_weights
    else:
        pos = min(position, len(arg.weight_set) - 1)
        weights = arg.weight_set[pos]
    ids = bucket.items if (arg is None or arg.ids is None) else arg.ids
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        if weights[i]:
            draw = _generate_exponential_distribution(x, ids[i], r, weights[i])
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _crush_bucket_choose(bucket: Bucket, work: list, x: int, r: int,
                         arg, position: int) -> int:
    """mapper.c:387-418."""
    assert bucket.size > 0
    if bucket.alg == CRUSH_BUCKET_UNIFORM:
        return _bucket_perm_choose(bucket, work, x, r)
    if bucket.alg == CRUSH_BUCKET_LIST:
        return _bucket_list_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_TREE:
        return _bucket_tree_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW:
        return _bucket_straw_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW2:
        return _bucket_straw2_choose(bucket, x, r, arg, position)
    return bucket.items[0]


def _is_out(map: CrushMap, weight: list[int], item: int, x: int) -> bool:
    """mapper.c:424-438 — probabilistic rejection by reweight vector."""
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    if (crush_hash32_2(x, item) & 0xFFFF) < w:
        return False
    return True


def _choose_arg_for(choose_args, bucket_id: int):
    if choose_args is None:
        return None
    return choose_args.get(-1 - bucket_id)


def _choose_firstn(map: CrushMap, work: _Work, bucket: Bucket, weight: list[int],
                   x: int, numrep: int, type: int, out: list[int], outpos: int,
                   out_size: int, tries: int, recurse_tries: int,
                   local_retries: int, local_fallback_retries: int,
                   recurse_to_leaf: bool, vary_r: int, stable: int,
                   out2: list[int] | None, parent_r: int, choose_args) -> int:
    """mapper.c:460-648 — depth-first with the collision/reject retry ladder."""
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        item = 0
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_bucket = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                r = rep + parent_r + ftotal
                if in_bucket.size == 0:
                    reject = True
                    collide = False
                else:
                    collide = False
                    if (local_fallback_retries > 0
                            and flocal >= (in_bucket.size >> 1)
                            and flocal > local_fallback_retries):
                        item = _bucket_perm_choose(
                            in_bucket, work.get(in_bucket.id), x, r)
                    else:
                        item = _crush_bucket_choose(
                            in_bucket, work.get(in_bucket.id), x, r,
                            _choose_arg_for(choose_args, in_bucket.id), outpos)
                    if item >= map.max_devices:
                        skip_rep = True
                        break
                    if item < 0:
                        sub = map.bucket(item)
                        itemtype = sub.type if sub else None
                    else:
                        itemtype = 0
                    if itemtype != type:
                        if item >= 0 or map.bucket(item) is None:
                            skip_rep = True
                            break
                        in_bucket = map.bucket(item)
                        retry_bucket = True
                        continue
                    for i in range(outpos):
                        if out[i] == item:
                            collide = True
                            break
                    reject = False
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            got = _choose_firstn(
                                map, work, map.bucket(item), weight, x,
                                1 if stable else outpos + 1, 0,
                                out2, outpos, count,
                                recurse_tries, 0,
                                local_retries, local_fallback_retries,
                                False, vary_r, stable, None, sub_r, choose_args)
                            if got <= outpos:
                                reject = True
                        else:
                            out2[outpos] = item
                    if not reject and not collide and itemtype == 0:
                        reject = _is_out(map, weight, item, x)
                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0
                          and flocal <= in_bucket.size + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True
        if skip_rep:
            rep += 1
            continue
        out[outpos] = item
        outpos += 1
        count -= 1
        rep += 1
    return outpos


def _choose_indep(map: CrushMap, work: _Work, bucket: Bucket, weight: list[int],
                  x: int, left: int, numrep: int, type: int, out: list[int],
                  outpos: int, tries: int, recurse_tries: int,
                  recurse_to_leaf: bool, out2: list[int] | None,
                  parent_r: int, choose_args) -> None:
    """mapper.c:655-843 — breadth-first, positionally stable."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[rep] = CRUSH_ITEM_UNDEF
    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != CRUSH_ITEM_UNDEF:
                continue
            in_bucket = bucket
            while True:
                r = rep + parent_r
                if (in_bucket.alg == CRUSH_BUCKET_UNIFORM
                        and in_bucket.size % numrep == 0):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_bucket.size == 0:
                    break
                item = _crush_bucket_choose(
                    in_bucket, work.get(in_bucket.id), x, r,
                    _choose_arg_for(choose_args, in_bucket.id), outpos)
                if item >= map.max_devices:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                if item < 0:
                    sub = map.bucket(item)
                    itemtype = sub.type if sub else None
                else:
                    itemtype = 0
                if itemtype != type:
                    if item >= 0 or map.bucket(item) is None:
                        out[rep] = CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[rep] = CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_bucket = map.bucket(item)
                    continue
                collide = False
                for i in range(outpos, endpos):
                    if out[i] == item:
                        collide = True
                        break
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        _choose_indep(map, work, map.bucket(item), weight, x,
                                      1, numrep, 0, out2, rep, recurse_tries,
                                      0, False, None, r, choose_args)
                        if out2[rep] == CRUSH_ITEM_NONE:
                            break
                    else:
                        out2[rep] = item
                if type == 0 and _is_out(map, weight, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] == CRUSH_ITEM_UNDEF:
            out[rep] = CRUSH_ITEM_NONE
        if out2 is not None and out2[rep] == CRUSH_ITEM_UNDEF:
            out2[rep] = CRUSH_ITEM_NONE


def crush_do_rule(map: CrushMap, ruleno: int, x: int, result_max: int,
                  weight: list[int], choose_args=None) -> list[int]:
    """mapper.c:900-1105 — interpret the rule program, return the placement."""
    if ruleno < 0 or ruleno >= map.max_rules or map.rules[ruleno] is None:
        return []
    rule = map.rules[ruleno]
    work = _Work()

    w: list[int] = [0] * result_max
    o: list[int] = [0] * result_max
    c: list[int] = [0] * result_max
    wsize = 0
    result: list[int] = []

    choose_tries = map.tunables.choose_total_tries + 1
    choose_leaf_tries = 0
    choose_local_retries = map.tunables.choose_local_tries
    choose_local_fallback_retries = map.tunables.choose_local_fallback_tries
    vary_r = map.tunables.chooseleaf_vary_r
    stable = map.tunables.chooseleaf_stable

    for step in rule.steps:
        if step.op == RULE_TAKE:
            arg = step.arg1
            ok = (0 <= arg < map.max_devices) or (map.bucket(arg) is not None)
            if ok:
                w[0] = arg
                wsize = 1
        elif step.op == RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                choose_tries = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0:
                choose_leaf_tries = step.arg1
        elif step.op == RULE_SET_CHOOSE_LOCAL_TRIES:
            if step.arg1 >= 0:
                choose_local_retries = step.arg1
        elif step.op == RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if step.arg1 >= 0:
                choose_local_fallback_retries = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_VARY_R:
            if step.arg1 >= 0:
                vary_r = step.arg1
        elif step.op == RULE_SET_CHOOSELEAF_STABLE:
            if step.arg1 >= 0:
                stable = step.arg1
        elif step.op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN,
                         RULE_CHOOSE_INDEP, RULE_CHOOSELEAF_INDEP):
            if wsize == 0:
                continue
            firstn = step.op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN)
            recurse_to_leaf = step.op in (RULE_CHOOSELEAF_FIRSTN,
                                          RULE_CHOOSELEAF_INDEP)
            osize = 0
            for i in range(wsize):
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                bucket = map.bucket(w[i])
                if bucket is None:
                    continue
                # the reference hands each choose call the offset sub-arrays
                # o+osize / c+osize with outpos 0 (mapper.c:1036-1073), so
                # collision checks are scoped to the current call only
                o_sub = [0] * (result_max - osize)
                c_sub = [0] * (result_max - osize)
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif map.tunables.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    placed = _choose_firstn(
                        map, work, bucket, weight, x, numrep, step.arg2,
                        o_sub, 0, result_max - osize,
                        choose_tries, recurse_tries,
                        choose_local_retries, choose_local_fallback_retries,
                        recurse_to_leaf, vary_r, stable, c_sub, 0, choose_args)
                else:
                    placed = min(numrep, result_max - osize)
                    _choose_indep(
                        map, work, bucket, weight, x, placed, numrep,
                        step.arg2, o_sub, 0, choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, c_sub, 0, choose_args)
                o[osize:osize + placed] = o_sub[:placed]
                c[osize:osize + placed] = c_sub[:placed]
                osize += placed
            if recurse_to_leaf:
                o[:osize] = c[:osize]
            w, o = o, w
            wsize = osize
        elif step.op == RULE_EMIT:
            for i in range(wsize):
                if len(result) >= result_max:
                    break
                result.append(w[i])
            wsize = 0
    return result


# ---------------------------------------------------------------------------
# flat firstn scalar oracle (ops.crush_kernel.flat_firstn twin)
# ---------------------------------------------------------------------------

def flat_firstn_ref(xs, ids, weights, reweight, *, numrep: int,
                    tries: int = 51):
    """Scalar twin of ``ops.crush_kernel.flat_firstn`` — the host-path
    CRUSH oracle the dispatch engine's circuit breaker degrades to
    when the device path is out.  Same semantics, same retry ladder
    (r = rep + ftotal, abandon after ``tries`` failures), bit-for-bit:
    returns ``[[osd, ...numrep] per x]`` with CRUSH_ITEM_NONE on
    failure, matching the kernel's (N, numrep) int32 rows.

    Pure stdlib scalars (the straw2 draw reuses
    ``_bucket_straw2_choose``); no numpy, no jax — runnable while the
    accelerator runtime is exactly what failed.
    """
    ids = [int(i) for i in ids]
    weights = [int(w) for w in weights]
    reweight = [int(w) for w in reweight]
    bucket = Bucket(id=-1, type=1, alg=CRUSH_BUCKET_STRAW2,
                    items=ids, item_weights=weights)
    n_rw = len(reweight)

    def out_of(item: int, x: int) -> bool:
        # the kernel's is_out: ids beyond the reweight vector (or
        # negative) are out, full weight always in, zero always out,
        # else the 16-bit hash coin flip
        if item < 0 or item >= n_rw:
            return True
        w = reweight[item]
        if w >= 0x10000:
            return False
        if w == 0:
            return True
        return not (crush_hash32_2(x, item) & 0xFFFF) < w

    rows = []
    for x in xs:
        x = int(x) & 0xFFFFFFFF
        row = [CRUSH_ITEM_NONE] * numrep
        for rep in range(numrep):
            ftotal = 0
            while True:
                item = _bucket_straw2_choose(
                    bucket, x, rep + ftotal, None, 0)
                if item not in row and not out_of(item, x):
                    row[rep] = item
                    break
                ftotal += 1
                if ftotal >= tries:
                    break
        rows.append(row)
    return rows
