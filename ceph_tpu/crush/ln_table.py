"""Fixed-point log tables for straw2 (crush_ln).

The straw2 draw is ``crush_ln(hash & 0xffff) - 2^48`` divided by the 16.16 item
weight (src/crush/mapper.c:334-359), where crush_ln computes 2^44*log2(x+1) via two
table lookups (mapper.c:248-290).  The tables (src/crush/crush_ln_table.h) are
*protocol constants*: every Ceph client/OSD/kernel on earth evaluates placement with
exactly these values, so bit-identical placement requires bit-identical tables.

Their defining math (documented in the reference header) is:

    RH_LH[2k]   = 2^48 / (1 + k/128)        (reciprocal, k = 0..128)
    RH_LH[2k+1] = 2^48 * log2(1 + k/128)
    LL[k]       = 2^48 * log2(1 + k/2^15)   (k = 0..255)

We generate the tables from that math (verified rounding: RH is ceiling, LH/LL are
floor) — but the historically shipped tables deviate from the math in frozen,
load-bearing ways that changed placement forever once deployed:

* LH[128] shipped as 0xffff00000000 instead of 2^48.
* 212 of the 256 LL entries shipped with a constant excess of 0x147700000
  (an artifact of whatever generator produced them; ~0.44 LSB of the input scale);
  21 entries are exact; 23 entries hold unrelated stray values.

The deviations are reproduced here as explicit override data with the indices spelled
out, because matching deployed-placement behaviour requires them.  (Verified
programmatically against the reference checkout during development; the
exhaustive 16-bit validation lives in tests/test_crush_kernel.py
test_crush_ln_exhaustive_16bit and the range/monotonicity golden checks
in tests/test_crush_ref.py.)
"""

from __future__ import annotations

import functools
from decimal import Decimal, localcontext

import numpy as np

_LL_EXCESS = 0x147700000

# LL indices whose shipped value is the exact floor (no excess).
_LL_EXACT = frozenset(
    [0, 1, 203, 216, 222, 233, 237, 238, 239, 243, 244, 245, 246, 248, 249,
     250, 251, 252, 253, 254, 255]
)

# LL indices whose shipped value is neither floor nor floor+excess: frozen strays.
_LL_STRAY = {
    56: 0xA2B07F3458, 127: 0x16DF6CA19BD, 134: 0x182B07F3458,
    181: 0x209C06E6212, 184: 0x212B07F3458, 188: 0x21D6A73A78F,
    193: 0x22C23679B4E, 198: 0x23A2C3B0EA4, 199: 0x23D13EE805B,
    200: 0x24035E9221F, 207: 0x25492644D65, 210: 0x25D13EE805B,
    212: 0x26296453882, 225: 0x287BDBF5255, 227: 0x28D13EE805B,
    228: 0x29035E9221F, 229: 0x29296453882, 231: 0x29902A37AAB,
    235: 0x2A4C7605D61, 236: 0x2A7BDBF5255, 240: 0x2B296453882,
    241: 0x2B5D022D80F, 247: 0x2C61A5E8F4C,
}

_LH_128 = 0xFFFF00000000  # shipped value; the math gives 2^48


def _floor_log2_scaled(num: int, den: int) -> int:
    """floor(2^48 * log2(num/den)) with enough precision to round correctly."""
    with localcontext() as ctx:
        ctx.prec = 60
        val = (Decimal(num) / Decimal(den)).ln() / Decimal(2).ln()
        return int((val * (1 << 48)).to_integral_value(rounding="ROUND_FLOOR"))


@functools.lru_cache(maxsize=None)
def _tables() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    rh = np.zeros(129, dtype=np.int64)
    lh = np.zeros(129, dtype=np.int64)
    for k in range(129):
        # ceil(2^48 * 128 / (128 + k))
        num, den = (1 << 48) * 128, 128 + k
        rh[k] = -((-num) // den)
        lh[k] = _floor_log2_scaled(128 + k, 128)
    lh[128] = _LH_128
    ll = np.zeros(256, dtype=np.int64)
    for k in range(256):
        if k in _LL_STRAY:
            ll[k] = _LL_STRAY[k]
        else:
            base = _floor_log2_scaled((1 << 15) + k, 1 << 15)
            ll[k] = base if k in _LL_EXACT else base + _LL_EXCESS
    for t in (rh, lh, ll):
        t.flags.writeable = False
    return rh, lh, ll


def rh_table() -> np.ndarray:
    """RH[k] = reciprocal entries, k = 0..128 (int64, read-only)."""
    return _tables()[0]


def lh_table() -> np.ndarray:
    """LH[k] = 2^48*log2(1+k/128) entries, k = 0..128 (int64, read-only)."""
    return _tables()[1]


def ll_table() -> np.ndarray:
    """LL[k] = 2^48*log2(1+k/2^15) entries, k = 0..255 (int64, read-only)."""
    return _tables()[2]
