"""`radosgw-admin` command-line tool (src/rgw/rgw_admin.cc analog,
the user-management core): S3 users live as records in the gateway's
backing pool (`.users.registry`), so every radosgw over that pool
serves them — created here, usable through any gateway within its
short user-cache TTL, no restarts.

    python -m ceph_tpu.tools.rgw_admin_cli --mon <host> -p <pool> <cmd>

Commands:
    user create --uid NAME [--access A] [--secret S] [--tenant T]
    user ls | user info --uid NAME | user rm --uid NAME
    bucket ls                       (the pool's bucket registry)
"""

from __future__ import annotations

import argparse
import json
import secrets
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="radosgw-admin")
    p.add_argument("--mon", required=True, help="mon host(s)")
    p.add_argument("-p", "--pool", type=int, required=True)
    p.add_argument("--ms-type", default="async")
    p.add_argument("--auth-key", default="",
                   help="cluster shared key (authenticated clusters)")
    p.add_argument("words", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.words:
        p.error("missing command")

    from ceph_tpu.client import RadosClient
    from ceph_tpu.rgw_rest import (
        S3Gateway, load_pool_users, remove_pool_user, save_pool_user)
    client = RadosClient(args.mon, ms_type=args.ms_type,
                         auth_key=args.auth_key.encode()
                         if args.auth_key else None)
    client.connect()
    io = client.open_ioctx(args.pool)
    w = args.words
    try:
        if w[0] == "user":
            verb = w[1]
            sub = argparse.ArgumentParser(prog=f"radosgw-admin user {verb}")
            if verb != "ls":
                sub.add_argument("--uid", required=True)
            if verb == "create":
                sub.add_argument("--access", default="")
                sub.add_argument("--secret", default="")
                sub.add_argument("--tenant", default="",
                                 help="QoS tenant lane (defaults to "
                                      "the uid; see docs/QOS.md)")
            a = sub.parse_args(w[2:])
            users = load_pool_users(io)
            if verb == "ls":
                for access, rec in sorted(users.items()):
                    print(f"{rec.get('uid', '?')}\t{access}")
                return 0
            if verb == "create":
                if any(r.get("uid") == a.uid for r in users.values()):
                    print(f"user {a.uid!r} exists", file=sys.stderr)
                    return 1
                if a.access and a.access in users:
                    print(f"access key {a.access!r} belongs to "
                          f"{users[a.access].get('uid')!r}",
                          file=sys.stderr)
                    return 1
                access = a.access or \
                    "AK" + secrets.token_hex(9).upper()
                secret = a.secret or secrets.token_hex(20)
                save_pool_user(io, access, secret, a.uid,
                               tenant=a.tenant or None)
                print(json.dumps({"uid": a.uid, "access_key": access,
                                  "secret_key": secret,
                                  "tenant": a.tenant or a.uid},
                                 indent=1))
                return 0
            mine = {acc: r for acc, r in users.items()
                    if r.get("uid") == a.uid}
            if not mine:
                print(f"no such user {a.uid!r}", file=sys.stderr)
                return 1
            if verb == "info":
                print(json.dumps(
                    {"uid": a.uid,
                     "keys": [{"access_key": acc,
                               "created": r.get("created")}
                              for acc, r in sorted(mine.items())]},
                    indent=1))
                return 0
            if verb == "rm":
                for acc in mine:
                    remove_pool_user(io, acc)
                return 0
            raise SystemExit(f"unknown user verb {verb!r}")
        if w[0] == "bucket" and w[1] == "ls":
            try:
                reg = io.get_omap(S3Gateway.REGISTRY)
            except OSError:
                reg = {}
            for name in sorted(reg):
                print(name)
            return 0
        raise SystemExit(f"unknown command {' '.join(w)!r}")
    except IndexError:
        print(f"radosgw-admin: missing operand for {w[0]!r}",
              file=sys.stderr)
        return 2
    except OSError as e:
        print(f"radosgw-admin: {e}", file=sys.stderr)
        return 1
    finally:
        client.shutdown()


if __name__ == "__main__":
    sys.exit(main())
