"""Compressor plugin registry (src/compressor/ analog — the same
named-plugin pattern as the erasure-code registry; the reference's QAT
hook is the precedent for hardware-offloaded plugins behind this API).

Plugins: zlib and lzma (stdlib-backed; the reference's
snappy/zstd/lz4 are external libs this image doesn't carry), an
identity "none", and ``tpu_bitplane`` — the device bit-plane coder
(ops/compression_kernel.py) with host zlib as its oracle/fallback,
BlueStore's default compression algorithm.

``create`` validates kwargs against each plugin's declared ``KWARGS``
(an unknown kwarg names the accepted set instead of leaking an opaque
TypeError), and every plugin's ``decompress`` raises the typed
``CompressionError`` on malformed input so read paths can map corrupt
compressed data to EIO instead of leaking ``zlib.error``/``LZMAError``.
"""

from __future__ import annotations

import lzma
import struct
import threading
import zlib


class CompressionError(Exception):
    """A compressed payload could not be decoded (corrupt/truncated
    body, unknown scheme tag).  Read paths map this to EIO."""


class Compressor:
    name = "none"
    #: kwargs ``create`` accepts for this plugin (name -> caster)
    KWARGS: dict = {}

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes) -> bytes:
        return data


class ZlibCompressor(Compressor):
    name = "zlib"
    KWARGS = {"level": int}

    def __init__(self, level: int = 5):
        self.level = int(level)

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        try:
            return zlib.decompress(data)
        except zlib.error as e:
            raise CompressionError(f"zlib decompress failed: {e}") from e


class LzmaCompressor(Compressor):
    name = "lzma"
    KWARGS = {"level": int}

    def __init__(self, level: int = 6):
        # level maps to the lzma preset (0 fastest .. 9 smallest) —
        # the seed silently ignored a passed level
        self.level = int(level)

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data, preset=self.level)

    def decompress(self, data: bytes) -> bytes:
        try:
            return lzma.decompress(data)
        except lzma.LZMAError as e:
            raise CompressionError(f"lzma decompress failed: {e}") from e


class TpuBitplaneCompressor(Compressor):
    """Device bit-plane coder: fixed-width entropy coding as a batched
    bit-matrix kernel (ops/compression_kernel.py), with host zlib as
    the fallback coder when plane-dropping cannot win (random data).

    Output framing (1 scheme byte + body):
      0x00  stored raw (neither coder helped)
      0x01  bit-plane body (compression_kernel.encode/decode_block)
      0x02  zlib body
    """

    name = "tpu_bitplane"
    KWARGS = {"level": int, "device": bool}

    _T_RAW, _T_PLANE, _T_ZLIB = b"\x00", b"\x01", b"\x02"

    def __init__(self, level: int = 5, device: bool = True):
        self.level = int(level)       # zlib-fallback level
        self.device = bool(device)    # False = numpy oracle only

    def compress(self, data: bytes) -> bytes:
        if not data:
            return self._T_RAW
        from ceph_tpu.ops import compression_kernel as bk
        if len(data) <= bk.MAX_BLOCK:
            planes = bk.pack_planes([data], device=self.device)[0]
            body = bk.encode_block(data, planes)
            if len(body) < len(data):
                return self._T_PLANE + body
        z = zlib.compress(data, self.level)
        if len(z) < len(data):
            return self._T_ZLIB + z
        return self._T_RAW + data

    def compress_batch(self, blobs: list) -> list:
        """Batch flavor: every blob's plane extraction rides ONE
        device call (BlueStore uses this for multi-block writes)."""
        from ceph_tpu.ops import compression_kernel as bk
        small = [i for i, b in enumerate(blobs)
                 if b and len(b) <= bk.MAX_BLOCK]
        planes = bk.pack_planes([blobs[i] for i in small],
                                device=self.device)
        out = []
        by_idx = dict(zip(small, planes))
        for i, data in enumerate(blobs):
            if i not in by_idx:
                out.append(self.compress(data))
                continue
            body = bk.encode_block(data, by_idx[i])
            if len(body) < len(data):
                out.append(self._T_PLANE + body)
                continue
            z = zlib.compress(data, self.level)
            out.append(self._T_ZLIB + z if len(z) < len(data)
                       else self._T_RAW + data)
        return out

    def decompress(self, data: bytes) -> bytes:
        if not data:
            raise CompressionError("tpu_bitplane: empty payload")
        tag, body = data[:1], data[1:]
        if tag == self._T_RAW:
            return body
        if tag == self._T_ZLIB:
            try:
                return zlib.decompress(body)
            except zlib.error as e:
                raise CompressionError(
                    f"tpu_bitplane zlib body corrupt: {e}") from e
        if tag == self._T_PLANE:
            from ceph_tpu.ops import compression_kernel as bk
            try:
                return bk.decode_block(body)
            except (ValueError, struct.error) as e:
                raise CompressionError(
                    f"tpu_bitplane body corrupt: {e}") from e
        raise CompressionError(
            f"tpu_bitplane: unknown scheme tag {tag!r}")


# analysis: allow[bare-lock] -- import-time plugin registry lock; leaf
_LOCK = threading.Lock()
_FACTORIES = {
    "none": Compressor,
    "zlib": ZlibCompressor,
    "lzma": LzmaCompressor,
    "tpu_bitplane": TpuBitplaneCompressor,
}


def register(name: str, factory) -> None:
    with _LOCK:
        _FACTORIES[name] = factory


def create(name: str, **kw) -> Compressor:
    """Compressor::create (compressor/Compressor.h:97).  Kwargs are
    validated against the plugin's declared ``KWARGS`` — an unknown
    one raises a ValueError naming the accepted set (the seed raised
    an opaque TypeError from the factory call)."""
    with _LOCK:
        factory = _FACTORIES.get(name)
    if factory is None:
        raise KeyError(f"compressor {name!r} unknown; "
                       f"known: {sorted(_FACTORIES)}")
    accepted = getattr(factory, "KWARGS", None)
    if accepted is not None:
        bad = sorted(set(kw) - set(accepted))
        if bad:
            raise ValueError(
                f"compressor {name!r} does not accept {bad}; "
                f"accepted kwargs: {sorted(accepted)}")
    return factory(**kw)


def names() -> list[str]:
    with _LOCK:
        return sorted(_FACTORIES)
