"""dmClock tag algebra + distributed service tracking + QoS wire ext.

Property tests pin the MClockQueue equilibrium against a water-filling
oracle (reservation floors, weight-proportional excess, limit caps,
work-conserving fallback); ServiceTracker tests pin the (delta, rho)
accounting incl. the two-OSD cluster-wide reservation; wire tests pin
the MOSDOp v4 / MOSDOpReply v2 QoS extension round-trip and the
old-peer downgrade in both directions."""

import random

from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.messages.osd_msgs import MOSDOp, MOSDOpReply, OSDOpField
from ceph_tpu.osd.op_queue import ClassInfo, MClockQueue
from ceph_tpu.qos.dmclock import (
    PHASE_LIMIT, PHASE_RESERVATION, PHASE_WEIGHT, QosProfile,
    ServiceTracker, profiles_from_db)


# -- discrete-event oracle ----------------------------------------------------

def expected_rates(profiles: dict[str, ClassInfo],
                   capacity: float) -> dict[str, float]:
    """Steady-state service rates for FULLY BACKLOGGED classes at a
    fixed-capacity server: s_i = clamp(max(r_i, lambda * w_i), <= l_i)
    with lambda chosen so the rates sum to capacity (water-filling).
    Reservations beyond capacity share proportionally (earliest-R
    round robin); if every class is limit-capped below capacity the
    work-conserving fallback hands the surplus out proportional to the
    limits (earliest-L service equalizes l-tag progress)."""
    res_total = sum(p.reservation for p in profiles.values())
    if res_total >= capacity:
        return {n: capacity * p.reservation / res_total
                for n, p in profiles.items()}

    def rate(n, lam):
        p = profiles[n]
        s = max(p.reservation, lam * p.weight)
        return min(s, p.limit) if p.limit else s

    cap_total = sum(rate(n, float("1e18")) for n in profiles)
    if cap_total <= capacity:
        base = {n: rate(n, float("1e18")) for n in profiles}
        lim_total = sum(p.limit for p in profiles.values())
        extra = capacity - cap_total
        return {n: base[n] + extra * profiles[n].limit / lim_total
                for n in profiles}
    lo, hi = 0.0, 1e18
    for _ in range(200):
        mid = (lo + hi) / 2
        if sum(rate(n, mid) for n in profiles) > capacity:
            hi = mid
        else:
            lo = mid
    return {n: rate(n, lo) for n in profiles}


def drive(profiles: dict[str, ClassInfo], capacity: float,
          n_ops: int = 6000,
          demand: dict[str, float] | None = None) -> dict[str, dict]:
    """Serve n_ops at a fixed-capacity server under OPEN arrivals
    (each class demands `demand[n]` ops/s, default the full capacity —
    genuine overload, queues grow); virtual time advances 1/capacity
    per service.  Returns per-class served counts and phases."""
    q = MClockQueue(profiles)
    demand = demand or {n: capacity for n in profiles}
    next_arr = {n: 0.0 for n in profiles}
    now = 0.0
    out = {n: {"served": 0, "phases": {PHASE_RESERVATION: 0,
                                       PHASE_WEIGHT: 0, PHASE_LIMIT: 0}}
           for n in profiles}
    for _ in range(n_ops):
        now += 1.0 / capacity
        for n, rate in demand.items():
            while next_arr[n] <= now:
                q.enqueue(n, 0, now=next_arr[n])
                next_arr[n] += 1.0 / rate
        got = q.dequeue(now=now)
        assert got is not None, "work-conserving: backlog never idles"
        name, _item, phase, _wait = got
        out[name]["served"] += 1
        out[name]["phases"][phase] += 1
    return out


def _assert_rates(profiles, capacity, n_ops=6000, tol=0.12):
    got = drive(profiles, capacity, n_ops)
    want = expected_rates(profiles, capacity)
    t = n_ops / capacity
    for n in profiles:
        measured = got[n]["served"] / t
        assert abs(measured - want[n]) <= tol * capacity, (
            n, measured, want[n], {k: v["served"] for k, v in got.items()})
    return got


def test_reservation_floor_under_heavy_competitor():
    profiles = {
        "hog": ClassInfo(weight=100.0),
        "gold": ClassInfo(reservation=100.0, weight=0.001),
    }
    got = _assert_rates(profiles, capacity=500.0)
    # the floor is served in reservation phase, not weight luck
    assert got["gold"]["phases"][PHASE_RESERVATION] \
        > 0.8 * got["gold"]["served"]


def test_weight_proportional_excess():
    profiles = {
        "a": ClassInfo(weight=8.0),
        "b": ClassInfo(weight=2.0),
        "c": ClassInfo(weight=1.0),
    }
    got = _assert_rates(profiles, capacity=400.0)
    assert got["a"]["served"] / max(1, got["b"]["served"]) > 3.0
    assert got["b"]["served"] / max(1, got["c"]["served"]) > 1.5


def test_limit_caps_and_floor_coexist():
    profiles = {
        "hog": ClassInfo(weight=10.0),
        "gold": ClassInfo(reservation=80.0, weight=0.001),
        "capped": ClassInfo(weight=50.0, limit=40.0),
    }
    got = _assert_rates(profiles, capacity=400.0)
    t = 6000 / 400.0
    # the cap holds within 10% despite the large weight
    assert got["capped"]["served"] / t <= 40.0 * 1.1


def test_work_conserving_fallback_all_limited():
    profiles = {
        "x": ClassInfo(weight=1.0, limit=50.0),
        "y": ClassInfo(weight=1.0, limit=100.0),
    }
    got = drive(profiles, capacity=600.0, n_ops=3000)
    # every op served (drive asserts no idling); surplus beyond the
    # caps flows through the fallback phase, proportional to limits
    assert got["x"]["phases"][PHASE_LIMIT] > 0
    assert got["y"]["phases"][PHASE_LIMIT] > 0
    ratio = got["y"]["served"] / max(1, got["x"]["served"])
    assert 1.5 < ratio < 2.7, ratio


def test_reservations_beyond_capacity_share_proportionally():
    profiles = {
        "r1": ClassInfo(reservation=300.0, weight=0.001),
        "r2": ClassInfo(reservation=100.0, weight=0.001),
    }
    _assert_rates(profiles, capacity=200.0, tol=0.15)


def test_randomized_profiles_match_oracle():
    rng = random.Random(1234)
    for trial in range(6):
        profiles = {}
        for i in range(rng.randint(2, 5)):
            res = rng.choice([0.0, 0.0, rng.uniform(10, 80)])
            w = rng.uniform(0.5, 20.0)
            lim = rng.choice([0.0, 0.0, rng.uniform(120, 300)])
            if lim and res > lim:
                res = lim / 2
            profiles[f"t{i}"] = ClassInfo(reservation=res, weight=w,
                                          limit=lim)
        _assert_rates(profiles, capacity=500.0, n_ops=8000, tol=0.15)


# -- distributed (delta, rho) -------------------------------------------------

def test_service_tracker_params_and_accounting():
    st = ServiceTracker()
    assert st.get_params(0) == (1, 1)       # first contact
    st.track_resp(PHASE_RESERVATION)
    st.track_resp(PHASE_RESERVATION)
    st.track_resp(PHASE_WEIGHT)
    assert st.get_params(0) == (3, 2)       # 3 done, 2 in reservation
    assert st.get_params(0) == (1, 0)       # nothing since the refresh
    assert st.get_params(1) == (1, 1)       # new server: fresh contact
    d = st.dump()
    assert d["completions"] == 3 and d["reservation_completions"] == 2


def test_service_tracker_prunes_idle_servers():
    st = ServiceTracker(idle_age=0.0)
    for s in range(64):
        st.get_params(s, now=float(s))
    st._prune(now=1e9)
    assert st.server_count() == 0


def test_cluster_wide_reservation_via_delta_rho():
    """Two OSDs, one reserved tenant + a heavy competitor on each.
    With ServiceTracker (delta, rho) riding the ops the tenant's
    COMBINED reservation service stays near r; naive per-op (1, 1)
    tags double-dip to ~2r."""
    def run(tracked: bool) -> float:
        capacity = 400.0          # per OSD
        r = 100.0
        queues = [MClockQueue({
            "hog": ClassInfo(weight=1000.0),
            "gold": ClassInfo(reservation=r, weight=0.001)})
            for _ in range(2)]
        tracker = ServiceTracker()
        now = 0.0
        for q in queues:
            for _ in range(4):
                q.enqueue("hog", 0, now=now)
                d, rho = tracker.get_params(id(q)) if tracked else (1, 1)
                q.enqueue("gold", 0, now=now, delta=d, rho=rho)
        served_gold = 0
        n_steps = 4000
        for _ in range(n_steps):
            now += 1.0 / capacity
            for q in queues:
                got = q.dequeue(now=now)
                if got is None:
                    continue
                name, _i, phase, _w = got
                if name == "gold":
                    served_gold += 1
                    tracker.track_resp(phase)
                    d, rho = (tracker.get_params(id(q)) if tracked
                              else (1, 1))
                    q.enqueue("gold", 0, now=now, delta=d, rho=rho)
                else:
                    q.enqueue("hog", 0, now=now)
        return served_gold / (n_steps / capacity)

    naive = run(tracked=False)
    tracked = run(tracked=True)
    assert naive > 170.0, naive        # ~2r double dip
    assert tracked < 140.0, tracked    # ~r cluster-wide floor
    assert tracked > 70.0, tracked     # ... but the floor still holds


def test_client_trackers_are_per_tenant():
    """One gateway RadosClient serves many tenants: each tenant lane
    gets its OWN ServiceTracker, so a hog's completions can never
    inflate an idle tenant's (delta, rho) and charge it for service
    it did not receive."""
    from ceph_tpu.client.rados import RadosClient
    c = RadosClient.__new__(RadosClient)
    import threading
    from collections import OrderedDict
    c._lock = threading.RLock()
    c._qos_trackers = OrderedDict()
    hog = c._tracker_for("hog")
    gold = c._tracker_for("gold")
    assert hog is not gold
    assert c._tracker_for("hog") is hog
    gold.get_params(0)
    for _ in range(500):
        hog.track_resp(PHASE_WEIGHT)
    # gold's view of osd.0 is untouched by the hog's completions
    assert c._tracker_for("gold").get_params(0) == (1, 0)
    # LRU bound: one-shot tenants age out
    c.QOS_TRACKER_CAP = 8
    for i in range(32):
        c._tracker_for(f"one-{i}")
    assert len(c._qos_trackers) == 8


# -- profiles -----------------------------------------------------------------

def test_qos_profile_validation_and_db_roundtrip():
    import pytest
    p = QosProfile(reservation=10, weight=5, limit=50)
    p.validate()
    assert QosProfile.from_dict(p.to_dict()) == p
    with pytest.raises(ValueError):
        QosProfile(weight=0).validate()
    with pytest.raises(ValueError):
        QosProfile(reservation=100, weight=1, limit=50).validate()
    db = {"gold": p.to_dict(), "broken": "not-a-dict"}
    profs = profiles_from_db(db)
    assert set(profs) == {"gold"} and profs["gold"].reservation == 10


# -- wire: MOSDOp v4 / MOSDOpReply v2 ----------------------------------------

def _roundtrip(msg, cls, my_version=None):
    enc = Encoder()
    msg.encode_payload(enc)
    out = cls.__new__(cls)
    out.decode_payload(Decoder(enc.tobytes()), 0)
    return out


def test_mosdop_qos_roundtrip():
    m = MOSDOp(client_id=7, tid=9, pgid=(1, 3), oid="o",
               ops=[OSDOpField(op=2, offset=0, length=3, data=b"abc")],
               epoch=5, qos_tenant="gold", qos_delta=4, qos_rho=2)
    got = _roundtrip(m, MOSDOp)
    assert (got.qos_tenant, got.qos_delta, got.qos_rho) == ("gold", 4, 2)
    assert got.oid == "o" and got.tid == 9

    r = MOSDOpReply(tid=9, result=0, epoch=5,
                    qos_phase=PHASE_RESERVATION)
    got = _roundtrip(r, MOSDOpReply)
    assert got.qos_phase == PHASE_RESERVATION


def test_mosdop_old_peer_decodes_v4_payload():
    """A seed-era (v3) decoder reads a v4 payload: the versioned
    section's length prefix skips the QoS tail, every v3 field
    lands intact."""
    m = MOSDOp(client_id=7, tid=9, pgid=(1, 3), oid="obj",
               ops=[OSDOpField(op=1)], epoch=5, snapid=2,
               write_snapc=4, qos_tenant="gold", qos_delta=9,
               qos_rho=9)
    enc = Encoder()
    m.encode_payload(enc)
    seen = {}

    def v3_body(d, v):
        assert v == 4                      # the writer's version
        seen["client_id"] = d.u64()
        seen["tid"] = d.u64()
        seen["pgid"] = (d.s64(), d.u32())
        seen["oid"] = d.str()
        seen["epoch"] = d.u32()
        seen["ops"] = d.list(OSDOpField.decode)
        seen["snapid"] = d.u64()
        seen["write_snapc"] = d.u64()
        # ... and STOPS: the qos tail is skipped by the section length
    Decoder(enc.tobytes()).versioned(3, v3_body)
    assert seen["oid"] == "obj" and seen["write_snapc"] == 4

    # reply side: v1 decoder over a v2 payload
    r = MOSDOpReply(tid=9, result=-5, epoch=5, qos_phase=PHASE_WEIGHT)
    enc = Encoder()
    r.encode_payload(enc)
    got = {}

    def v1_body(d, v):
        got["tid"] = d.u64()
        got["result"] = d.s32()
        got["epoch"] = d.u32()
        got["ops"] = d.list(OSDOpField.decode)
    Decoder(enc.tobytes()).versioned(1, v1_body)
    assert got["tid"] == 9 and got["result"] == -5


def test_mosdop_new_peer_decodes_v3_payload():
    """An old-peer (v3) MOSDOp decodes on this build with neutral QoS
    defaults: empty tenant, delta = rho = 1 (exact mClock)."""
    enc = Encoder()
    enc.versioned(3, 1, lambda e: (
        e.u64(7), e.u64(9), e.s64(1), e.u32(3), e.str("obj"), e.u32(5),
        e.list([OSDOpField(op=1)], lambda e2, op: op.encode(e2)),
        e.u64(0), e.u64(0)))
    m = MOSDOp.__new__(MOSDOp)
    m.decode_payload(Decoder(enc.tobytes()), 0)
    assert m.oid == "obj"
    assert (m.qos_tenant, m.qos_delta, m.qos_rho) == ("", 1, 1)

    enc = Encoder()
    enc.versioned(1, 1, lambda e: (
        e.u64(9), e.s32(0), e.u32(5),
        e.list([], lambda e2, op: op.encode(e2))))
    r = MOSDOpReply.__new__(MOSDOpReply)
    r.decode_payload(Decoder(enc.tobytes()), 0)
    assert r.qos_phase == 0


def test_feature_bit_registered():
    from ceph_tpu.msg.features import (
        FEATURE_QOS_TAGS, SUPPORTED_FEATURES, feature_names)
    assert SUPPORTED_FEATURES & FEATURE_QOS_TAGS
    assert "qos-tags" in feature_names(FEATURE_QOS_TAGS)
