"""DCN story: the cluster data path across OS-process boundaries.

The reference scales past one host with NCCL-less TCP messengers; the
TPU-native equivalent (SURVEY.md §5) is a two-plane design:

* data plane — `jax.distributed` multi-controller runtime: each process
  owns its local devices (ICI domain), XLA collectives ride DCN between
  processes.  One global `Mesh` spans every device of every process and
  `jit` over sharded global arrays inserts the cross-process collectives
  exactly as it inserts ICI ones inside a process.
* control plane — the same TCP messenger stack the daemons use
  (`msg/event_tcp.py`), carrying typed messages between processes.

`run_dcn_pair(n)` is the executable proof: it spawns TWO worker
processes, each with n/2 virtual CPU devices; the workers build the
global 2-process mesh, run the batched GF(2^8) erasure encode over
globally-sharded stripes with a cross-process reduction, verify the
result against the host oracle, and then cross-check their digests over
a TCP messenger session.  `__graft_entry__.dryrun_multichip` invokes it,
so the driver exercises the multi-process path on every round.

`pick_stack(peer_process, my_process)` is the SURVEY §5 routing rule the
messenger family uses: same process -> "ici" (device-buffer handoff),
different process -> "async" (TCP/DCN).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time


def pick_stack(peer_process: int, my_process: int) -> str:
    """Messenger stack per peer: ICI inside a process, TCP across."""
    return "ici" if peer_process == my_process else "async"


def run_dcn_pair(n_devices: int = 8, timeout: float = 240.0,
                 retries: int = 1) -> None:
    """Spawn the two-process mesh proof; raises on any failure.
    One retry absorbs environment flakes (coordinator port races,
    jax startup stalls on a loaded host) — the assertion content is
    deterministic, only the process orchestration is not."""
    last: Exception | None = None
    for _attempt in range(retries + 1):
        try:
            _run_dcn_pair_once(n_devices, timeout)
            return
        except (RuntimeError, TimeoutError) as e:
            last = e
    raise last


def _run_dcn_pair_once(n_devices: int, timeout: float) -> None:
    assert n_devices >= 2 and n_devices % 2 == 0, \
        "need an even global device count of at least 2"
    from ceph_tpu.common import free_port
    coord = f"127.0.0.1:{free_port()}"
    ms_port = free_port()
    procs = []
    env = dict(os.environ)
    # the workers configure their own platform; a parent-forced platform
    # (e.g. the test conftest's cpu pin) must not leak conflicting
    # device counts into them
    env.pop("XLA_FLAGS", None)
    for pid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.parallel.dcn",
             "--coordinator", coord, "--num-processes", "2",
             "--process-id", str(pid),
             "--local-devices", str(n_devices // 2),
             "--ms-port", str(ms_port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    deadline = time.time() + timeout
    outs = []
    for p in procs:
        remaining = max(1.0, deadline - time.time())
        try:
            out, _ = p.communicate(timeout=remaining)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise TimeoutError("dcn worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"dcn worker {pid} failed (rc={p.returncode}):\n{out}")


def worker_main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--local-devices", type=int, required=True)
    ap.add_argument("--ms-port", type=int, required=True)
    args = ap.parse_args(argv)

    # platform setup MUST precede any jax backend initialization
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count="
        f"{args.local_devices}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(args.coordinator, args.num_processes,
                               args.process_id)
    import functools

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import ceph_tpu  # noqa: F401  (x64 for the GF/CRUSH kernels)
    from ceph_tpu.gf.matrix import gen_cauchy1_matrix
    from ceph_tpu.gf.tables import bit_matrix
    from ceph_tpu.ops.gf_kernel import _encode_xla, ec_encode_ref

    n_global = args.num_processes * args.local_devices
    devs = jax.devices()
    assert len(devs) == n_global, (len(devs), n_global)
    mesh = Mesh(np.array(devs), ("dp",))

    # deterministic global workload; every process derives the same bytes
    k, m, chunk = 4, 2, 256
    stripes = 4 * n_global
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8)
    per_proc = stripes // args.num_processes
    local = data[args.process_id * per_proc:
                 (args.process_id + 1) * per_proc]
    sharding = NamedSharding(mesh, P("dp", None, None))
    arr = jax.make_array_from_process_local_data(sharding, local)

    coding = gen_cauchy1_matrix(k, m)[k:]
    w = jnp.asarray(bit_matrix(coding))
    enc = functools.partial(_encode_xla, w, k=k, m=m)

    # encode over the GLOBAL mesh; the jnp.sum is a cross-process
    # all-reduce riding the DCN backend
    total = int(jax.jit(
        lambda d: jnp.sum(enc(d).astype(jnp.int64)))(arr))
    expect = int(ec_encode_ref(coding, data).astype(np.int64).sum())
    assert total == expect, (total, expect)

    # control plane: cross-check digests over the TCP messenger.
    # data plane #2: each worker also stages a bulk chunk in its
    # IciTransport wire mode and hands the TOKEN to the peer, which
    # redeems it with a cross-process device pull — the ici-wire
    # messenger's EC-shard path exercised at the transport level
    from ceph_tpu.messages import MMonCommand, MMonCommandAck
    from ceph_tpu.msg.ici import IciTransport
    from ceph_tpu.msg.messenger import Dispatcher, EntityName, Messenger

    ici = IciTransport.instance()
    try:
        ici.enable_wire()
        my_chunk = bytes([args.process_id]) * 65536
        my_token = ici.stage(my_chunk,
                             EntityName("osd", 1 - args.process_id))
    except Exception:
        # backend without the transfer engine: the control-plane proof
        # still runs; token fields stay empty and both sides skip
        my_token = b""

    def check_peer_token(tok_hex: str, peer_pid: int) -> bool:
        if not (my_token and tok_hex):
            return True     # transfer engine unavailable: skip
        data = ici.redeem(bytes.fromhex(tok_hex))
        assert data == bytes([peer_pid]) * 65536, len(data)
        assert ici.pulls >= 1     # it really crossed processes
        return True

    stack = pick_stack(peer_process=1 - args.process_id,
                       my_process=args.process_id)
    assert stack == "async"
    result = {}
    if args.process_id == 0:
        class D(Dispatcher):
            def ms_dispatch(self, msg):
                if isinstance(msg, MMonCommand):
                    if msg.cmd.get("done"):
                        # the peer finished its pull of OUR token: we
                        # may tear the transfer server down now
                        result["done"] = True
                        return True
                    ok = (msg.cmd.get("total") == total
                          and check_peer_token(
                              msg.cmd.get("token", ""), 1))
                    msg.connection.send_message(MMonCommandAck(
                        tid=msg.tid, result=0 if ok else -1,
                        output=my_token.hex()))
                    # publish only AFTER the pull + ack: the main
                    # thread must not shut us down mid-handshake
                    result["peer"] = msg.cmd
                    return True
                return False

        ms = Messenger.create(EntityName("mon", 0), stack)
        ms.add_dispatcher_tail(D())
        ms.bind(f"127.0.0.1:{args.ms_port}")
        ms.start()
        want = {"peer"} | ({"done"} if my_token else set())
        deadline = time.time() + 60
        while not want <= result.keys() and time.time() < deadline:
            time.sleep(0.05)
        ms.shutdown()
        assert result.get("peer", {}).get("total") == total, result
        assert not my_token or result.get("done"), result
    else:
        acked = {}

        class D(Dispatcher):
            def ms_dispatch(self, msg):
                if isinstance(msg, MMonCommandAck):
                    acked["rc"] = msg.result
                    acked["token"] = msg.output
                    return True
                return False

        ms = Messenger.create(EntityName("osd", 1), stack)
        ms.add_dispatcher_tail(D())
        ms.start()
        con = ms.connect_to(f"127.0.0.1:{args.ms_port}",
                            EntityName("mon", 0))
        con.send_message(MMonCommand(tid=1, cmd={
            "total": total, "process": args.process_id,
            "devices": n_global, "token": my_token.hex()}))
        deadline = time.time() + 60
        while "rc" not in acked and time.time() < deadline:
            time.sleep(0.05)
        assert check_peer_token(acked.get("token", ""), 0)
        # release the stager: our pull of its token is complete
        con.send_message(MMonCommand(tid=2, cmd={"done": 1}))
        time.sleep(0.2)     # let the frame flush before teardown
        ms.shutdown()
        assert acked.get("rc") == 0, acked
    print(f"dcn worker {args.process_id}: global sum {total} over "
          f"{n_global} devices in {args.num_processes} processes")
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
