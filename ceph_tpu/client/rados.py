"""librados-style client + Objecter.

The op path mirrors the reference (SURVEY.md §3.1): IoCtx.operate -> Objecter
op_submit -> _calc_target (client-side CRUSH on the subscribed OSDMap) ->
MOSDOp to the primary -> MOSDOpReply completes the waiter.  Map updates
re-target and resend every in-flight op (Objecter resend-on-map-change).

Object -> ps uses ceph_str_hash_rjenkins (src/common/ceph_hash.cc) — the
Jenkins lookup2 string hash, distinct from the CRUSH rjenkins1 mix.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager

from ceph_tpu.common.context import CephTpuContext
from ceph_tpu.messages import MMonCommand, MMonCommandAck, MOSDMapMsg, MOSDOp
from ceph_tpu.messages.osd_msgs import (
    MWatchNotify, MWatchNotifyAck, OP_CALL, OP_NOTIFY, OP_UNWATCH,
    OP_WATCH)
from ceph_tpu.messages.osd_msgs import (
    OP_DELETE, OP_OMAP_GET, OP_OMAP_RMKEYS, OP_OMAP_SET, OP_PGLS,
    OP_READ, OP_STAT, OP_WRITE, OP_WRITEFULL, OSDOpField)
from ceph_tpu.mon.monitor import MMonSubscribe
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.messages import MOSDOpReply
from ceph_tpu.osd.map_codec import advance_map
from ceph_tpu.osd.osdmap import CEPH_NOSD, OSDMap, pg_to_pgid

_M32 = 0xFFFFFFFF


def _mix3(a: int, b: int, c: int) -> tuple[int, int, int]:
    """Jenkins lookup2 mix (ceph_hash.cc mix() macro)."""
    a = (a - b - c) & _M32; a ^= c >> 13
    b = (b - c - a) & _M32; b ^= (a << 8) & _M32
    c = (c - a - b) & _M32; c ^= b >> 13
    a = (a - b - c) & _M32; a ^= c >> 12
    b = (b - c - a) & _M32; b ^= (a << 16) & _M32
    c = (c - a - b) & _M32; c ^= b >> 5
    a = (a - b - c) & _M32; a ^= c >> 3
    b = (b - c - a) & _M32; b ^= (a << 10) & _M32
    c = (c - a - b) & _M32; c ^= b >> 15
    return a, b, c


def ceph_str_hash_rjenkins(s: bytes | str) -> int:
    """ceph_str_hash_rjenkins (src/common/ceph_hash.cc): lookup2 over bytes."""
    if isinstance(s, str):
        s = s.encode("utf-8")
    length = len(s)
    a = b = 0x9E3779B9
    c = 0
    i = 0
    while length - i >= 12:
        a = (a + int.from_bytes(s[i:i + 4], "little")) & _M32
        b = (b + int.from_bytes(s[i + 4:i + 8], "little")) & _M32
        c = (c + int.from_bytes(s[i + 8:i + 12], "little")) & _M32
        a, b, c = _mix3(a, b, c)
        i += 12
    c = (c + length) & _M32
    rest = s[i:]
    if len(rest) >= 11:
        c = (c + (rest[10] << 24)) & _M32
    if len(rest) >= 10:
        c = (c + (rest[9] << 16)) & _M32
    if len(rest) >= 9:
        c = (c + (rest[8] << 8)) & _M32
    if len(rest) >= 8:
        b = (b + (rest[7] << 24)) & _M32
    if len(rest) >= 7:
        b = (b + (rest[6] << 16)) & _M32
    if len(rest) >= 6:
        b = (b + (rest[5] << 8)) & _M32
    if len(rest) >= 5:
        b = (b + rest[4]) & _M32
    if len(rest) >= 4:
        a = (a + (rest[3] << 24)) & _M32
    if len(rest) >= 3:
        a = (a + (rest[2] << 16)) & _M32
    if len(rest) >= 2:
        a = (a + (rest[1] << 8)) & _M32
    if len(rest) >= 1:
        a = (a + rest[0]) & _M32
    a, b, c = _mix3(a, b, c)
    return c


class _Waiter:
    def __init__(self, msg: MOSDOp, base_pool: int, is_write: bool,
                 direct: bool = False,
                 fixed_pgid: tuple[int, int] | None = None):
        self.msg = msg
        #: PG-targeted ops (pgls): the pg is the address, no oid hash
        self.fixed_pgid = fixed_pgid
        #: the pool the caller named — retargeting re-applies any
        #: cache-tier overlay from this, not from a prior redirect
        self.base_pool = base_pool
        self.is_write = is_write
        #: bypass cache-tier overlays (the tier agent's own I/O must
        #: reach the pool it names, or flushes would loop back into
        #: the cache and evict would destroy the only copy)
        self.direct = direct
        self.event = threading.Event()
        self.reply: MOSDOpReply | None = None
        #: map-change/stale-epoch resend count: the first resend is
        #: immediate, later ones back off exponentially with jitter
        self.resends = 0
        #: True while a deferred resend row sits in _resend_q: later
        #: map epochs coalesce into it (it targets from the newest map
        #: when it fires) instead of queueing duplicate sends
        self.resend_queued = False


class AioCompletion:
    """librados AioCompletion analog over a pending Objecter op."""

    def __init__(self, client: "RadosClient", tid: int, waiter: _Waiter):
        self.client = client
        self.tid = tid
        self._w = waiter

    def is_complete(self) -> bool:
        return self._w.event.is_set()

    def wait_for_complete(self, timeout: float | None = None) -> bool:
        return self._w.event.wait(timeout)

    def get_return_value(self) -> int:
        return self._w.reply.result if self._w.reply else -110  # ETIMEDOUT

    @property
    def reply(self) -> MOSDOpReply | None:
        return self._w.reply

    @property
    def data(self) -> bytes:
        r = self._w.reply
        return r.ops[0].data if r and r.ops else b""

    def cancel(self) -> None:
        with self.client._lock:
            self.client._waiters.pop(self.tid, None)
        # wake any blocked waiter: a cancelled op never gets its reply
        # (get_return_value reads -ETIMEDOUT from the missing reply)
        self._w.event.set()


class RadosClient(Dispatcher):
    """RadosClient + Objecter (librados/RadosClient.cc:229 connect)."""

    _next_client_id = 1
    # analysis: allow[bare-lock] -- import-time class-level client-id allocator; leaf
    _id_lock = threading.Lock()

    def __init__(self, mon_addr: str, ctx: CephTpuContext | None = None,
                 ms_type: str = "async", timeout: float = 10.0,
                 auth_key=None, cephx: tuple[str, str] | None = None):
        with RadosClient._id_lock:
            self.client_id = RadosClient._next_client_id
            RadosClient._next_client_id += 1
        self.ctx = ctx or CephTpuContext(f"client.{self.client_id}")
        self.mon_addr = mon_addr
        #: comma-separated mon_host list; subscribe to all, command with
        #: per-mon failover (any mon forwards commands to the leader)
        self.mon_addrs = [a for a in mon_addr.split(",") if a]
        self.timeout = timeout
        self.osdmap = OSDMap()
        #: op targeting reads the context's shared epoch-keyed mapping
        #: cache (Objecter-side OSDMapMapping): _calc_target becomes a
        #: cached-raw pipeline tail instead of a scalar crush_do_rule
        #: per op.  Hot-togglable; any epoch mismatch falls back to the
        #: scalar oracle, so correctness never depends on the cache.
        self._map_shared = bool(
            self.ctx.conf.get("osdmap_mapping_shared"))
        self.ctx.conf.add_observer(
            "osdmap_mapping_shared",
            lambda _n, v: setattr(self, "_map_shared", bool(v)))
        #: newest-map slot + single background warm worker: map storms
        #: must neither stall the dispatch thread nor spawn a thread
        #: per epoch (the slot keeps only the latest, matching the
        #: service's own newest-wins queueing)
        self._warm_latest: OSDMap | None = None
        self._warm_thread: threading.Thread | None = None
        self._map_event = threading.Event()
        # analysis: allow[bare-lock] -- client session RLock; client-local hierarchy, conversion deferred
        self._lock = threading.RLock()
        self._next_tid = 1
        self._waiters: dict[int, _Waiter] = {}
        self._cmd_waiters: dict[int, tuple[threading.Event, list]] = {}
        #: (pool, oid) -> watch callback(payload)
        self._watch_cbs: dict[tuple, object] = {}
        #: dmClock client state (qos.dmclock.ServiceTracker), one
        #: tracker PER QOS ENTITY — the tenant lane (or the bare
        #: client when untenanted): every outgoing MOSDOp is stamped
        #: with (delta, rho) for its target OSD — completions of THAT
        #: TENANT anywhere / in reservation phase since its last op to
        #: that OSD — so per-tenant reservations and limits hold
        #: across OSDs, not per daemon.  A single shared tracker would
        #: cross-contaminate tenants behind one gateway client: a hog's
        #: completions would inflate an idle tenant's delta and lock it
        #: out of its own weight/limit budget for service it never
        #: received.  Replies feed phases back via MOSDOpReply.qos_phase
        #: into the completing op's own tenant tracker.  LRU-bounded:
        #: one-shot tenants age out rather than growing the map forever.
        from collections import OrderedDict
        self._qos_trackers: "OrderedDict[str, object]" = OrderedDict()
        #: thread-local QoS tenant lane (qos_tenant() context manager):
        #: ops submitted by this thread bill to the tenant — the RGW
        #: front wraps each authenticated request in its tenant's lane
        self._qos_tl = threading.local()
        #: capped-backoff resend queue: (due monotonic, waiter) rows
        #: drained by a single coalesced timer — a map storm neither
        #: re-sends every in-flight op once per epoch nor spawns a
        #: timer per op
        self._resend_q: list[tuple[float, _Waiter]] = []
        self._resend_timer: threading.Timer | None = None
        #: the armed timer's deadline (monotonic): a new row due
        #: EARLIER must cancel and re-arm, or a short-backoff op waits
        #: behind a max-backoff op's far timer
        self._resend_due: float = 0.0
        #: client-side Objecter counters (librados perf dump analog):
        #: resend volume and how many of them were backoff-deferred
        from ceph_tpu.common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder(f"objecter.{self.client_id}")
                     .add_u64("op_resends")
                     .add_u64("op_resend_backoffs")
                     .create_perf_counters())
        self.ctx.perf.add(self.perf)
        self.name = EntityName("client", self.client_id)
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        if cephx is not None:
            # per-entity credentials: entity-secret proof to mons,
            # mon-granted tickets to every service
            from ceph_tpu.auth.cephx import TicketKeyring
            from ceph_tpu.auth.handshake import CephxConfig
            entity, secret = cephx
            self.auth_entity = entity
            self.msgr.set_auth_cephx(CephxConfig(
                entity=entity, key=secret,
                keyring=TicketKeyring(self._fetch_ticket)))
        else:
            self.auth_entity = None
        self.msgr.set_policy("osd", ConnectionPolicy.stateful_peer())
        self.msgr.set_policy("mon", ConnectionPolicy.stateful_peer())
        self.msgr.add_dispatcher_tail(self)

    def _fetch_ticket(self, service: str):
        """TicketKeyring callback: one mon round trip per refresh."""
        from ceph_tpu.auth.cephx import ticket_from_json
        try:
            rc, out = self.mon_command({"prefix": "auth get-ticket",
                                        "service": service})
        except (OSError, TimeoutError):
            return None
        return ticket_from_json(out) if rc == 0 else None

    # -- lifecycle ------------------------------------------------------------

    #: re-subscribe cadence: map pushes ride the mon-side session, so a
    #: dropped session must be re-established or the client goes stale
    SUB_RENEW = 5.0

    def connect(self) -> None:
        self.msgr.bind("127.0.0.1:0") if _is_tcp(self.msgr) else \
            self.msgr.bind(f"client.{self.client_id}")
        self.msgr.start()
        self._subscribe()
        if not self._map_event.wait(self.timeout):
            raise TimeoutError("no OSDMap from mon")
        self._sub_timer: threading.Timer | None = None
        self._schedule_sub_renew()

    def _subscribe(self) -> None:
        from ceph_tpu.common.moncmd import mon_targets
        with self._lock:
            epoch = self.osdmap.epoch
        for rank, addr in mon_targets(self.osdmap, self.mon_addrs):
            mon = self.msgr.connect_to(addr, EntityName("mon", rank))
            mon.send_message(MMonSubscribe(name=str(self.name),
                                           addr=self.msgr.my_addr,
                                           epoch=epoch))

    def _schedule_sub_renew(self) -> None:
        if getattr(self, "_stopped", False):
            return
        self._sub_timer = threading.Timer(self.SUB_RENEW, self._sub_renew)
        self._sub_timer.daemon = True
        self._sub_timer.start()

    def _sub_renew(self) -> None:
        try:
            self._subscribe()
        except OSError:
            pass
        finally:
            self._schedule_sub_renew()

    def shutdown(self) -> None:
        self._stopped = True
        if getattr(self, "_sub_timer", None) is not None:
            self._sub_timer.cancel()
        with self._lock:
            if self._resend_timer is not None:
                self._resend_timer.cancel()
                self._resend_timer = None
            self._resend_q.clear()
        self.msgr.shutdown()

    # -- dispatch -------------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, MOSDMapMsg):
            with self._lock:
                newmap, gapped = advance_map(self.osdmap, msg)
                if newmap is None:
                    if not gapped:
                        return True
                else:
                    self.osdmap = newmap
                    pending = list(self._waiters.values())
            if gapped:
                # deltas don't connect to our epoch: ask the mon to
                # backfill (it sends the chain or a full map)
                self._subscribe()
                return True
            if self._map_shared:
                # warm the shared cache in the BACKGROUND: the op path
                # must never stall behind a table build (a light client
                # on a many-pool cluster would otherwise pay an
                # OSD-sized rebuild on its dispatch thread); until the
                # build lands, targeting falls back to the scalar
                # oracle per op — exactly the seed's cost
                with self._lock:
                    self._warm_latest = newmap
                    if self._warm_thread is None:
                        self._warm_thread = threading.Thread(
                            target=self._warm_worker, daemon=True,
                            name="rados-map-warm")
                        self._warm_thread.start()
            self._map_event.set()
            for w in pending:   # resend on map change (Objecter semantics)
                self._resend_op(w)
            return True
        if isinstance(msg, MOSDOpReply):
            with self._lock:
                w = self._waiters.pop(msg.tid, None)
            if w is not None:
                # dmclock response accounting (phase echo -> rho): count
                # into the completing op's OWN tenant tracker before
                # waking the waiter, so the lane's next op carries the
                # completion in its (delta, rho)
                self._tracker_for(w.msg.qos_tenant).track_resp(
                    getattr(msg, "qos_phase", 0))
                w.reply = msg
                w.event.set()
            return True
        if isinstance(msg, MWatchNotify):
            cb = self._watch_cbs.get((msg.pool, msg.oid))
            if cb is not None:
                try:
                    cb(msg.payload)
                finally:
                    msg.connection.send_message(MWatchNotifyAck(
                        pool=msg.pool, oid=msg.oid,
                        notify_id=msg.notify_id))
            return True
        if isinstance(msg, MMonCommandAck):
            with self._lock:
                cw = self._cmd_waiters.pop(msg.tid, None)
            if cw is not None:
                cw[1].append(msg)
                cw[0].set()
            return True
        return False

    # -- mon commands ---------------------------------------------------------

    def mgr_command(self, cmd: dict) -> tuple[int, str]:
        """Route a mgr-tier command (pg dump / iostat / balancer ...):
        discover the active mgr through the mon, then send the command
        envelope straight to it (the reference's mgr command re-target)."""
        import json as _json
        mgr_db = self.osdmap.mgr_db or {}
        addr = mgr_db.get("addr", "")
        if not addr:
            # pre-mgr_db mons: fall back to asking
            rc, out = self.mon_command({"prefix": "mgr dump"})
            if rc != 0:
                return rc, out
            addr = _json.loads(out).get("addr", "")
        if not addr:
            return -2, "no active mgr"
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
            ev: tuple[threading.Event, list] = (threading.Event(), [])
            self._cmd_waiters[tid] = ev
        con = self.msgr.connect_to(addr, EntityName("mgr", 0))
        con.send_message(MMonCommand(tid=tid, cmd=cmd))
        if ev[0].wait(self.timeout):
            ack = ev[1][0]
            return ack.result, ack.output
        with self._lock:
            self._cmd_waiters.pop(tid, None)
        return -110, "mgr command timed out"

    def mon_command(self, cmd: dict) -> tuple[int, str]:
        """Cycle through the monitors until the overall deadline: a mon
        may be dead, electing, or between leaders — transient windows
        that the next attempt (or the next mon) heals."""
        import time as _time
        deadline = _time.time() + self.timeout
        last_exc: Exception | None = None
        from ceph_tpu.common.moncmd import mon_targets
        while True:
            for rank, addr in mon_targets(self.osdmap, self.mon_addrs):
                remaining = deadline - _time.time()
                if remaining <= 0:
                    raise last_exc if last_exc \
                        else TimeoutError("no monitors")
                with self._lock:
                    tid = self._next_tid
                    self._next_tid += 1
                    ev: tuple[threading.Event, list] = (threading.Event(),
                                                        [])
                    self._cmd_waiters[tid] = ev
                mon = self.msgr.connect_to(addr, EntityName("mon", rank))
                mon.send_message(MMonCommand(tid=tid, cmd=cmd))
                if ev[0].wait(min(2.5, remaining)):
                    ack = ev[1][0]
                    if ack.result == -11:  # no quorum there yet: an
                        # election is running; don't hammer the mons
                        last_exc = OSError(11, ack.output)
                        threading.Event().wait(0.25)
                        continue
                    return ack.result, ack.output
                with self._lock:
                    self._cmd_waiters.pop(tid, None)
                last_exc = TimeoutError(
                    f"mon command {cmd} timed out ({addr})")

    def wait_for_epoch(self, epoch: int, timeout: float | None = None
                       ) -> None:
        deadline = threading.Event()
        t = timeout if timeout is not None else self.timeout
        end = t
        import time as _time
        start = _time.time()
        while self.osdmap.epoch < epoch:
            if _time.time() - start > end:
                raise TimeoutError(
                    f"epoch {epoch} not reached (at {self.osdmap.epoch})")
            deadline.wait(0.02)

    # -- objecter -------------------------------------------------------------

    def _calc_target(self, pool_id: int, oid: str,
                     is_write: bool = False,
                     direct: bool = False) -> tuple[tuple[int, int],
                                                    int]:
        """osdc/Objecter.cc:2795 — object -> pg -> primary, client side.
        Cache-tier overlays redirect here (Objecter _calc_target honors
        pool.read_tier/write_tier): ops aimed at the base pool land on
        the cache pool instead; the cache OSD promotes/flushes."""
        pool = self.osdmap.pools[pool_id]
        tier = pool.write_tier if is_write else pool.read_tier
        if not direct and tier >= 0 and tier in self.osdmap.pools:
            pool_id, pool = tier, self.osdmap.pools[tier]
        ps = ceph_str_hash_rjenkins(oid)
        # reduce to the pg first (raw_pg_to_pg), THEN place — the osd receives
        # the reduced pg and must compute the identical mapping
        pgid = pg_to_pgid(ps, pool.pg_num)
        _up, _primary, _acting, acting_primary = \
            self._pg_mapping(pool_id, pgid)
        return (pool_id, pgid), acting_primary

    def _warm_worker(self) -> None:
        """Drain the newest-map slot into the shared mapping cache;
        exits (and deregisters) when the slot is empty.  The slot
        write and the exit decision share self._lock, so a map landing
        while we exit always sees _warm_thread None and respawns."""
        while True:
            with self._lock:
                nm = self._warm_latest
                self._warm_latest = None
                if nm is None:
                    self._warm_thread = None
                    return
            try:
                self.ctx.mapping_service().update_to(nm)
            except Exception:
                pass   # reads keep falling back to the scalar oracle

    def _pg_mapping(self, pool_id: int, pgid: int
                    ) -> tuple[list[int], int, list[int], int]:
        """(up, up_primary, acting, acting_primary) — shared mapping
        cache when enabled (scalar-oracle fallback on any epoch or
        object mismatch), else the scalar pipeline."""
        if self._map_shared:
            return self.ctx.mapping_service().lookup(
                self.osdmap, pool_id, pgid)
        return self.osdmap.pg_to_up_acting_osds(pool_id, pgid)

    def _send_op(self, w: _Waiter) -> None:
        if w.fixed_pgid is not None:
            # PG-targeted op (pgls): the pg IS the address — map it to
            # its primary directly, never rehash an oid
            pgid = w.fixed_pgid
            _up, _p, _a, primary = self._pg_mapping(pgid[0], pgid[1])
        else:
            pgid, primary = self._calc_target(w.base_pool, w.msg.oid,
                                              w.is_write, w.direct)
        w.msg.pgid = pgid
        w.msg.epoch = self.osdmap.epoch
        if w.is_write:
            # SnapContext stamp (Objecter rides the op's snapc, not the
            # server map): re-stamped on every (re)send from the pool
            # the op actually TARGETS this time (pgid[0]) — snap_seq is
            # monotone WITHIN a pool, but a retarget (cache tier added/
            # removed mid-op) crosses into an independent snap_seq
            # namespace, so carrying a max() across sends would
            # over-stamp the object's snapc there
            pool = self.osdmap.pools.get(pgid[0])
            if pool is not None:
                w.msg.write_snapc = pool.snap_seq
        if primary == CEPH_NOSD:
            return  # no primary this epoch; resent on next map
        # dmClock tags for THIS target from the op's own tenant lane:
        # (re)sends re-stamp because a retargeted op bills its service
        # deltas to the osd actually serving it (dmclock ServiceTracker
        # get_params per request)
        w.msg.qos_delta, w.msg.qos_rho = \
            self._tracker_for(w.msg.qos_tenant).get_params(primary)
        addr = self.osdmap.osd_addrs[primary]
        con = self.msgr.connect_to(addr, EntityName("osd", primary))
        con.send_message(w.msg)

    def _resend_op(self, w: _Waiter) -> None:
        """Resend an in-flight op after a map change / stale-epoch
        retarget, with CAPPED EXPONENTIAL BACKOFF + JITTER past the
        first resend: one map flip never delays an op, but an op that
        keeps being resent (map storm, flapping primary) waits
        ~base * 2^(n-1) ms (jittered, capped) between attempts instead
        of hammering the cluster once per epoch.  Deferred resends
        re-target from the NEWEST map when their timer fires — so an
        epoch arriving while a resend is already queued coalesces into
        the queued row (a second row would just duplicate the send)."""
        base = float(self.ctx.conf.get("client_resend_backoff_ms"))
        cap = float(self.ctx.conf.get("client_resend_backoff_max_ms"))
        send_now = False
        # one critical section for check-bump-queue: concurrent map
        # deliveries racing the resend_queued check must not both
        # queue (or both count) the same waiter
        with self._lock:
            if w.resend_queued:
                return
            w.resends += 1
            self.perf.inc("op_resends")
            if w.resends <= 1:
                send_now = True
            else:
                delay = min(cap, base * (2 ** (w.resends - 2)))
                delay *= (0.5 + 0.5 * random.random()) / 1e3
                self.perf.inc("op_resend_backoffs")
                w.resend_queued = True
                self._resend_q.append((time.monotonic() + delay, w))
                self._arm_resend_timer()
        if send_now:
            self._send_op(w)

    def _arm_resend_timer(self) -> None:
        """Under self._lock: one coalesced timer at the earliest due
        time serves the whole queue.  An armed timer is re-armed when
        a NEW row is due before its deadline — otherwise a 25 ms
        backoff queued behind a 2 s one would wait the full 2 s."""
        if not self._resend_q or getattr(self, "_stopped", False):
            return
        due = min(t for t, _ in self._resend_q)
        if self._resend_timer is not None:
            if due >= self._resend_due:
                return
            self._resend_timer.cancel()
        timer = threading.Timer(max(0.0, due - time.monotonic()),
                                self._drain_resends)
        timer.daemon = True
        self._resend_timer = timer
        self._resend_due = due
        timer.start()

    def _drain_resends(self) -> None:
        now = time.monotonic()
        with self._lock:
            self._resend_timer = None
            live = [(t, w) for t, w in self._resend_q
                    if w.msg.tid in self._waiters]   # replied: drop
            ready = [w for t, w in live if t <= now]
            self._resend_q = [(t, w) for t, w in live if t > now]
            for w in ready:
                w.resend_queued = False
            self._arm_resend_timer()
        for w in ready:
            try:
                self._send_op(w)
            except (OSError, TimeoutError):
                pass   # next map change (or timeout) retries again
            except Exception as e:
                # anything else (a pool deleted under the op making
                # _calc_target raise) must not unwind the ONE shared
                # timer thread mid-fan: the remaining ready waiters
                # were already dequeued with resend_queued=False and
                # would never be re-sent — stranded until their own
                # op timeout on a healthy cluster
                from ceph_tpu.common.logging import dout
                dout("rados", 0, "%s: resend of tid %d failed "
                     "(waiter left for map change/timeout): %r",
                     self.name, w.msg.tid, e)

    #: distinct tenant trackers retained per client (LRU)
    QOS_TRACKER_CAP = 1024

    def _tracker_for(self, tenant: str):
        """The tenant lane's own ServiceTracker (lazy, LRU-bounded);
        '' is the untenanted per-client lane."""
        from ceph_tpu.qos.dmclock import ServiceTracker
        with self._lock:
            t = self._qos_trackers.get(tenant)
            if t is None:
                t = self._qos_trackers[tenant] = ServiceTracker()
                while len(self._qos_trackers) > self.QOS_TRACKER_CAP:
                    self._qos_trackers.popitem(last=False)
            else:
                self._qos_trackers.move_to_end(tenant)
            return t

    @contextmanager
    def qos_tenant(self, tenant: str | None):
        """Bill every op submitted by this thread inside the block to
        the tenant's QoS lane (the RGW request wrapper): the tenant tag
        rides each MOSDOp and the OSDs schedule it as client.<tenant>
        with the qos_db profile.  Nests; None is a no-op lane."""
        prev = getattr(self._qos_tl, "tenant", None)
        self._qos_tl.tenant = tenant
        try:
            yield
        finally:
            self._qos_tl.tenant = prev

    def aio_operate(self, pool_id: int, oid: str, ops: list[OSDOpField],
                    snapid: int = 0, direct: bool = False,
                    pgid: tuple[int, int] | None = None,
                    tenant: str | None = None) -> "AioCompletion":
        """Submit without blocking (librados aio_*): returns a completion
        the caller waits on.  In-flight completions resend on map change
        like synchronous ops."""
        if "\x1d" in oid:
            # the GROUP SEPARATOR is reserved for the OSD's internal
            # snap-clone store names (osd.daemon.CLONE_SEP); allowing it
            # through would let a client oid impersonate a clone
            raise ValueError("object names may not contain \\x1d")
        is_write = any(op.op in (OP_WRITE, OP_WRITEFULL, OP_DELETE,
                                 OP_OMAP_SET, OP_OMAP_RMKEYS)
                       for op in ops)
        if tenant is None:
            tenant = getattr(self._qos_tl, "tenant", None)
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
            msg = MOSDOp(client_id=self.client_id, tid=tid,
                         pgid=(pool_id, 0), oid=oid, ops=ops,
                         epoch=self.osdmap.epoch, snapid=snapid,
                         qos_tenant=tenant or "")
            w = _Waiter(msg, pool_id, is_write, direct,
                        fixed_pgid=pgid)
            self._waiters[tid] = w
        self._send_op(w)
        return AioCompletion(self, tid, w)

    def operate(self, pool_id: int, oid: str, ops: list[OSDOpField],
                snapid: int = 0, direct: bool = False,
                pgid: tuple[int, int] | None = None,
                tenant: str | None = None) -> MOSDOpReply:
        # head sampling (tracing_sample_rate): an untraced op opens a
        # trace at the configured rate, whose root span covers submit
        # through reply — the tail-retention check then decides whether
        # the completed trace is worth keeping.  Explicit trace_ctx
        # callers pass through (already traced).
        from ceph_tpu.common import tracing
        with tracing.maybe_sampled(f"osd_op {oid}",
                                   daemon=f"client.{self.client_id}"):
            c = self.aio_operate(pool_id, oid, ops, snapid=snapid,
                                 direct=direct, pgid=pgid,
                                 tenant=tenant)
            if not c.wait_for_complete(self.timeout):
                c.cancel()
                raise TimeoutError(f"op {c.tid} on {oid} timed out")
            if c.get_return_value() < 0:
                raise OSError(-c.get_return_value(),
                              f"op on {oid} failed")
            return c.reply

    # -- pools ----------------------------------------------------------------

    def pool_id_by_name(self, name_or_id) -> int:
        return int(name_or_id)

    def open_ioctx(self, pool_id: int, direct: bool = False) -> "IoCtx":
        return IoCtx(self, int(pool_id), direct=direct)


def _is_tcp(msgr) -> bool:
    return msgr.is_wire


class IoCtx:
    """Pool I/O handle (librados IoCtx)."""

    def __init__(self, client: RadosClient, pool_id: int,
                 direct: bool = False, tenant: str | None = None):
        self.client = client
        self.pool_id = pool_id
        #: bypass cache-tier overlays (tier-agent internal I/O)
        self.direct = direct
        #: explicit QoS tenant lane: every op through this handle bills
        #: to the tenant (overrides the client's thread-local lane) —
        #: rgw_lite buckets and bench tenants use this form
        self.tenant = tenant

    def with_tenant(self, tenant: str | None) -> "IoCtx":
        """A view of this pool handle whose ops bill to the tenant's
        QoS lane (librados would set the ioctx namespace/tenant)."""
        return IoCtx(self.client, self.pool_id, direct=self.direct,
                     tenant=tenant)

    def _op(self, oid, ops, snapid=0):
        return self.client.operate(self.pool_id, oid, ops,
                                   snapid=snapid, direct=self.direct,
                                   tenant=self.tenant)

    def write_full(self, oid: str, data: bytes) -> None:
        self._op(oid, [OSDOpField(OP_WRITEFULL, 0, len(data), data)])

    def aio_write_full(self, oid: str, data: bytes) -> "AioCompletion":
        return self.client.aio_operate(
            self.pool_id, oid, [OSDOpField(OP_WRITEFULL, 0, len(data),
                                           data)], direct=self.direct,
            tenant=self.tenant)

    def aio_read(self, oid: str, length: int = 0,
                 offset: int = 0) -> "AioCompletion":
        return self.client.aio_operate(
            self.pool_id, oid, [OSDOpField(OP_READ, offset, length)],
            direct=self.direct, tenant=self.tenant)

    def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        self._op(oid, [OSDOpField(OP_WRITE, offset, len(data), data)])

    def read(self, oid: str, length: int = 0, offset: int = 0,
             snapid: int = 0) -> bytes:
        r = self._op(oid, [OSDOpField(OP_READ, offset, length)],
                     snapid=snapid)
        return r.ops[0].data if r.ops else b""

    def _watch_keys(self, oid: str) -> list[tuple]:
        """A cache-tier overlay redirects the watch to the cache pool,
        whose OSD sends notifies stamped with ITS pool id — register
        the callback under both keys so the lookup hits either way."""
        keys = [(self.pool_id, oid)]
        pool = self.client.osdmap.pools.get(self.pool_id)
        if pool is not None and not self.direct and pool.write_tier >= 0:
            keys.append((pool.write_tier, oid))
        return keys

    def watch(self, oid: str, callback) -> None:
        """Register for notifies on the object (librados watch; the
        callback runs on the client's dispatch thread)."""
        for k in self._watch_keys(oid):
            self.client._watch_cbs[k] = callback
        self._op(oid, [OSDOpField(OP_WATCH, 0, 0)])

    def unwatch(self, oid: str) -> None:
        for k in self._watch_keys(oid):
            self.client._watch_cbs.pop(k, None)
        self._op(oid, [OSDOpField(OP_UNWATCH, 0, 0)])

    def execute(self, oid: str, cls: str, method: str,
                inp: bytes = b"") -> bytes:
        """Run an in-OSD object class method (librados exec)."""
        data = cls.encode() + b"\0" + method.encode() + b"\0" + inp
        r = self._op(oid, [OSDOpField(OP_CALL, 0, 0, data)])
        return r.ops[0].data if r.ops else b""

    def notify(self, oid: str, payload: bytes = b"") -> None:
        """Fan payload out to every watcher; returns once all acked
        (librados notify)."""
        self._op(oid, [OSDOpField(OP_NOTIFY, 0, 0, payload)])

    def remove(self, oid: str) -> None:
        self._op(oid, [OSDOpField(OP_DELETE)])

    def stat(self, oid: str) -> dict:
        r = self._op(oid, [OSDOpField(OP_STAT)])
        return {"size": r.ops[0].length}

    def set_omap(self, oid: str, keys: dict) -> None:
        e = Encoder()
        e.map(keys, lambda e2, k: e2.str(k), lambda e2, v: e2.bytes(v))
        self._op(oid, [OSDOpField(OP_OMAP_SET, 0, 0, e.tobytes())])

    def get_omap(self, oid: str) -> dict:
        r = self._op(oid, [OSDOpField(OP_OMAP_GET)])
        return Decoder(r.ops[0].data).map(lambda d: d.str(),
                                          lambda d: d.bytes())

    def rm_omap_keys(self, oid: str, keys: list[str]) -> None:
        e = Encoder()
        e.list(keys, lambda e2, k: e2.str(k))
        self._op(oid, [OSDOpField(OP_OMAP_RMKEYS, 0, 0, e.tobytes())])

    def list_objects(self) -> list[str]:
        """Logical object names in the pool (`rados ls`): one PGLS op
        per PG of the BASE pool, each answered by that PG's primary
        (Objecter pg-targeted listing; librados nobjects_begin).
        Re-lists when pg_num grew mid-iteration — a PG split would
        otherwise silently omit objects rehashed to child PGs."""
        for _attempt in range(4):
            pool = self.client.osdmap.pools.get(self.pool_id)
            if pool is None:
                raise OSError(2, f"pool {self.pool_id} gone")
            pg_num = pool.pg_num
            names: set[str] = set()
            for ps in range(pg_num):
                r = self.client.operate(
                    self.pool_id, "", [OSDOpField(OP_PGLS, 0, 0)],
                    direct=True, pgid=(self.pool_id, ps))
                if r.result != 0:
                    raise OSError(-r.result or 5,
                                  f"pgls {self.pool_id}.{ps}")
                blob = r.ops[0].data if r.ops else b""
                if blob:
                    names.update(Decoder(blob).list(
                        lambda d: d.str()))
            cur = self.client.osdmap.pools.get(self.pool_id)
            if cur is not None and cur.pg_num == pg_num:
                return sorted(names)
        raise OSError(11, "pool splitting continuously; retry listing")
