"""Thrasher soak in CI (VERDICT round-1 item 8): randomized osd
kill/revive/out/in under a mixed replicated + EC workload; zero lost or
corrupt acked objects after heal."""

from ceph_tpu.tools.thrasher import run_soak


def test_thrasher_soak(tmp_path):
    res = run_soak(duration=18.0, seed=11, n_osds=6,
                   base_path=str(tmp_path))
    assert res["actions"] >= 5, res
    assert res["rep_ops"] > 50, res
    assert res["corruptions"] == [], res
    assert res["lost_rep"] == [], res
    assert res["lost_ec"] == [], res
    # structured health transitioned during the storm and recovered
    assert "HEALTH_WARN" in res["health_seen"], res["health_seen"]
    assert "OSD_DOWN" in res["health_seen"], res["health_seen"]
    assert res["final_health"] == "HEALTH_OK", res["final_health"]


def test_thrasher_soak_torn_ec_write_seed(tmp_path):
    """Regression: seed 14's storm tears an EC write across a kill (one
    shard lands at version V, the rest stay at V-1); peering must trim
    the authoritative log to the k-th highest holder last_update
    (_ec_trim_log) or recovery livelocks needing an unreconstructable
    version and the object reads as lost."""
    res = run_soak(duration=18.0, seed=14, n_osds=6,
                   base_path=str(tmp_path))
    assert res["corruptions"] == [], res
    assert res["lost_rep"] == [], res
    assert res["lost_ec"] == [], res
