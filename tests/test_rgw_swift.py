"""RGW Swift dialect (rgw_rest_swift.cc / rgw_swift_auth.cc analog):
TempAuth v1.0 tokens, account/container/object verbs, JSON and text
listings, metadata headers, COPY, and S3 interop over the same buckets."""

from __future__ import annotations

import http.client
import json

import pytest

from ceph_tpu.rgw_rest import S3Gateway
from ceph_tpu.rgw_swift import SwiftRestServer
from ceph_tpu.tools.vstart import MiniCluster


class SwiftClient:
    def __init__(self, addr: str):
        self.host, port = addr.rsplit(":", 1)
        self.port = int(port)
        self.token = None

    def req(self, method: str, path: str, body: bytes = b"",
            headers: dict | None = None):
        h = dict(headers or {})
        if self.token and "X-Auth-Token" not in h:
            h["X-Auth-Token"] = self.token
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=30)
        conn.request(method, path, body=body, headers=h)
        r = conn.getresponse()
        data = r.read()
        out = (r.status, data, dict(r.getheaders()))
        conn.close()
        return out

    def login(self, user: str, key: str):
        st, _, hdrs = self.req("GET", "/auth/v1.0", headers={
            "X-Auth-User": user, "X-Auth-Key": key})
        assert st == 200, st
        self.token = hdrs["X-Auth-Token"]
        return hdrs["X-Storage-Url"]


class FakeClock:
    def __init__(self):
        self.t = 1_700_000_000.0

    def __call__(self):
        return self.t


@pytest.fixture(scope="module")
def rig():
    c = MiniCluster(n_osds=3).start()
    c.wait_for_osd_count(3)
    client = c.client()
    pool = c.create_pool(client, pg_num=8, size=2)
    io = client.open_ioctx(pool)
    clock = FakeClock()
    gw = S3Gateway(io, clock=clock)
    srv = SwiftRestServer(gateway=gw, clock=clock).start()
    srv.add_account("acme", "secret-key")
    srv.add_account("rival", "other-key")
    sc = SwiftClient(srv.addr)
    sc.login("acme:admin", "secret-key")
    yield {"swift": sc, "srv": srv, "gw": gw, "clock": clock,
           "cluster": c}
    srv.shutdown()
    c.stop()


def test_auth_rejects_bad_creds_and_expired_tokens(rig):
    sc = SwiftClient(rig["srv"].addr)
    st, _, _ = sc.req("GET", "/auth/v1.0", headers={
        "X-Auth-User": "acme:admin", "X-Auth-Key": "WRONG"})
    assert st == 401
    sc.login("acme:admin", "secret-key")
    assert sc.req("GET", "/v1/AUTH_acme")[0] in (200, 204)
    # expire the token
    rig["clock"].t += 2 * 3600
    assert sc.req("GET", "/v1/AUTH_acme")[0] == 401
    # cross-account token is refused
    other = SwiftClient(rig["srv"].addr)
    other.login("rival:u", "other-key")
    assert other.req("GET", "/v1/AUTH_acme")[0] == 401
    rig["swift"].login("acme:admin", "secret-key")   # refresh for others


def test_container_object_lifecycle(rig):
    sc = rig["swift"]
    assert sc.req("PUT", "/v1/AUTH_acme/photos")[0] == 201
    assert sc.req("PUT", "/v1/AUTH_acme/photos")[0] == 202  # idempotent
    st, _, h = sc.req("PUT", "/v1/AUTH_acme/photos/cat.jpg",
                      body=b"meow" * 100, headers={
                          "X-Object-Meta-Kind": "feline"})
    assert st == 201
    st, data, h = sc.req("GET", "/v1/AUTH_acme/photos/cat.jpg")
    assert st == 200 and data == b"meow" * 100
    assert h.get("X-Object-Meta-Kind") == "feline"
    st, _, h = sc.req("HEAD", "/v1/AUTH_acme/photos/cat.jpg")
    assert st == 200

    # COPY via X-Copy-From preserves metadata
    st, _, _ = sc.req("PUT", "/v1/AUTH_acme/photos/copy.jpg",
                      headers={"X-Copy-From": "/photos/cat.jpg"})
    assert st == 200 or st == 201
    st, data, h = sc.req("GET", "/v1/AUTH_acme/photos/copy.jpg")
    assert data == b"meow" * 100
    assert h.get("X-Object-Meta-Kind") == "feline"

    # listings: text and json
    st, body, h = sc.req("GET", "/v1/AUTH_acme/photos")
    assert st == 200
    assert body.decode().splitlines() == ["cat.jpg", "copy.jpg"]
    assert h["X-Container-Object-Count"] == "2"
    st, body, _ = sc.req("GET", "/v1/AUTH_acme/photos?format=json")
    rows = json.loads(body)
    assert [r["name"] for r in rows] == ["cat.jpg", "copy.jpg"]
    assert rows[0]["bytes"] == 400

    # account listing shows the container
    st, body, _ = sc.req("GET", "/v1/AUTH_acme?format=json")
    assert any(r["name"] == "photos" for r in json.loads(body))

    # non-empty container refuses DELETE; empty one goes
    assert sc.req("DELETE", "/v1/AUTH_acme/photos")[0] == 409
    sc.req("DELETE", "/v1/AUTH_acme/photos/cat.jpg")
    sc.req("DELETE", "/v1/AUTH_acme/photos/copy.jpg")
    assert sc.req("DELETE", "/v1/AUTH_acme/photos/ghost")[0] == 404
    assert sc.req("DELETE", "/v1/AUTH_acme/photos")[0] == 204


def test_cross_account_isolation(rig):
    sc = rig["swift"]
    other = SwiftClient(rig["srv"].addr)
    other.login("rival:u", "other-key")
    assert sc.req("PUT", "/v1/AUTH_acme/private")[0] == 201
    sc.req("PUT", "/v1/AUTH_acme/private/doc", body=b"mine")
    # rival cannot touch acme's container through its own account path
    assert other.req("GET", "/v1/AUTH_rival/private/doc")[0] in (403, 404)
    st, _, _ = other.req("PUT", "/v1/AUTH_rival/private/doc",
                         body=b"theirs")
    assert st == 403   # container owned by swift:acme
    sc.req("DELETE", "/v1/AUTH_acme/private/doc")
    sc.req("DELETE", "/v1/AUTH_acme/private")


def test_s3_interop_same_buckets(rig):
    # a container made via Swift is a bucket the S3 gateway can read
    sc, gw = rig["swift"], rig["gw"]
    assert sc.req("PUT", "/v1/AUTH_acme/shared")[0] == 201
    sc.req("PUT", "/v1/AUTH_acme/shared/obj", body=b"both dialects")
    data, head = gw.get_object("shared", "obj")
    assert data == b"both dialects"
    # and S3-side writes appear in the Swift listing
    gw.put_object("shared", "from-s3", b"x", {})
    st, body, _ = sc.req("GET", "/v1/AUTH_acme/shared")
    assert "from-s3" in body.decode()
