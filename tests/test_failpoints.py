"""Fault-injected device runtime (common/failpoint.py + the dispatch
engine's supervised recovery).

The load-bearing claims, each pinned here:

  * failpoint framework — named points with always/prob/oneshot/nth
    modes, channel qualifiers, deterministic under seed(), driven by
    the ``kernel_failpoints`` option and the ``failpoint set/clear/ls``
    admin commands;
  * retry ladder — a transient device fault is retried with bounded
    exponential backoff and heals invisibly (bit-exact result,
    counters tell the story); permanent errors fan immediately;
  * circuit breaker — consecutive device failures open a per-channel
    breaker, batches route through the BIT-EXACT host oracle
    (ec_encode_ref / host pattern decode / scalar CRUSH / numpy
    ladder), a background probe re-closes it when the device heals,
    and traffic returns to the device path;
  * thread supervision — a dead dispatch/completion run-loop is
    revived and re-fans its in-flight batches; past the restart budget
    the engine WEDGES LOUDLY: every waiter gets EngineWedgedError and
    flush() raises instead of silently timing out (the PR 11 satellite
    regression);
  * degraded-mode visibility — fault counters, the
    ceph_kernel_fallback_* / ceph_kernel_breaker_* prometheus
    families, the MMgrReport v4 faults tail, and the mgr's
    KERNEL_DEGRADED health warning;
  * client resend hardening — map-change resends of the same op back
    off exponentially with jitter (first resend immediate), surfaced
    in the client perf dump.

Geometry reuses test_dispatch's K1/M1 (k=4, m=2) so the process-global
jit cache is shared rather than grown.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from ceph_tpu.common import failpoint
from ceph_tpu.ops import telemetry
from ceph_tpu.ops.dispatch import DeviceDispatchEngine, EngineWedgedError


@pytest.fixture(autouse=True)
def _clean_failpoints():
    """Failpoints are process-global: never leak armed points into (or
    out of) a test."""
    failpoint.clear()
    yield
    failpoint.clear()


def _engine(**kw):
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats(), **kw)
    eng.fault_backoff_ms = 1.0
    eng.fault_backoff_max_ms = 5.0
    eng.probe_interval = 0.05
    return eng


def _dbl(batch):
    return np.asarray(batch) * 2


def _wait_breaker(eng, channel, state, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if eng.breaker_states().get(channel) == state:
            return True
        time.sleep(0.02)
    return False


# -- framework ----------------------------------------------------------------

class TestFailpointFramework:
    def test_modes(self):
        failpoint.seed(1234)
        failpoint.set("site.a", "always")
        with pytest.raises(failpoint.InjectedDeviceFault):
            failpoint.hit("site.a")
        failpoint.set("site.a", "oneshot")
        with pytest.raises(failpoint.InjectedDeviceFault):
            failpoint.hit("site.a")
        failpoint.hit("site.a")          # disarmed itself
        failpoint.set("site.b", "nth:3")
        failpoint.hit("site.b")
        failpoint.hit("site.b")
        with pytest.raises(failpoint.InjectedDeviceFault):
            failpoint.hit("site.b")
        failpoint.hit("site.b")          # fired once, gone
        failpoint.set("site.c", "prob:1.0")
        with pytest.raises(failpoint.InjectedDeviceFault):
            failpoint.hit("site.c")
        failpoint.set("site.c", "prob:0.0")
        for _ in range(50):
            failpoint.hit("site.c")

    def test_channel_qualifier_and_ls(self):
        failpoint.set("dispatch.launch:ec_encode", "always")
        failpoint.hit("dispatch.launch", tag="ec_decode")   # other lane
        with pytest.raises(failpoint.InjectedDeviceFault):
            failpoint.hit("dispatch.launch", tag="ec_encode")
        rows = failpoint.ls()
        assert rows["dispatch.launch:ec_encode"]["fires"] == 1
        assert rows["dispatch.launch:ec_encode"]["mode"] == "always"
        failpoint.clear("dispatch.launch:ec_encode")
        assert failpoint.ls() == {}

    def test_bad_specs_rejected(self):
        with pytest.raises(ValueError):
            failpoint.set("x", "sometimes")
        with pytest.raises(ValueError):
            failpoint.set("x", "prob:1.5")
        with pytest.raises(ValueError):
            failpoint.set("x", "nth:0")
        with pytest.raises(ValueError):
            failpoint.configure("just-a-name")
        assert failpoint.ls() == {}      # nothing half-applied

    def test_config_option_drives_registry(self):
        from ceph_tpu.common.config import Config
        conf = Config()
        failpoint.configure_from_conf(conf)
        conf.set("kernel_failpoints",
                 "dispatch.launch:ec_encode=prob:0.5;"
                 "dispatch.device_put=oneshot")
        rows = failpoint.ls()
        assert rows["dispatch.launch:ec_encode"]["mode"] == "prob:0.5"
        assert rows["dispatch.device_put"]["mode"] == "oneshot"
        conf.set("kernel_failpoints", "")
        assert failpoint.ls() == {}

    def test_context_construction_keeps_programmatic_points(self):
        """The registry is process-global but contexts come and go: a
        daemon revived mid-storm applies its default-EMPTY
        kernel_failpoints spec, and that must not disarm points the
        chaos mode (or an admin) armed via set() — only replace the
        points the option itself owns."""
        from ceph_tpu.common.context import CephTpuContext
        failpoint.set("dispatch.launch:ec_encode", "prob:0.25")
        ctx = CephTpuContext("fp-survive-test")   # applies empty spec
        assert "dispatch.launch:ec_encode" in failpoint.ls()
        # the option still owns (and replaces) its own points...
        ctx.conf.set("kernel_failpoints", "dispatch.device_put=always")
        ctx.conf.set("kernel_failpoints", "")
        rows = failpoint.ls()
        assert "dispatch.device_put" not in rows
        # ...while the storm's point rides through untouched
        assert "dispatch.launch:ec_encode" in rows
        # set()/clear() take ownership back from the option
        ctx.conf.set("kernel_failpoints", "site.conf=always")
        failpoint.set("site.conf", "oneshot")
        ctx.conf.set("kernel_failpoints", "")
        assert failpoint.ls()["site.conf"]["mode"] == "oneshot"

    def test_admin_commands(self):
        from ceph_tpu.common.context import CephTpuContext
        ctx = CephTpuContext("fp-admin-test")
        assert ctx.admin.execute("failpoint set", name="site.x",
                                 mode="always") == "ok"
        assert "site.x" in ctx.admin.execute("failpoint ls")
        assert ctx.admin.execute("failpoint clear",
                                 name="site.x") == "ok"
        assert ctx.admin.execute("failpoint ls") == {}
        dump = ctx.admin.execute("dump_fault_stats")
        assert set(dump) == {"encode", "decode"}
        assert "breaker_states" in dump["encode"]

    def test_configure_ownership_is_per_context(self):
        """Contexts COEXIST in one process: a second context applying
        its (default-empty or own) kernel_failpoints spec must replace
        only the points ITS option armed — never another context's."""
        from ceph_tpu.common.context import CephTpuContext
        a = CephTpuContext("fp-owner-a")
        a.conf.set("kernel_failpoints", "dispatch.launch=prob:0.2")
        # constructing B applies ITS default-empty spec: A's survives
        b = CephTpuContext("fp-owner-b")
        assert "dispatch.launch" in failpoint.ls()
        b.conf.set("kernel_failpoints", "site.b=always")
        b.conf.set("kernel_failpoints", "")
        rows = failpoint.ls()
        assert "site.b" not in rows          # B replaced its own...
        assert "dispatch.launch" in rows     # ...and left A's alone
        a.conf.set("kernel_failpoints", "")
        assert "dispatch.launch" not in failpoint.ls()

    def test_thread_death_points_inject_base_exception(self):
        failpoint.set("dispatch.complete_thread_death", "oneshot")
        with pytest.raises(failpoint.InjectedThreadDeath):
            failpoint.hit("dispatch.complete_thread_death")
        # and except Exception cannot absorb it
        assert not isinstance(failpoint.InjectedThreadDeath("x"),
                              Exception)


# -- engine recovery (pure numpy fns — no jit cost) ---------------------------

class TestEngineRecovery:
    def test_transient_fault_retried_bit_exact(self):
        eng = _engine()
        try:
            failpoint.set("dispatch.launch:chan", "oneshot")
            data = np.arange(12, dtype=np.int64).reshape(6, 2)
            got = eng.submit(("k",), _dbl, data, label="chan",
                             fallback=_dbl).result(10)
            assert (got == data * 2).all()
            d = eng.stats.fault_dump()
            assert d["retries"] == 1 and d["retry_successes"] == 1
            assert d["fallback_batches"] == 0
            assert d["breaker_states"] == {}
        finally:
            eng.stop()

    def test_permanent_error_fans_immediately(self):
        eng = _engine()
        try:
            def bad(batch):
                raise ValueError("shape nonsense")
            f = eng.submit(("k",), bad, np.ones((2, 2)), label="chan",
                           fallback=_dbl)
            with pytest.raises(ValueError):
                f.result(10)
            assert eng.stats.fault_dump()["retries"] == 0
        finally:
            eng.stop()

    def test_persistent_fault_serves_fallback_then_probe_recloses(self):
        eng = _engine()
        eng.breaker_threshold = 2
        try:
            failpoint.set("dispatch.launch:chan", "always")
            for i in range(5):
                got = eng.submit(("k",), _dbl,
                                 np.full((3, 2), i, dtype=np.int64),
                                 label="chan", fallback=_dbl).result(10)
                assert (got == i * 2).all()   # bit-exact degradation
            d = eng.stats.fault_dump()
            assert d["breaker_opens"] == 1, d
            assert d["fallback_batches"] >= 2, d
            assert eng.breaker_states()["chan"] == \
                telemetry.BREAKER_OPEN
            # faults clear -> the background probe re-closes and the
            # device path resumes
            failpoint.clear()
            assert _wait_breaker(eng, "chan", telemetry.BREAKER_CLOSED)
            d = eng.stats.fault_dump()
            assert d["breaker_closes"] == 1 and d["probe_successes"] >= 1
            before = eng.stats.fault_dump()["fallback_batches"]
            got = eng.submit(("k",), _dbl,
                             np.full((2, 2), 9, dtype=np.int64),
                             label="chan", fallback=_dbl).result(10)
            assert (got == 18).all()
            assert eng.stats.fault_dump()["fallback_batches"] == before
        finally:
            eng.stop()

    def test_probe_failure_keeps_breaker_open(self):
        eng = _engine()
        eng.breaker_threshold = 1
        eng.fault_max_retries = 0
        try:
            failpoint.set("dispatch.launch:chan", "always")
            eng.submit(("k",), _dbl, np.ones((2, 2), dtype=np.int64),
                       label="chan", fallback=_dbl).result(10)
            assert _wait_breaker(eng, "chan", telemetry.BREAKER_OPEN)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if eng.stats.fault_dump()["probe_failures"] >= 2:
                    break
                time.sleep(0.02)
            d = eng.stats.fault_dump()
            assert d["probe_failures"] >= 2, d
            assert d["breaker_closes"] == 0, d
            assert eng.breaker_states()["chan"] in (
                telemetry.BREAKER_OPEN, telemetry.BREAKER_HALF_OPEN)
        finally:
            failpoint.clear()
            eng.stop()

    def test_no_fallback_error_fans_after_retries(self):
        eng = _engine()
        try:
            failpoint.set("dispatch.launch:chan", "always")
            f = eng.submit(("k",), _dbl, np.ones((2, 2)), label="chan")
            with pytest.raises(failpoint.InjectedDeviceFault):
                f.result(10)
            d = eng.stats.fault_dump()
            assert d["retries"] == eng.fault_max_retries
        finally:
            failpoint.clear()
            eng.stop()

    def test_breaker_channels_are_independent(self):
        eng = _engine()
        eng.breaker_threshold = 1
        eng.fault_max_retries = 0
        try:
            failpoint.set("dispatch.launch:sick", "always")
            eng.submit(("a",), _dbl, np.ones((2, 2), dtype=np.int64),
                       label="sick", fallback=_dbl).result(10)
            assert _wait_breaker(eng, "sick", telemetry.BREAKER_OPEN)
            got = eng.submit(("b",), _dbl,
                             np.full((2, 2), 4, dtype=np.int64),
                             label="healthy", fallback=_dbl).result(10)
            assert (got == 8).all()
            states = eng.breaker_states()
            assert states.get("healthy", telemetry.BREAKER_CLOSED) \
                == telemetry.BREAKER_CLOSED
            assert eng.stats.fault_dump()["breaker_opens"] == 1
        finally:
            failpoint.clear()
            eng.stop()

    def test_thread_death_supervision_refans_in_flight(self):
        """A dying completion run-loop is revived and the queued work
        is re-fanned — waiters never notice beyond latency."""
        eng = _engine()
        try:
            # prime threads so the failpoint hits a RUNNING loop
            eng.submit(("k",), _dbl, np.ones((2, 2), dtype=np.int64),
                       label="chan").result(10)
            failpoint.set("dispatch.complete_thread_death", "oneshot")
            futs = [eng.submit(("k",), _dbl,
                               np.full((2, 2), i, dtype=np.int64),
                               label="chan") for i in range(4)]
            for i, f in enumerate(futs):
                assert (f.result(10) == i * 2).all()
            d = eng.stats.fault_dump()
            assert d["thread_deaths"] >= 1 and d["thread_restarts"] >= 1
            assert eng.flush(10)
        finally:
            eng.stop()

    def test_dispatch_thread_death_also_supervised(self):
        eng = _engine()
        try:
            failpoint.set("dispatch.dispatch_thread_death", "oneshot")
            got = eng.submit(("k",), _dbl,
                             np.full((3, 2), 5, dtype=np.int64),
                             label="chan").result(10)
            assert (got == 10).all()
            assert eng.stats.fault_dump()["thread_restarts"] >= 1
        finally:
            eng.stop()

    def test_restart_budget_decays_after_healthy_window(self):
        """The budget bounds death STORMS, not isolated recovered
        deaths over an engine's lifetime: a run-loop healthy past
        thread_restart_window since its last death earns the budget
        back, so deaths spread out never wedge."""
        eng = _engine()
        eng.thread_restarts = 1
        eng.thread_restart_window = 0.05
        try:
            for i in range(3):     # 3 isolated deaths > budget of 1
                failpoint.set("dispatch.complete_thread_death",
                              "oneshot")
                got = eng.submit(("k",), _dbl,
                                 np.full((2, 2), i + 1, dtype=np.int64),
                                 label="chan").result(10)
                assert (got == 2 * (i + 1)).all()
                # wait out the injected death AND the healthy window
                deadline = time.monotonic() + 5
                while (failpoint.ls() and time.monotonic() < deadline):
                    time.sleep(0.01)
                time.sleep(0.1)
            assert eng.stats.fault_dump()["thread_deaths"] >= 3
            assert not eng._wedged
            assert eng.flush(10.0)
        finally:
            failpoint.clear()
            eng.stop()

    def test_wedge_is_loud_not_silent(self):
        """PR 11 satellite regression: restart budget exhausted ->
        every waiter gets EngineWedgedError, flush() RAISES instead of
        silently timing out, stop() reports failure, and new submits
        run inline rather than hanging."""
        eng = _engine()
        eng.thread_restarts = 0
        try:
            failpoint.set("dispatch.complete_thread_death", "always")
            f = eng.submit(("k",), _dbl, np.ones((2, 2)), label="chan")
            with pytest.raises(EngineWedgedError):
                f.result(10)
            failpoint.clear()
            with pytest.raises(EngineWedgedError):
                eng.flush(2.0)
            assert eng.stats.fault_dump()["thread_deaths"] >= 1
            # new submits are served inline — never dropped, never hung
            got = eng.submit(("k",), _dbl,
                             np.full((2, 2), 7, dtype=np.int64),
                             label="chan").result(5)
            assert (got == 14).all()
            assert eng.stop() is False    # wedged engines report it
        finally:
            failpoint.clear()
            eng.stop()

    def test_fallback_preserves_per_key_order(self):
        """Breaker-open fallback batches still deliver per-key in
        submission order (the OSD's log/commit ordering contract)."""
        eng = _engine()
        eng.breaker_threshold = 1
        eng.fault_max_retries = 0
        try:
            failpoint.set("dispatch.launch:chan", "always")
            eng.submit(("k",), _dbl, np.ones((2, 2), dtype=np.int64),
                       label="chan", fallback=_dbl).result(10)
            assert _wait_breaker(eng, "chan", telemetry.BREAKER_OPEN)
            order: list[int] = []
            lock = threading.Lock()
            futs = []
            for i in range(16):
                fut = eng.submit(("k",), _dbl,
                                 np.full((2, 2), i, dtype=np.int64),
                                 label="chan", fallback=_dbl)
                fut.add_done_callback(
                    lambda f, i=i: (lock.acquire(timeout=5),
                                    order.append(i), lock.release()))
                futs.append(fut)
            for f in futs:
                f.result(10)
            eng.flush(10)
            deadline = time.monotonic() + 5
            while len(order) < 16 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert order == list(range(16))
        finally:
            failpoint.clear()
            eng.stop()

    def test_device_put_boundary_fires_on_unmeshed_engines(self):
        """The h2d boundary failpoint must be reachable on
        single-device (unmeshed) engines too: DeviceChaos arms
        dispatch.device_put, and chaos coverage must not silently
        shrink to meshed hosts."""
        eng = _engine()
        try:
            failpoint.set("dispatch.device_put:chan", "oneshot")
            got = eng.submit(("k",), _dbl,
                             np.full((2, 2), 3, dtype=np.int64),
                             label="chan", fallback=_dbl).result(10)
            assert (got == 6).all()
            assert failpoint.ls() == {}      # the oneshot was consumed
            assert eng.stats.fault_dump()["retries"] >= 1
        finally:
            eng.stop()

    def test_fallback_batches_keep_phase_ledger_clean(self):
        """Breaker-routed batches time the HOST oracle under the
        launch anchor — recording them would let an outage dominate
        the steady device phase histograms with host-path runtimes
        (the same rule the recovery ladder already applies)."""
        eng = _engine()
        eng.breaker_threshold = 1
        eng.fault_max_retries = 0
        try:
            failpoint.set("dispatch.launch:chan", "always")
            eng.submit(("k",), _dbl, np.ones((2, 2), dtype=np.int64),
                       label="chan", fallback=_dbl).result(10)
            assert _wait_breaker(eng, "chan", telemetry.BREAKER_OPEN)
            before = eng.stats.phases.dump(False)["phases"]
            for i in range(3):
                eng.submit(("k",), _dbl,
                           np.full((2, 2), i, dtype=np.int64),
                           label="chan", fallback=_dbl).result(10)
            after = eng.stats.phases.dump(True)
            assert after["phases"] == before
            assert after["recent"] == []
        finally:
            failpoint.clear()
            eng.stop()

    def test_future_delivery_is_first_wins(self):
        """_deliver must be idempotent: _wedge racing the live
        completion thread (or a revived loop re-fanning its batch)
        must never overwrite a delivered result with a contradictory
        outcome — an acked op's value flipping to an error after its
        callbacks already fired, or the reverse."""
        from ceph_tpu.ops.dispatch import DispatchFuture
        f = DispatchFuture()
        seen = []
        f.add_done_callback(lambda fut: seen.append(fut.exception()))
        f._deliver(5, None)
        f._deliver(None, RuntimeError("late wedge"))
        assert f.result(1) == 5 and f.exception(1) is None
        assert seen == [None]          # callbacks fired exactly once
        # and the reverse ordering: a delivered error stays an error
        g = DispatchFuture()
        g._deliver(None, RuntimeError("real failure"))
        g._deliver(7, None)
        with pytest.raises(RuntimeError):
            g.result(1)

    def test_base_exception_continuation_cannot_strand_batch(self):
        """A done-callback raising past Exception (SystemExit-class)
        escapes _deliver's Exception-only shield AFTER the batch was
        popped from _inflight — it must not kill the completion loop
        mid-fan-out, or the batch's remaining futures would hang
        forever with no thread death able to re-fan them."""
        def slow_dbl(batch):
            time.sleep(0.05)
            return np.asarray(batch) * 2
        eng = _engine(max_delay_us=200000)
        try:
            # occupy the pipeline so the next submits coalesce into
            # ONE batch (idle engines flush each submit alone)
            warm = eng.submit(("warm",), slow_dbl,
                              np.ones((2, 2), dtype=np.int64),
                              label="chan")
            futs = [eng.submit(("k",), _dbl,
                               np.full((2, 2), i, dtype=np.int64),
                               label="chan") for i in range(4)]
            futs[0].add_done_callback(
                lambda f: (_ for _ in ()).throw(SystemExit("boom")))
            warm.result(10)
            for i, f in enumerate(futs):
                assert (f.result(10) == i * 2).all()
            assert eng.stats.fault_dump()["thread_deaths"] == 0
            # the loop is alive and serving
            got = eng.submit(("k2",), _dbl,
                             np.full((2, 2), 9, dtype=np.int64),
                             label="chan").result(10)
            assert (got == 18).all()
            assert eng.flush(10)
        finally:
            eng.stop()

    def test_pre_assembly_failure_cannot_leak_or_strand(self):
        """A failure BEFORE batch assembly (mesh lookup, bucketing,
        breaker routing) must fan to the batch's futures like any
        build error — not escape _dispatch_batch with _building
        incremented and the reqs already partitioned out of _pending,
        which would strand the waiters and make flush() time out
        silently forever."""
        eng = _engine()
        try:
            calls = {"n": 0}

            def broken_mesh_lookup():
                calls["n"] += 1
                if calls["n"] == 1:       # only the dispatch-path call
                    raise MemoryError("mesh lookup under pressure")
                return None
            eng._mesh_placement = broken_mesh_lookup
            # MemoryError is transient: the completion-thread retry
            # ladder rebuilds from reqs (no placement) and succeeds
            got = eng.submit(("k",), _dbl,
                             np.full((3, 2), 4, dtype=np.int64),
                             label="chan", fallback=_dbl).result(10)
            assert (got == 8).all()
            d = eng.stats.fault_dump()
            assert d["retries"] >= 1 and d["retry_successes"] >= 1
            assert eng.flush(10)          # nothing leaked in _building
            assert eng._building == 0
        finally:
            eng.stop()


# -- per-channel fallback bit-exactness (the chaos-gate oracle compare) -------

class TestChannelBitExactness:
    def _open_breaker(self, eng, channel):
        eng.breaker_threshold = 1
        eng.fault_max_retries = 0
        failpoint.set(f"dispatch.launch:{channel}", "always")

    def test_encode_channel_fallback_matches_device(self):
        from ceph_tpu.ec import registry_instance
        codec = registry_instance().factory(
            "jerasure", {"technique": "reed_sol_van", "k": "4",
                         "m": "2", "runtime": "tpu"})
        rng = np.random.default_rng(11)
        data = rng.integers(0, 256, (7, 4, 512), dtype=np.uint8)
        eng = _engine()
        try:
            device = np.asarray(
                codec.submit_chunks(eng, data).result(120))
            self._open_breaker(eng, "ec_encode")
            # trip the breaker, then compare the oracle-served result
            codec.submit_chunks(eng, data).result(120)
            assert _wait_breaker(eng, "ec_encode",
                                 telemetry.BREAKER_OPEN)
            degraded = np.asarray(
                codec.submit_chunks(eng, data).result(120))
            assert (degraded == device).all()
            assert eng.stats.fault_dump()["fallback_batches"] >= 1
        finally:
            failpoint.clear()
            eng.stop()

    def test_decode_channel_fallback_matches_device(self):
        from ceph_tpu.ec import registry_instance
        codec = registry_instance().factory(
            "jerasure", {"technique": "reed_sol_van", "k": "4",
                         "m": "2", "runtime": "tpu"})
        rng = np.random.default_rng(13)
        stripes = rng.integers(0, 256, (6, 4, 512), dtype=np.uint8)
        chosen, targets = (0, 2, 4, 5), (1, 3)   # mixed-pattern decode
        chosen2, targets2 = (1, 2, 3, 4), (0,)
        eng = _engine()
        try:
            dev1 = np.asarray(codec.submit_decode_chunks(
                eng, chosen, stripes, targets).result(120))
            dev2 = np.asarray(codec.submit_decode_chunks(
                eng, chosen2, stripes, targets2).result(120))
            self._open_breaker(eng, "ec_decode")
            codec.submit_decode_chunks(
                eng, chosen, stripes, targets).result(120)
            assert _wait_breaker(eng, "ec_decode",
                                 telemetry.BREAKER_OPEN)
            deg1 = np.asarray(codec.submit_decode_chunks(
                eng, chosen, stripes, targets).result(120))
            deg2 = np.asarray(codec.submit_decode_chunks(
                eng, chosen2, stripes, targets2).result(120))
            assert (deg1 == dev1).all() and (deg2 == dev2).all()
        finally:
            failpoint.clear()
            eng.stop()

    def test_crush_channel_fallback_matches_device(self):
        from ceph_tpu.ops.dispatch import submit_flat_firstn
        rng = np.random.default_rng(17)
        n_osds = 24
        ids = np.arange(n_osds, dtype=np.int32)
        weights = np.full(n_osds, 0x10000, dtype=np.int64)
        reweight = np.full(n_osds, 0x10000, dtype=np.int64)
        reweight[5] = 0
        xs = rng.integers(0, 2**32, 64, dtype=np.uint32)
        eng = _engine()
        try:
            device = np.asarray(submit_flat_firstn(
                eng, xs, ids, weights, reweight,
                numrep=3).result(300))
            self._open_breaker(eng, "crush_firstn")
            submit_flat_firstn(eng, xs, ids, weights, reweight,
                               numrep=3).result(300)
            assert _wait_breaker(eng, "crush_firstn",
                                 telemetry.BREAKER_OPEN, timeout=30)
            degraded = np.asarray(submit_flat_firstn(
                eng, xs, ids, weights, reweight,
                numrep=3).result(300))
            assert (degraded == device).all()
        finally:
            failpoint.clear()
            eng.stop()

    def test_ladder_channel_fallback_matches_device(self):
        from ceph_tpu.ops import placement_kernel as pk
        from ceph_tpu.ops.dispatch import submit_finish_ladder
        rng = np.random.default_rng(19)
        n, w, pairs, m_osd = 48, 4, 2, 10
        raw = rng.integers(0, m_osd, (n, w)).astype(np.int32)
        raw[rng.random((n, w)) < 0.1] = pk.NONE
        operands = pk.LadderOperands(
            raw=raw,
            pps=rng.integers(0, 2**32, n, dtype=np.uint32),
            raw_len=np.full(n, w, dtype=np.int32),
            up_rows=rng.integers(0, m_osd, (n, w)).astype(np.int32),
            up_len=rng.integers(0, w + 1, n).astype(np.int32),
            items=rng.integers(-1, m_osd,
                               (n, pairs, 2)).astype(np.int32),
            temp_rows=rng.integers(-1, m_osd, (n, w)).astype(np.int32),
            temp_len=(rng.integers(0, w + 1, n)
                      * (rng.random(n) < 0.3)).astype(np.int32),
            ptemp=np.where(rng.random(n) < 0.2,
                           rng.integers(0, m_osd, n),
                           -1).astype(np.int32),
            state=rng.integers(0, 4, m_osd).astype(np.int32),
            weight=(rng.integers(0, 2, m_osd)
                    * 0x10000).astype(np.int64),
            affinity=np.where(rng.random(m_osd) < 0.5, 0x10000,
                              rng.integers(0, 0x10000,
                                           m_osd)).astype(np.int32),
            erasure=False, width=w)
        eng = _engine()
        try:
            device = np.asarray(
                submit_finish_ladder(eng, operands).result(300))
            self._open_breaker(eng, "pg_finish")
            submit_finish_ladder(eng, operands).result(300)
            assert _wait_breaker(eng, "pg_finish",
                                 telemetry.BREAKER_OPEN, timeout=30)
            degraded = np.asarray(
                submit_finish_ladder(eng, operands).result(300))
            assert (degraded == device).all()
            # and the standalone oracle agrees (ladder_ref twin)
            ref = pk.ladder_ref(operands.raw, *operands.aux(),
                                operands.state, operands.weight,
                                operands.affinity, erasure=False)
            assert (ref == device).all()
        finally:
            failpoint.clear()
            eng.stop()


# -- client resend backoff ----------------------------------------------------

class TestClientResendBackoff:
    def _client(self):
        from ceph_tpu.client.rados import RadosClient
        return RadosClient("client-backoff-test", ms_type="loopback")

    def test_first_resend_immediate_then_backoff(self):
        from types import SimpleNamespace
        c = self._client()
        try:
            c.ctx.conf.set("client_resend_backoff_ms", 30.0)
            sent: list[float] = []
            c._send_op = lambda w: sent.append(time.monotonic())
            from ceph_tpu.client.rados import _Waiter
            w = _Waiter(SimpleNamespace(tid=1, qos_tenant=""), 0, True)
            c._waiters[1] = w
            t0 = time.monotonic()
            c._resend_op(w)                      # 1st: immediate
            assert len(sent) == 1 and sent[0] - t0 < 0.02
            c._resend_op(w)                      # 2nd: deferred
            assert len(sent) == 1
            deadline = time.monotonic() + 5
            while len(sent) < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert len(sent) == 2
            assert sent[1] - t0 >= 0.014         # >= base/2 (jitter floor)
            pd = c.ctx.perf.dump()
            obj = pd[f"objecter.{c.client_id}"]
            assert obj["op_resends"] == 2
            assert obj["op_resend_backoffs"] == 1
        finally:
            c.shutdown()

    def test_backoff_grows_and_caps(self):
        from types import SimpleNamespace
        c = self._client()
        try:
            c.ctx.conf.set("client_resend_backoff_ms", 10.0)
            c.ctx.conf.set("client_resend_backoff_max_ms", 25.0)
            c._send_op = lambda w: None
            from ceph_tpu.client.rados import _Waiter
            w = _Waiter(SimpleNamespace(tid=2, qos_tenant=""), 0, True)
            w.resends = 9                        # deep retry history
            c._waiters[2] = w
            t0 = time.monotonic()
            c._resend_op(w)
            with c._lock:
                (due, _w2), = c._resend_q
            # capped: jittered delay in [cap/2, cap]
            assert 0.010 <= due - t0 <= 0.027
        finally:
            c.shutdown()

    def test_completed_ops_drop_from_resend_queue(self):
        from types import SimpleNamespace
        c = self._client()
        try:
            c.ctx.conf.set("client_resend_backoff_ms", 20.0)
            sent = []
            c._send_op = lambda w: sent.append(w)
            from ceph_tpu.client.rados import _Waiter
            w = _Waiter(SimpleNamespace(tid=3, qos_tenant=""), 0, True)
            w.resends = 1
            c._waiters[3] = w
            c._resend_op(w)
            del c._waiters[3]                    # reply landed
            time.sleep(0.1)
            assert sent == []                    # never resent
        finally:
            c.shutdown()

    def test_epoch_storm_coalesces_deferred_resends(self):
        """A map storm while a resend is already deferred must NOT
        queue duplicate rows: the queued row targets from the newest
        map when it fires, so N epochs -> at most one queued send (and
        op_resends counts sends scheduled, not epochs observed)."""
        from types import SimpleNamespace
        c = self._client()
        try:
            c.ctx.conf.set("client_resend_backoff_ms", 30.0)
            sent = []
            c._send_op = lambda w: sent.append(time.monotonic())
            from ceph_tpu.client.rados import _Waiter
            w = _Waiter(SimpleNamespace(tid=1, qos_tenant=""), 0, True)
            c._waiters[1] = w
            c._resend_op(w)                      # 1st: immediate
            for _ in range(5):                   # epoch storm
                c._resend_op(w)
            with c._lock:
                assert len(c._resend_q) == 1     # coalesced, not 6 rows
            deadline = time.monotonic() + 5
            while len(sent) < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            time.sleep(0.08)                     # no trailing duplicates
            assert len(sent) == 2
            obj = c.ctx.perf.dump()[f"objecter.{c.client_id}"]
            assert obj["op_resends"] == 2
            # drained: the next epoch defers a fresh (deduped) row
            c._resend_op(w)
            with c._lock:
                assert len(c._resend_q) == 1
        finally:
            c.shutdown()

    def test_resend_error_does_not_strand_queue(self):
        """A resend raising past OSError/TimeoutError (e.g. the op's
        pool deleted under it, making target calc raise) must not
        unwind the ONE shared timer thread mid-fan — the remaining
        ready waiters must still be sent."""
        from types import SimpleNamespace
        c = self._client()
        try:
            c.ctx.conf.set("client_resend_backoff_ms", 10.0)
            sent: list[int] = []

            def send(w):
                if w.msg.tid == 1:
                    raise KeyError("pool gone")
                sent.append(w.msg.tid)
            c._send_op = send
            from ceph_tpu.client.rados import _Waiter
            for tid in (1, 2):
                w = _Waiter(SimpleNamespace(tid=tid, qos_tenant=""),
                            0, True)
                w.resends = 1            # next resend defers
                c._waiters[tid] = w
                c._resend_op(w)
            deadline = time.monotonic() + 5
            while not sent and time.monotonic() < deadline:
                time.sleep(0.005)
            assert sent == [2]
        finally:
            c.shutdown()


# -- visibility ---------------------------------------------------------------

class TestVisibility:
    def test_mgr_report_carries_faults_tail(self):
        from ceph_tpu.mgr.daemon import MMgrReport
        from ceph_tpu.msg.encoding import Decoder, Encoder
        faults = {"encode": {"breaker_states": {"ec_encode": 1},
                             "fallback_batches": 3}}
        msg = MMgrReport(osd_id=4, faults=faults)
        enc = Encoder()
        msg.encode_payload(enc)
        out = MMgrReport.__new__(MMgrReport)
        out.decode_payload(Decoder(enc.tobytes()), MMgrReport.HEAD_VERSION)
        assert out.faults == faults

    def test_mgr_health_kernel_degraded(self):
        import time as _time
        from ceph_tpu.mgr.daemon import MgrDaemon, MMgrReport
        mgr = MgrDaemon("mgr-health-test", ms_type="loopback")
        degraded = MMgrReport(osd_id=1, faults={
            "encode": {"breaker_states": {"ec_encode": 1}},
            "decode": {"breaker_states": {}}})
        with mgr._lock:
            mgr.reports[1] = (_time.time(), degraded)
        h = mgr.health()
        checks = {c["check"]: c for c in h["checks"]}
        assert "KERNEL_DEGRADED" in checks, h
        assert checks["KERNEL_DEGRADED"]["severity"] == "warn"
        assert checks["KERNEL_DEGRADED"]["daemons"] == {
            "1": ["encode/ec_encode"]}
        assert h["status"] == "HEALTH_WARN"
        # breaker re-closes -> the warning clears
        healed = MMgrReport(osd_id=1, faults={
            "encode": {"breaker_states": {"ec_encode": 0}}})
        with mgr._lock:
            mgr.reports[1] = (_time.time(), healed)
        h = mgr.health()
        assert all(c["check"] != "KERNEL_DEGRADED"
                   for c in h["checks"]), h
        # a daemon that died mid-outage (stale report, never pruned)
        # must read as STALE, not pin KERNEL_DEGRADED forever
        with mgr._lock:
            mgr.reports[1] = (_time.time() - 3600.0, degraded)
        h = mgr.health()
        checks = {c["check"] for c in h["checks"]}
        assert "KERNEL_DEGRADED" not in checks, h
        assert "MGR_STALE_REPORTS" in checks, h

    def test_prometheus_fault_families(self):
        from test_kernel_telemetry import _scrape, parse_exposition
        stats = telemetry.dispatch_stats()
        stats.record_retry(True)
        stats.record_fallback(64)
        stats.record_breaker("ec_encode", telemetry.BREAKER_OPEN)
        stats.record_probe(False)
        try:
            fams = parse_exposition(_scrape())
            assert fams["ceph_kernel_fallback_batches_total"][
                "type"] == "counter"
            assert fams["ceph_kernel_fallback_stripes_total"][
                "type"] == "counter"
            assert fams["ceph_kernel_breaker_state"]["type"] == "gauge"
            assert fams["ceph_kernel_breaker_transitions_total"][
                "type"] == "counter"
            probes = fams["ceph_kernel_fallback_probes_total"]
            assert {s[1].get("outcome") for s in probes["samples"]} \
                == {"success", "failure"}
            state = [s for s in fams["ceph_kernel_breaker_state"]
                     ["samples"]
                     if s[1] == {"engine": "encode",
                                 "channel": "ec_encode"}]
            assert state and state[0][2] == 1.0
            batches = [s for s in fams[
                "ceph_kernel_fallback_batches_total"]["samples"]
                if s[1] == {"engine": "encode"}]
            assert batches[0][2] >= 1.0
            # both engines emit the families, decode included
            assert any(s[1].get("engine") == "decode" for s in fams[
                "ceph_kernel_fallback_batches_total"]["samples"])
        finally:
            stats.clear()

    def test_fault_digest_shape(self):
        d = telemetry.fault_digest()
        assert set(d) == {"encode", "decode"}
        for eng in d.values():
            assert {"retries", "fallback_batches", "breaker_opens",
                    "breaker_closes", "probe_successes",
                    "thread_deaths",
                    "breaker_states"} <= set(eng)

    def test_prometheus_daemon_breaker_family(self):
        """The mgr exports each daemon's shipped breaker map as
        ceph_kernel_daemon_breaker_state{ceph_daemon,engine,channel}:
        the process-local sink family cannot attribute degradation
        across daemons — this one names the right daemon."""
        import sys
        sys.path.insert(0, "tests")
        from test_kernel_telemetry import parse_exposition
        from ceph_tpu.mgr.modules.prometheus import Module

        class _Mgr:
            class _Map:
                max_osd = 1
                epoch = 1
                osd_weight = [0x10000]

                def is_up(self, o):
                    return True

                def exists(self, o):
                    return True

            osdmap = _Map()

            def get(self, name):
                return {
                    "health": {"status": "HEALTH_OK"},
                    "pg_summary": {},
                    "df": {"total_objects": 0, "total_bytes_used": 0},
                    "counters": {},
                    "perf_reports": {},
                    "qos_feed": {},
                    "faults_feed": {
                        3: {"encode": {"breaker_states":
                                       {"ec_encode": 1}},
                            "decode": {"breaker_states": {}}},
                        5: {"encode": {"breaker_states":
                                       {"ec_encode": 0}}}},
                }[name]

            def get_store(self, key, default=None):
                return default

        mod = Module.__new__(Module)
        mod.mgr = _Mgr()
        fams = parse_exposition(mod.scrape_text())
        fam = fams["ceph_kernel_daemon_breaker_state"]
        assert fam["type"] == "gauge"
        states = {(s[1]["ceph_daemon"], s[1]["engine"],
                   s[1]["channel"]): s[2] for s in fam["samples"]}
        # per-daemon attribution: osd.3 open, osd.5 closed — no
        # last-writer-wins masking across daemons
        assert states[("osd.3", "encode", "ec_encode")] == 1.0
        assert states[("osd.5", "encode", "ec_encode")] == 0.0

    def test_ctx_fault_digest_reads_own_engine_breakers(self):
        """The shipped MMgrReport faults tail attributes degradation
        to ONE daemon, but the process-global sink's breaker_states is
        last-writer-wins across every in-process daemon: a context's
        digest must read breaker ground truth from its OWN engines —
        and a daemon that never built an engine must not inherit
        another daemon's open breaker."""
        from ceph_tpu.common.context import CephTpuContext
        sink = telemetry.dispatch_stats()
        sink.record_breaker("ec_encode", telemetry.BREAKER_OPEN)
        try:
            ctx = CephTpuContext("fault-digest-test")
            # the raw telemetry digest sees the (polluted) global sink
            assert telemetry.fault_digest()["encode"][
                "breaker_states"] == {"ec_encode": 1}
            # no engine built: no breakers, nothing inherited
            d = ctx.fault_digest()
            assert d["encode"]["breaker_states"] == {}
            assert d["decode"]["breaker_states"] == {}
            # engine built but healthy: still its own (empty) map
            ctx.dispatch_engine()
            assert ctx.fault_digest()["encode"]["breaker_states"] == {}
            # counters still flow from the shared sink
            assert ctx.fault_digest()["encode"]["breaker_opens"] >= 1
            # the admin payload rides the same per-context digest
            assert ctx.admin.execute("dump_fault_stats")["encode"][
                "breaker_states"] == {}
            ctx.dispatch_engine().stop()
        finally:
            sink.clear()


# -- device-chaos thrasher (the PR 11 chaos gate, tier-1) ---------------------

def test_device_chaos_storm(tmp_path):
    """Failpoints fire at >=10%% on the encode/decode/ladder channels
    (plus hard outages, boundary faults and run-loop kills) while the
    thrasher kills OSDs under the mixed workload: ZERO acked-object
    corruption, and after the faults clear every breaker re-closes
    (reconvergence to the device path).  Deterministic seed, ~30s —
    fault injection runs on every PR."""
    from ceph_tpu.tools.thrasher import run_soak
    res = run_soak(duration=11.0, seed=5, n_osds=5,
                   base_path=str(tmp_path), device_chaos=True)
    assert res["corruptions"] == [], res
    assert res["lost_rep"] == [], res
    assert res["lost_ec"] == [], res
    assert res["chaos_actions"] > 0, res
    assert res["rep_ops"] + res["ec_ops"] > 5, res
    assert res["breakers_reconverged"] is True, res["fault_digest"]
    digest = res["fault_digest"]
    # the storm actually bit: the engines saw faults and recovered
    touched = sum(d.get("retries", 0) + d.get("fallback_batches", 0)
                  for d in digest.values())
    assert touched > 0, digest
    # every breaker ended CLOSED
    for d in digest.values():
        assert all(st == telemetry.BREAKER_CLOSED
                   for st in d.get("breaker_states", {}).values()), \
            digest
