"""Batched hierarchical mapper vs the scalar oracle — bit-exactness across
topologies, rule shapes, weights, reweights, and exhaustion corners."""

import numpy as np
import pytest

from ceph_tpu.crush import build_flat_map, build_two_level_map, crush_do_rule
from ceph_tpu.crush.builder import add_simple_rule, make_bucket
from ceph_tpu.crush.mapper_jax import BatchMapper
from ceph_tpu.crush.types import (
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    CrushMap,
    Rule,
    RuleStep,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_SET_CHOOSELEAF_TRIES,
    RULE_TAKE,
    Tunables,
)

rng = np.random.default_rng(1234)


def assert_matches(m, rid, result_max, reweight, n=150):
    bm = BatchMapper(m)
    xs = rng.integers(0, 2**32, n, dtype=np.uint32)
    got = np.asarray(bm.do_rule(rid, xs, result_max,
                                np.asarray(reweight, dtype=np.int64)))
    for i, x in enumerate(xs):
        want = crush_do_rule(m, rid, int(x), result_max, list(reweight))
        mine = [int(v) for v in got[i]]
        # oracle firstn rows are dense; indep rows are positional — compare
        # against the dense compaction first, positional prefix second
        compact = [v for v in mine if v != CRUSH_ITEM_NONE]
        assert want in (compact, mine[:len(want)]), \
            f"x={x}: want={want} got={mine}"


def test_flat_firstn_and_indep():
    m, _root, rid = build_flat_map(20)
    assert_matches(m, rid, 3, [0x10000] * 20)
    assert_matches(m, 1, 6, [0x10000] * 20)


def test_two_level_chooseleaf_firstn():
    m, _root, rid = build_two_level_map(8, 4)
    assert_matches(m, rid, 3, [0x10000] * 32)


def test_two_level_chooseleaf_indep_with_tries():
    m, _root, _ = build_two_level_map(6, 3)
    rid = m.add_rule(Rule(ruleset=9, type=3, min_size=1, max_size=20, steps=[
        RuleStep(RULE_SET_CHOOSELEAF_TRIES, 5, 0),
        RuleStep(RULE_TAKE, -1, 0),
        RuleStep(RULE_CHOOSELEAF_INDEP, 0, 1),
        RuleStep(RULE_EMIT, 0, 0)]))
    assert_matches(m, rid, 5, [0x10000] * 18)


def test_multistep_choose_then_choose():
    m, _root, _ = build_two_level_map(8, 4)
    rid = m.add_rule(Rule(ruleset=8, type=1, min_size=1, max_size=10, steps=[
        RuleStep(RULE_TAKE, -1, 0),
        RuleStep(RULE_CHOOSE_FIRSTN, 3, 1),
        RuleStep(RULE_CHOOSE_FIRSTN, 1, 0),
        RuleStep(RULE_EMIT, 0, 0)]))
    assert_matches(m, rid, 3, [0x10000] * 32)


def test_weighted_hosts_with_reweight_outs():
    m = CrushMap()
    m.max_devices = 24
    hosts = []
    for h in range(6):
        osds = list(range(h * 4, h * 4 + 4))
        wts = [int(w) for w in rng.integers(0x8000, 0x30000, 4)]
        hid = -(h + 2)
        m.add_bucket(make_bucket(hid, CRUSH_BUCKET_STRAW2, 1, osds, wts))
        hosts.append(hid)
    m.add_bucket(make_bucket(-1, CRUSH_BUCKET_STRAW2, 2, hosts,
                             [m.bucket(h).weight for h in hosts]))
    rid = add_simple_rule(m, -1, 1, "firstn")
    rw = [0x10000] * 24
    rw[5] = 0
    rw[11] = 0x4000
    rw[17] = 0
    assert_matches(m, rid, 3, rw)


def test_exhaustion_returns_short_or_none():
    m, _root, rid = build_two_level_map(3, 2)
    assert_matches(m, rid, 6, [0x10000] * 6, n=80)


def test_negative_numrep_means_result_max_minus():
    # "choose firstn -1 type 0" places result_max-1 items (mapper.c:1009-1014)
    m, _root, _ = build_flat_map(12)
    rid = m.add_rule(Rule(ruleset=5, type=1, min_size=1, max_size=10, steps=[
        RuleStep(RULE_TAKE, -1, 0),
        RuleStep(RULE_CHOOSE_FIRSTN, -1, 0),
        RuleStep(RULE_EMIT, 0, 0)]))
    assert_matches(m, rid, 3, [0x10000] * 12, n=60)


def test_fastpath_detected_for_canonical_rules():
    from ceph_tpu.crush import fastpath
    m, _root, rid = build_two_level_map(8, 4)
    fr = fastpath.detect(m, rid)
    assert fr is not None and fr.kind == "chooseleaf"
    mf, _root2, ridf = build_flat_map(16)
    fr2 = fastpath.detect(mf, ridf)
    assert fr2 is not None and fr2.kind == "choose_flat"
    # indep rule on the flat map is not fast-pathed
    assert fastpath.detect(mf, 1) is None


def test_fastpath_overflow_falls_back_exactly():
    """Tiny block forces the lax.cond full-range recompute; results must
    still match the oracle bit for bit (heavy rejection: most OSDs out)."""
    import functools
    import jax
    from ceph_tpu.crush import fastpath
    m, _root, rid = build_two_level_map(4, 3)
    rw = [0] * 12
    rw[1] = 0x10000
    rw[7] = 0x6000
    rw[10] = 0x2000  # nearly everything out -> long retry ladders
    fr = fastpath.detect(m, rid)
    assert fr is not None
    fm = fastpath.FastMapper(fr)
    xs = rng.integers(0, 2**32, 100, dtype=np.uint32)
    got = np.asarray(jax.jit(functools.partial(fm.run, result_max=3, block=1))(
        xs, np.asarray(rw, dtype=np.int64)))
    for i, x in enumerate(xs):
        want = crush_do_rule(m, rid, int(x), 3, rw)
        compact = [int(v) for v in got[i] if v != CRUSH_ITEM_NONE]
        assert compact == want, f"x={x}: want={want} got={compact}"


def test_fastpath_vary_r_zero():
    m, _root, rid = build_two_level_map(5, 4)
    m.tunables.chooseleaf_vary_r = 0
    assert_matches(m, rid, 3, [0x10000] * 20, n=100)


def test_fastpath_uneven_host_sizes():
    m = CrushMap()
    m.max_devices = 16
    sizes = [1, 3, 5, 7]
    hosts, base = [], 0
    for h, sz in enumerate(sizes):
        osds = list(range(base, base + sz))
        base += sz
        hid = -(h + 2)
        m.add_bucket(make_bucket(hid, CRUSH_BUCKET_STRAW2, 1, osds,
                                 [0x10000 + 0x1000 * i for i in range(sz)]))
        hosts.append(hid)
    m.add_bucket(make_bucket(-1, CRUSH_BUCKET_STRAW2, 2, hosts,
                             [m.bucket(h).weight for h in hosts]))
    rid = add_simple_rule(m, -1, 1, "firstn")
    rw = [0x10000] * 16
    rw[0] = 0x8000
    assert_matches(m, rid, 3, rw, n=120)


def test_invalid_ruleno_returns_empty():
    m, _root, _rid = build_flat_map(8)
    bm = BatchMapper(m)
    out = np.asarray(bm.do_rule(99, np.arange(16, dtype=np.uint32), 3,
                                np.full(8, 0x10000, dtype=np.int64)))
    assert (out == CRUSH_ITEM_NONE).all()
    # matching the scalar oracle's empty result
    assert crush_do_rule(m, 99, 1, 3, [0x10000] * 8) == []


def test_non_straw2_map_rejected():
    m, _root, _rid = build_flat_map(8, alg=CRUSH_BUCKET_STRAW)
    with pytest.raises(ValueError, match="straw2"):
        BatchMapper(m)


def test_legacy_tunables_rejected():
    m, _root, _rid = build_flat_map(8)
    m.tunables = Tunables.legacy()
    with pytest.raises(ValueError, match="modern tunables"):
        BatchMapper(m)


def test_two_stage_pallas_schedule_interpret():
    """The two-stage _run_pallas schedule (R1 probe, argsort compaction,
    scatter-merge, cap overflow guard) vs the XLA ladder, in interpret
    mode — the TPU-only glue otherwise never runs in CI."""
    import jax.numpy as jnp

    from ceph_tpu.crush.fastpath import FastMapper, detect
    from ceph_tpu.ops.pallas_straw2 import PallasColumns

    crush_map, _root, rid = build_two_level_map(20, 4)
    # small tries -> small Rf fallback range: interpret-mode tracing of
    # the full-range cond branch is minutes-slow at the default 51
    crush_map.tunables.choose_total_tries = 7
    wrng = np.random.default_rng(11)
    for b in crush_map.buckets:
        if b is not None and b.type == 1:
            b.item_weights = [int(w) for w in
                              wrng.integers(0x8000, 0x20000, b.size)]
            b.weight = sum(b.item_weights)
    root = crush_map.bucket(-1)
    root.item_weights = [crush_map.bucket(h).weight for h in root.items]
    root.weight = sum(root.item_weights)
    fr = detect(crush_map, rid)
    n_osds = 80
    reweight = np.full(n_osds, 0x10000, dtype=np.int64)
    reweight[::7] = 0x4000   # heavy rejection -> stage-2 lanes exist
    reweight[::13] = 0
    rw = jnp.asarray(reweight)
    xs = jnp.asarray(np.random.default_rng(2).integers(
        0, 2 ** 32, (1024,), dtype=np.uint32))

    fm = FastMapper(fr)
    fm._pallas = PallasColumns(fr, interpret=True)
    fm.TWO_STAGE_MIN = 512     # force the two-stage path at test size
    fm.STAGE2_CAP = 512
    res_two = np.asarray(fm.run(xs, rw, 3))

    fm_xla = FastMapper(fr)
    fm_xla._pallas = None
    res_xla = np.asarray(fm_xla.run(xs, rw, 3))
    np.testing.assert_array_equal(res_two, res_xla)

    # cap overflow guard: capacity 8 certainly overflows -> whole-batch
    # recompute path, still exact
    fm.STAGE2_CAP = 8
    res_cap = np.asarray(fm.run(xs, rw, 3))
    np.testing.assert_array_equal(res_cap, res_xla)


# -- tree buckets (batched descent vs the scalar oracle) ---------------------

def test_tree_hosts_chooseleaf_firstn():
    from ceph_tpu.crush.types import CRUSH_BUCKET_TREE
    m, _root, rid = build_two_level_map(8, 4, host_alg=CRUSH_BUCKET_TREE)
    assert_matches(m, rid, 3, [0x10000] * 32)


def test_tree_root_flat_firstn_and_indep():
    from ceph_tpu.crush.types import CRUSH_BUCKET_TREE
    m, _root, rid = build_flat_map(17, alg=CRUSH_BUCKET_TREE)
    assert_matches(m, rid, 3, [0x10000] * 17)
    assert_matches(m, 1, 5, [0x10000] * 17)


def test_tree_nonuniform_weights_and_reweight():
    from ceph_tpu.crush.types import CRUSH_BUCKET_TREE
    wrng = np.random.default_rng(42)
    weights = [int(w) for w in wrng.integers(0x4000, 0x30000, 21)]
    m, _root, rid = build_flat_map(21, weights=weights,
                                   alg=CRUSH_BUCKET_TREE)
    reweight = [int(w) for w in wrng.integers(0, 0x10001, 21)]
    reweight[2] = 0
    assert_matches(m, rid, 4, reweight)


def test_mixed_straw2_root_tree_hosts():
    # straw2 root over tree host buckets: both algs inside one descent
    from ceph_tpu.crush.types import CRUSH_BUCKET_TREE
    m, _root, rid = build_two_level_map(
        6, 5, host_alg=CRUSH_BUCKET_TREE, root_alg=CRUSH_BUCKET_STRAW2)
    assert_matches(m, rid, 3, [0x10000] * 30)
