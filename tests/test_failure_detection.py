"""Failure adjudication on the monitor: reporter quorum across failure
domains, alive-cancellation, adaptive (laggy-aware) grace, and the xinfo
laggy history — OSDMonitor::check_failure / process_failure semantics
(src/mon/OSDMonitor.cc:2537-2572) at MiniCluster scale."""

import time

import pytest

from ceph_tpu.messages import MOSDFailure
from ceph_tpu.osd.map_codec import decode_osdmap, encode_osdmap
from ceph_tpu.osd.osdmap import OSDMap, OSDXInfo
from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture
def cluster():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    yield c
    c.stop()


def _inject_failure(mon, reporter, failed_osd, failed_for=100.0,
                    alive=False):
    mon._work_q.put(("failure", MOSDFailure(
        reporter=reporter, failed_osd=failed_osd, failed_for=failed_for,
        epoch=mon.osdmap.epoch, alive=alive), None))


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_single_reporter_does_not_mark_down(cluster):
    mon = cluster.mon
    _inject_failure(mon, reporter=0, failed_osd=2)
    time.sleep(0.3)
    assert mon.osdmap.is_up(2)
    assert 2 in mon._failure_reports


def test_reporter_quorum_marks_down(cluster):
    mon = cluster.mon
    _inject_failure(mon, reporter=0, failed_osd=2)
    _inject_failure(mon, reporter=1, failed_osd=2)
    assert _wait(lambda: not mon.osdmap.is_up(2)), \
        "two distinct reporters should mark the osd down"
    # down_stamp recorded for the laggy history
    assert mon.osdmap.get_xinfo(2).down_stamp > 0


def test_alive_report_cancels(cluster):
    """A reporter that hears from the peer again retracts its report
    (MOSDFailure FLAG_ALIVE); the half-filed failure never fires."""
    mon = cluster.mon
    _inject_failure(mon, reporter=0, failed_osd=2)
    assert _wait(lambda: 2 in mon._failure_reports)
    _inject_failure(mon, reporter=0, failed_osd=2, alive=True)
    assert _wait(lambda: 2 not in mon._failure_reports)
    # the second reporter alone is below quorum
    _inject_failure(mon, reporter=1, failed_osd=2)
    time.sleep(0.3)
    assert mon.osdmap.is_up(2)


def test_reporters_must_span_failure_domains(cluster):
    """Two osds under the same host bucket are one witness
    (mon_osd_reporter_subtree_level)."""
    mon = cluster.mon
    # construct a hierarchical map state: host0={0,1}, host1={2}
    from ceph_tpu.crush.builder import make_bucket
    from ceph_tpu.crush.types import CRUSH_BUCKET_STRAW2
    with mon._lock:
        m = mon.osdmap
        m.crush.buckets = []
        h0 = make_bucket(-2, CRUSH_BUCKET_STRAW2, 1, [0, 1],
                         [0x10000, 0x10000])
        h1 = make_bucket(-3, CRUSH_BUCKET_STRAW2, 1, [2], [0x10000])
        root = make_bucket(-1, CRUSH_BUCKET_STRAW2, 2, [-2, -3],
                           [h0.weight, h1.weight])
        for b in (h0, h1, root):
            m.crush.add_bucket(b)
    assert mon._reporter_subtree(0) == -2
    assert mon._reporter_subtree(1) == -2
    assert mon._reporter_subtree(2) == -3
    # reporters 0 and 1 share a host: not a quorum of failure domains
    _inject_failure(mon, reporter=0, failed_osd=2)
    _inject_failure(mon, reporter=1, failed_osd=2)
    time.sleep(0.4)
    assert mon.osdmap.is_up(2)


def test_adaptive_grace_extends_with_laggy_history(cluster):
    mon = cluster.mon
    now = time.time()
    base = float(mon.ctx.conf.get("osd_heartbeat_grace"))
    xi = mon.osdmap.get_xinfo(2)
    assert mon._failure_grace(2, now) == base
    xi.laggy_probability = 0.5
    xi.laggy_interval = 20.0
    xi.down_stamp = now
    g = mon._failure_grace(2, now)
    assert g == pytest.approx(base + 10.0, rel=1e-3)
    # the history decays: an episode half a halflife ago counts ~71%
    halflife = float(mon.ctx.conf.get("mon_osd_laggy_halflife"))
    xi.down_stamp = now - halflife
    assert mon._failure_grace(2, now) == pytest.approx(base + 5.0, rel=1e-3)
    # a report younger than the extended grace does not fire
    xi.down_stamp = now
    _inject_failure(mon, reporter=0, failed_osd=2, failed_for=base + 1)
    _inject_failure(mon, reporter=1, failed_osd=2, failed_for=base + 1)
    time.sleep(0.4)
    assert mon.osdmap.is_up(2)
    # but one older than it does
    _inject_failure(mon, reporter=0, failed_osd=2, failed_for=base + 11)
    assert _wait(lambda: not mon.osdmap.is_up(2))


def test_laggy_history_accrues_on_reboot(cluster):
    """An osd marked down that boots right back is laggy, not dead:
    its xinfo decaying averages move (OSDMonitor::prepare_boot)."""
    mon = cluster.mon
    client = cluster.client()
    rc, out = client.mon_command({"prefix": "osd down", "id": 2})
    assert rc == 0, out
    assert _wait(lambda: not mon.osdmap.is_up(2))
    # the daemon is still alive; its tick re-sends MOSDBoot
    assert _wait(lambda: mon.osdmap.is_up(2), timeout=10.0), \
        "marked-down-but-alive osd never re-booted"
    xi = mon.osdmap.get_xinfo(2)
    assert xi.laggy_probability > 0
    assert xi.laggy_interval >= 0


def test_dead_reporters_do_not_count(cluster):
    """A report whose reporter has since died is not a live witness:
    one real reporter must not complete the quorum with a ghost."""
    mon = cluster.mon
    _inject_failure(mon, reporter=0, failed_osd=2)
    assert _wait(lambda: 2 in mon._failure_reports)
    # reporter 0 dies and is marked down
    cluster.kill_osd(0)
    client = cluster.client()
    rc, out = client.mon_command({"prefix": "osd down", "id": 0})
    assert rc == 0, out
    assert _wait(lambda: not mon.osdmap.is_up(0))
    # a single live reporter arrives: must NOT be quorum
    _inject_failure(mon, reporter=1, failed_osd=2)
    time.sleep(0.4)
    assert mon.osdmap.is_up(2)


def test_rebooted_peer_gets_fresh_grace_clock():
    """After a peer is marked down, other osds drop its heartbeat state;
    when it reboots they must not instantly re-report it with the stale
    pre-crash timestamp (the down-flap loop)."""
    c = MiniCluster(n_osds=3, ms_type="loopback", heartbeats=True).start()
    try:
        c.wait_for_osd_count(3)
        for osd in c.osds.values():
            osd.ctx.conf.set("osd_heartbeat_interval", 0.1)
            osd.ctx.conf.set("osd_heartbeat_grace", 0.6)
        observer = c.osds[0]
        # first tick was scheduled with the default 1s interval
        assert _wait(lambda: 2 in observer._hb_last, timeout=5.0)
        c.kill_osd(2)
        client = c.client()
        rc, out = client.mon_command({"prefix": "osd down", "id": 2})
        assert rc == 0, out
        assert _wait(lambda: not c.mon.osdmap.is_up(2))
        epoch = c.mon.osdmap.epoch
        c.wait_for_epoch(epoch)
        # the observer's next tick drops the dead peer's clock
        assert _wait(lambda: 2 not in observer._hb_last, timeout=5.0), \
            "observer kept the dead peer's stale heartbeat timestamp"
        assert 2 not in observer._failure_reported
        # peer reboots much later: clock restarts from first contact
        c.run_osd(2)
        c.wait_for_osd_count(3)
        time.sleep(1.0)  # several grace periods of healthy pinging
        assert c.mon.osdmap.is_up(2), \
            "rebooted healthy osd was re-reported from stale state"
    finally:
        c.stop()


def test_dead_daemon_answers_nothing():
    """A shut-down osd must not keep answering pings over a connection
    accepted mid-shutdown — a zombie replier keeps peers' liveness
    clocks fresh for a dead osd and failure detection never fires
    (OSD::ms_dispatch is_stopping semantics)."""
    c = MiniCluster(n_osds=3, ms_type="async", heartbeats=True).start()
    try:
        c.wait_for_osd_count(3)
        for osd in c.osds.values():
            osd.ctx.conf.set("osd_heartbeat_interval", 0.1)
            osd.ctx.conf.set("osd_heartbeat_grace", 0.5)
        c.mon.ctx.conf.set("osd_heartbeat_grace", 0.5)
        time.sleep(1.2)
        victim = c.osds[2]
        c.kill_osd(2)
        # the victim object must be inert: no live accepted sessions
        # may dispatch into it
        from ceph_tpu.messages.osd_msgs import MOSDPing
        assert victim.ms_dispatch(MOSDPing(from_osd=0)) is True  # swallowed
        # peers' reports must now converge on a mark-down
        assert _wait(lambda: not c.mon.osdmap.is_up(2), timeout=10.0), \
            "dead osd never marked down (zombie replies?)"
    finally:
        c.stop()


def test_stale_map_osd_catches_up():
    """An osd that missed a map push converges via subscription renewal
    (MonClient renew) instead of monitoring peers against a stale map."""
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        osd = c.osds[1]
        for o in c.osds.values():
            o.ctx.conf.set("osd_map_renew_interval", 0.2)
        # simulate a missed push: regress osd1's map to epoch 0
        from ceph_tpu.osd.osdmap import OSDMap
        with osd._lock:
            osd.osdmap = OSDMap(epoch=0)
        assert _wait(lambda: osd.osdmap.epoch == c.mon.osdmap.epoch,
                     timeout=5.0), "renewal never re-synced the stale osd"
    finally:
        c.stop()


def test_xinfo_codec_roundtrip():
    m = OSDMap()
    m.set_max_osd(3)
    m.mark_up(0)
    m.osd_xinfo[1] = OSDXInfo(down_stamp=123.5, laggy_probability=0.3,
                              laggy_interval=42.0)
    m2 = decode_osdmap(encode_osdmap(m))
    assert m2.osd_xinfo[1].down_stamp == 123.5
    assert m2.osd_xinfo[1].laggy_probability == 0.3
    assert m2.osd_xinfo[1].laggy_interval == 42.0
    assert m2.osd_xinfo[0].down_stamp == 0.0


def test_osd_sends_alive_cancellation():
    """End-to-end: a transiently silent peer is reported, answers again,
    and the reporter retracts — the mon's report table drains and the
    peer is never marked down."""
    c = MiniCluster(n_osds=3, ms_type="loopback", heartbeats=True).start()
    try:
        c.wait_for_osd_count(3)
        for osd in c.osds.values():
            osd.ctx.conf.set("osd_heartbeat_interval", 0.1)
            osd.ctx.conf.set("osd_heartbeat_grace", 0.6)
        # require 3 reporters so the two live peers can't complete quorum
        c.mon.ctx.conf.set("mon_osd_min_down_reporters", 3)
        time.sleep(0.5)
        victim = c.osds[2]
        # simulate a transient partition: the victim stops sending and
        # answering pings (but stays booted)
        victim._stop = True
        if victim._hb_timer:
            victim._hb_timer.cancel()
        old = victim._handle_ping
        victim._handle_ping = lambda msg: None
        assert _wait(lambda: 2 in c.mon._failure_reports, timeout=5.0), \
            "peers never reported the silent osd"
        # partition heals
        victim._handle_ping = old
        victim._stop = False
        victim._schedule_heartbeat()
        victim._schedule_tick()
        assert _wait(lambda: 2 not in c.mon._failure_reports, timeout=5.0), \
            "alive cancellation never drained the report table"
        assert c.mon.osdmap.is_up(2)
    finally:
        c.stop()
