"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding (pjit/shard_map over a
jax.sharding.Mesh) is exercised without TPU hardware — the same mechanism the driver's
dryrun uses.  This must be configured before jax initializes its backends.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# config.update, not the env var: the environment exports JAX_PLATFORMS=axon (the
# real TPU tunnel) and the plugin outranks an env override, but tests need the
# virtual 8-device CPU mesh.  When a TPU platform IS advertised by the
# environment, expose it ALONGSIDE cpu ("cpu,axon": cpu stays the default
# backend) so the compiled-TPU cross-validation gate runs by default on TPU
# hosts instead of being silently skipped — that suite is the only thing that
# catches Mosaic compiled-path miscompiles (round 3's is_out bug).
_plat = os.environ.get("CEPH_TPU_TEST_PLATFORM")
if _plat is None:
    _env = os.environ.get("JAX_PLATFORMS", "")
    _tpu = next((p for p in ("axon", "tpu") if p in _env.split(",")), None)
    _plat = f"cpu,{_tpu}" if _tpu else "cpu"
jax.config.update("jax_platforms", _plat)

import ceph_tpu  # noqa: E402,F401  (enables x64 before tests create arrays)

import pytest  # noqa: E402

from ceph_tpu.common import lockdep  # noqa: E402

_LOCKDEP_ENV = os.environ.get("CEPH_TPU_LOCKDEP", "") not in ("", "0")
#: modules that ALWAYS run under runtime lockdep, even in a plain
#: tier-1 run: the async hot paths this repo's lock discipline exists
#: for.  Their engines/trackers/messengers are constructed per-test,
#: so make_lock hands them DebugRLocks while the fixture is active.
_LOCKDEP_MODULES = {"test_dispatch", "test_decode_dispatch",
                    "test_mapping_service"}


@pytest.fixture(autouse=True)
def _lockdep_guard(request):
    """Under CEPH_TPU_LOCKDEP=1 (every test) or for the dispatch/
    decode/mapping modules (always): enable lockdep, reset the order
    graph between tests, and assert no violations at teardown — daemon
    threads swallow the LockOrderError raise, so the violations list
    is the reliable signal (lockdep.py's CI contract)."""
    mod = getattr(request, "module", None)
    modname = mod.__name__.rsplit(".", 1)[-1] if mod else ""
    if not (_LOCKDEP_ENV or modname in _LOCKDEP_MODULES):
        yield
        return
    lockdep.reset()
    was = lockdep.enabled()
    lockdep.enable(True)
    try:
        yield
        assert not lockdep.violations, (
            "lock-order violations recorded during this test (the "
            "raise may have died on a daemon thread):\n\n"
            + "\n\n".join(lockdep.violations))
    finally:
        lockdep.enable(was or _LOCKDEP_ENV)
        lockdep.reset()
