"""Daemon-side mon command RPC, shared by OSD/mgr (the MonClient's
command path, reduced): fan the command to every mon (only the leader
executes; peons forward), wait for the first ack.

One instance per daemon; the owner must route MMonCommandAck messages
from its ms_dispatch into handle_ack()."""

from __future__ import annotations

import queue
import threading

from ceph_tpu.msg.messenger import EntityName


def mon_targets(osdmap, static_addrs: list[str]) -> list[tuple[int, str]]:
    """(rank, addr) list every mon consumer should iterate: the
    COMMITTED monmap first (daemons follow `mon add/rm` instead of
    dying with their boot-time mon list), then any statically-
    configured address the map does not cover — a committed entry can
    go stale when a mon restarts on a fresh ephemeral port, and the
    static fallback is what lets the consumer still reach it."""
    mons = (getattr(osdmap, "mon_db", None) or {}).get("mons") or {}
    out = sorted(((int(r), a) for r, a in mons.items()),
                 key=lambda kv: kv[0])
    known = {a for _r, a in out}
    out.extend((r, a) for r, a in enumerate(static_addrs)
               if a not in known)
    return out


class MonCommander:
    def __init__(self, msgr, mon_addrs: list[str], osdmap_fn=None):
        self.msgr = msgr
        self.mon_addrs = mon_addrs
        self._osdmap_fn = osdmap_fn
        # analysis: allow[bare-lock] -- mon command-table leaf lock
        self._lock = threading.Lock()
        self._tid = 0
        self._waiters: dict[int, queue.Queue] = {}

    def _targets(self) -> list[tuple[int, str]]:
        return mon_targets(self._osdmap_fn() if self._osdmap_fn
                           else None, self.mon_addrs)

    def cmd(self, cmd: dict, timeout: float = 8.0) -> tuple[int, str]:
        from ceph_tpu.messages import MMonCommand
        with self._lock:
            self._tid += 1
            tid = self._tid
            q: queue.Queue = queue.Queue()
            self._waiters[tid] = q
        try:
            for rank, addr in self._targets():
                con = self.msgr.connect_to(addr.strip(),
                                           EntityName("mon", rank))
                con.send_message(MMonCommand(tid=tid, cmd=dict(cmd)))
            try:
                return q.get(timeout=timeout)
            except queue.Empty:
                return -110, "mon command timed out"
        finally:
            with self._lock:
                self._waiters.pop(tid, None)

    def handle_ack(self, msg) -> bool:
        """Route an MMonCommandAck; True if it was one of ours."""
        with self._lock:
            q = self._waiters.get(msg.tid)
        if q is not None:
            q.put((msg.result, msg.output))
            return True
        return False

    def fetch_ticket(self, service: str):
        from ceph_tpu.auth.cephx import ticket_from_json
        rc, out = self.cmd({"prefix": "auth get-ticket",
                            "service": service})
        return ticket_from_json(out) if rc == 0 else None

    def fetch_rotating(self, service: str) -> dict[int, str] | None:
        import json
        rc, out = self.cmd({"prefix": "auth rotating",
                            "service": service})
        if rc != 0:
            return None
        return {int(g): k for g, k in json.loads(out).items()}
