"""Cross-op device-call coalescing engine (ceph_tpu.ops.dispatch).

The load-bearing claims, each pinned here:

  * bit-exactness — N threads submitting MIXED-size encodes through one
    engine get exactly what ec_encode_ref computes for their own data,
    no matter how the engine stacked, padded, and sliced the batches;
  * shape bucketing bounds the jit compile cache by the bucket table
    (exact-count via the gf_kernel compile-cache delta, the same
    pattern test_kernel_telemetry uses), so variable-size client
    writes cannot retrace per distinct size;
  * flush-on-idle — a lone op never waits out the coalesce delay
    (reason "idle", coalesce factor 1), so single-op latency cannot
    regress when the engine is on;
  * cross-op coalescing — requests queued while the engine is busy
    share ONE device call, delivered in submission order.

Chunk widths here are deliberately absent from every other suite: the
jit cache is process-global, and the bounded-cache test counts entries.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from ceph_tpu.ops import telemetry
from ceph_tpu.ops.dispatch import (DeviceDispatchEngine, bucket_stripes,
                                   submit_flat_firstn)

# unique geometry (see module docstring)
K1, M1, B1 = 4, 2, 288     # bit-exactness suites
K2, M2, B2 = 6, 3, 416     # bounded-cache suite


def _coding(k, m, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(1, 256, (m, k), dtype=np.uint8)


def _stripes(n, k, b, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, k, b), dtype=np.uint8)


def _encoder(coding):
    from ceph_tpu.ops.gf_kernel import make_encoder
    return make_encoder(coding)


# -- bucketing ---------------------------------------------------------------

def test_bucket_stripes_power_of_two():
    assert [bucket_stripes(n) for n in (1, 2, 3, 4, 5, 8, 9, 1000)] \
        == [1, 2, 4, 4, 8, 8, 16, 1024]


# -- flush-on-idle (the single-op latency guarantee) -------------------------

def test_idle_flush_no_wait_single_op():
    """A lone submit on an idle engine flushes immediately (reason
    "idle"), alone in its device call, well under the coalesce delay
    it would otherwise have waited out."""
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(max_delay_us=200_000.0, stats=stats)
    try:
        t0 = time.monotonic()
        out = eng.submit(("idle", 1), lambda a: a + 1,
                         np.zeros((3, 2), np.uint8)).result(timeout=10)
        dt = time.monotonic() - t0
        assert (out == 1).all() and out.shape == (3, 2)
        assert dt < 0.1, f"idle op waited {dt:.3f}s (delay is 200ms)"
        assert stats.flush_reasons["idle"] == 1
        assert stats.batches == 1
        assert stats.coalesce.sum == 1     # one request in the call
    finally:
        eng.stop()


# -- cross-op coalescing -----------------------------------------------------

def test_requests_queued_while_busy_share_one_call():
    """While the engine chews a slow batch, concurrent submits with the
    same key accumulate and dispatch as ONE call, completions delivered
    in submission order."""
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(max_delay_us=50_000.0, stats=stats)
    entered = threading.Event()
    release = threading.Event()

    def slow(a):
        entered.set()
        release.wait(5.0)
        return a

    try:
        blocker = eng.submit(("slow", 0), slow, np.zeros((1,), np.uint8))
        # wait until the dispatch thread is inside the blocker's fn
        # (the engine is demonstrably busy) before piling on
        assert entered.wait(5.0)
        order: list[int] = []
        futs = [eng.submit(("fast", 1), lambda a: a * 2,
                           np.full((i + 1, 4), i, np.int64))
                for i in range(4)]
        for i, f in enumerate(futs):
            f.add_done_callback(lambda _f, i=i: order.append(i))
        release.set()
        for i, f in enumerate(futs):
            out = f.result(timeout=10)
            assert out.shape == (i + 1, 4)
            assert (out == 2 * i).all()
        blocker.result(timeout=10)
        assert stats.batches == 2, "4 queued requests must share 1 call"
        assert stats.coalesce.sum == 5          # 1 + 4 requests
        assert order == [0, 1, 2, 3]            # submission order
        assert stats.completed == 5
    finally:
        eng.stop()


def test_max_stripes_caps_a_batch():
    """A batch closes at max_stripes even with more work queued."""
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(max_stripes=8, max_delay_us=50_000.0,
                               stats=stats)
    entered = threading.Event()
    release = threading.Event()

    def slow(a):
        entered.set()
        release.wait(5.0)
        return a

    try:
        eng.submit(("slow", 0), slow, np.zeros((1,), np.uint8))
        assert entered.wait(5.0)
        futs = [eng.submit(("k", 0), lambda a: a,
                           np.zeros((4, 2), np.uint8))
                for _ in range(4)]     # 16 stripes > max 8
        release.set()
        for f in futs:
            f.result(timeout=10)
        assert stats.batches >= 3      # blocker + at least 2 capped
        assert stats.flush_reasons["full"] >= 1
    finally:
        eng.stop()


# -- bit-exactness under concurrency -----------------------------------------

def test_threaded_mixed_size_encodes_bit_exact():
    """8 writers x 6 mixed-size encodes through one engine: every
    delivered parity equals ec_encode_ref of that writer's own data."""
    from ceph_tpu.ops.gf_kernel import ec_encode_ref
    coding = _coding(K1, M1)
    encode = _encoder(coding)
    eng = DeviceDispatchEngine(max_delay_us=500.0,
                               stats=telemetry.DispatchStats())
    key = ("ec", K1, M1, B1)
    errors: list[str] = []

    def writer(wid):
        rng = np.random.default_rng(100 + wid)
        for i in range(6):
            data = _stripes(int(rng.integers(1, 38)), K1, B1,
                            seed=wid * 100 + i)
            got = eng.submit(key, encode, data).result(timeout=120)
            want = ec_encode_ref(coding, data)
            if not (np.asarray(got) == want).all():
                errors.append(f"writer {wid} op {i}: mismatch")

    try:
        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
    finally:
        eng.stop()


def test_padded_bucket_output_equals_unpadded():
    """Non-power-of-two sizes pad with zero stripes on dispatch; the
    delivered slice must equal the unpadded reference encode (zeros
    encode to zeros under a linear code, and the pad is sliced off)."""
    from ceph_tpu.ops.gf_kernel import ec_encode_ref
    coding = _coding(K1, M1, seed=1)
    encode = _encoder(coding)
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(stats=stats)
    try:
        for n in (3, 5, 7, 11):
            data = _stripes(n, K1, B1, seed=n)
            got = eng.submit(("pad", K1, M1, B1), encode,
                             data).result(timeout=120)
            assert got.shape == (n, M1, B1)
            assert (np.asarray(got)
                    == ec_encode_ref(coding, data)).all()
        # 3->4, 5->8, 7->8, 11->16: padding genuinely happened
        assert stats.padded_stripes == (1 + 3 + 1 + 5)
    finally:
        eng.stop()


# -- compile-cache bound (the retrace story) ---------------------------------

def test_jit_cache_bounded_by_bucket_table():
    """40 randomized write sizes in [1, 64] submitted through the
    engine compile AT MOST one executable per power-of-two bucket —
    the exact-count compile-cache delta the telemetry suite pioneered.
    Unbucketed, the same traffic would cost up to 40 retraces."""
    from ceph_tpu.ops.gf_kernel import _jit_entries
    coding = _coding(K2, M2, seed=2)
    encode = _encoder(coding)
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats())
    rng = np.random.default_rng(3)
    sizes = [int(s) for s in rng.integers(1, 65, 40)]
    try:
        # warm nothing: measure the whole sweep's cache growth
        before = _jit_entries()
        for i, n in enumerate(sizes):
            out = eng.submit(("bound", K2, M2, B2), encode,
                             _stripes(n, K2, B2, seed=i)
                             ).result(timeout=120)
            assert out.shape == (n, M2, B2)
        grown = _jit_entries() - before
        buckets = {bucket_stripes(n) for n in sizes}
        assert grown <= len(buckets), \
            f"{grown} compiles for {len(buckets)} buckets {sorted(buckets)}"
    finally:
        eng.stop()


# -- EC codec + CRUSH submit APIs --------------------------------------------

def test_ec_submit_chunks_matches_encode_chunks():
    """ErasureCode.submit_chunks through the engine == encode_chunks
    direct, for both the device runtime and the numpy oracle."""
    from ceph_tpu.ec import registry_instance
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats())
    try:
        for runtime in ("tpu", "cpu"):
            codec = registry_instance().factory(
                "jerasure", {"technique": "reed_sol_van", "k": "4",
                             "m": "2", "runtime": runtime})
            data = _stripes(9, 4, 512, seed=4)
            got = codec.submit_chunks(eng, data).result(timeout=120)
            assert (np.asarray(got)
                    == codec.encode_chunks(data)).all()
    finally:
        eng.stop()


def test_submit_flat_firstn_matches_direct():
    """Coalesced bulk PG remap == the direct kernel call, padded lanes
    sliced off."""
    from ceph_tpu.ops import crush_kernel as ck
    rng = np.random.default_rng(5)
    n_osds = 24
    ids = np.arange(n_osds, dtype=np.int32)
    weights = rng.integers(0x8000, 0x20000, n_osds).astype(np.int64)
    reweight = np.full(n_osds, 0x10000, dtype=np.int64)
    reweight[2] = 0
    xs = rng.integers(0, 2**32, 37, dtype=np.uint32)   # pads to 64
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats())
    try:
        got = submit_flat_firstn(eng, xs, ids, weights, reweight,
                                 numrep=3).result(timeout=120)
        want = np.asarray(ck.flat_firstn(xs, ids, weights, reweight,
                                         numrep=3))
        assert got.shape == want.shape == (37, 3)
        assert (np.asarray(got) == want).all()
    finally:
        eng.stop()


def test_crush_test_tool_flat_rides_engine():
    """crush_test's tpu backend on a flat map dispatches through the
    default context's engine (submit counters move) and stays bit-exact
    vs. the scalar oracle backend."""
    import io
    from ceph_tpu.common.context import default_context
    from ceph_tpu.crush import build_flat_map
    from ceph_tpu.tools.crush_test import run_test
    m, _root, rule = build_flat_map(20, [0x10000] * 15 + [0x20000] * 5)
    stats = default_context().dispatch_engine().stats
    s0 = stats.summary()["submits"]
    tpu = run_test(m, [rule], 0, 300, 3, backend="tpu", out=io.StringIO())
    assert stats.summary()["submits"] > s0, \
        "flat rule did not ride the dispatch engine"
    ref = run_test(m, [rule], 0, 300, 3, backend="scalar",
                   out=io.StringIO())
    assert tpu[rule]["sizes"] == ref[rule]["sizes"]
    assert tpu[rule]["util"] == ref[rule]["util"]


# -- lifecycle ---------------------------------------------------------------

def test_stop_drains_then_runs_inline():
    """stop() completes queued work; submits after stop run inline on
    the caller (no thread, no hang)."""
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats())
    f1 = eng.submit(("x", 0), lambda a: a + 1, np.zeros((2,), np.int64))
    eng.stop()
    assert (f1.result(timeout=10) == 1).all()
    f2 = eng.submit(("x", 0), lambda a: a + 2, np.zeros((2,), np.int64))
    assert f2.done() and (f2.result() == 2).all()


def test_submit_error_fans_to_the_right_futures():
    """A failing kernel resolves every future in ITS batch with the
    exception; the engine keeps serving afterwards."""
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats())

    def boom(a):
        raise RuntimeError("kernel died")

    try:
        f = eng.submit(("err", 0), boom, np.zeros((1,), np.uint8))
        with pytest.raises(RuntimeError, match="kernel died"):
            f.result(timeout=10)
        ok = eng.submit(("ok", 0), lambda a: a, np.ones((1,), np.uint8))
        assert (ok.result(timeout=10) == 1).all()
    finally:
        eng.stop()


def test_batch_build_error_fans_to_futures_engine_survives():
    """An exception in BATCH CONSTRUCTION (pad/concatenate — e.g. two
    same-key requests with mismatched trailing shapes, or MemoryError
    under pressure) resolves the batch's futures with the exception
    instead of killing the dispatch thread: a dead thread would strand
    every outstanding future and wedge the engine for good."""
    eng = DeviceDispatchEngine(max_delay_us=50_000.0,
                               stats=telemetry.DispatchStats())

    def slow(a):
        time.sleep(0.3)
        return a

    try:
        busy = eng.submit(("busy", 0), slow, np.zeros((2, 4), np.uint8))
        time.sleep(0.05)   # engine busy: the next two coalesce
        f1 = eng.submit(("k", 0), lambda a: a, np.zeros((3, 4), np.uint8))
        f2 = eng.submit(("k", 0), lambda a: a, np.zeros((2, 5), np.uint8))
        for f in (f1, f2):
            with pytest.raises(ValueError):
                f.result(timeout=10)
        assert busy.result(timeout=10).shape == (2, 4)
        # the dispatch thread survived: the engine still serves
        ok = eng.submit(("ok", 0), lambda a: a + 1,
                        np.zeros((1, 4), np.uint8))
        assert (ok.result(timeout=10) == 1).all()
    finally:
        eng.stop()


def test_flush_waits_for_queue_drain():
    eng = DeviceDispatchEngine(stats=telemetry.DispatchStats())
    try:
        futs = [eng.submit(("f", 0), lambda a: a,
                           np.zeros((2,), np.uint8)) for _ in range(5)]
        assert eng.flush(timeout=10)
        for f in futs:
            assert f.result(timeout=1) is not None
    finally:
        eng.stop()
