"""osdmaptool --test-map-pgs analog (src/tools/osdmaptool.cc:32-42,184-196):
map every PG of every pool through the full placement pipeline and print the
distribution summary (avg/min/max PGs per OSD, mapping rate).

Runs through the context's shared PG mapping service — the same
epoch-keyed cache, incremental invalidation and dispatch-engine path
the OSDs/client/balancer use — so the tool exercises (and measures)
the production mapping path, not a private mapper."""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from ceph_tpu.common.context import default_context
from ceph_tpu.crush import build_two_level_map
from ceph_tpu.osd import OSDMap, PGPool


def test_map_pgs(m: OSDMap, out=sys.stdout, dump: bool = False) -> dict:
    t0 = time.perf_counter()
    svc = default_context().mapping_service()
    svc.warm(m)
    total = np.zeros(max(m.max_osd, 1), dtype=np.int64)
    n_pgs = 0
    for pool_id, pool in m.pools.items():
        counts = svc.pg_counts(m, pool_id)
        total[:len(counts)] += counts
        n_pgs += pool.pg_num
        if dump:
            for pg in range(pool.pg_num):
                up, upp, acting, actp = svc.lookup(m, pool_id, pg)
                print(f"{pool_id}.{pg}\t{up}\t{upp}", file=out)
    dt = time.perf_counter() - t0
    in_osds = total[total > 0]
    result = {
        "pg_total": n_pgs,
        "osd_count": int((total > 0).sum()),
        "avg": float(in_osds.mean()) if in_osds.size else 0.0,
        "min": int(in_osds.min()) if in_osds.size else 0,
        "max": int(in_osds.max()) if in_osds.size else 0,
        "elapsed_s": dt,
        "pgs_per_s": n_pgs / dt if dt else 0.0,
    }
    print(f"pool pg_num sum {n_pgs}", file=out)
    print(f"size distribution: avg {result['avg']:.2f} "
          f"min {result['min']} max {result['max']} "
          f"over {result['osd_count']} osds "
          f"({result['pgs_per_s']:.0f} pg mappings/s)", file=out)
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="osdmap_test")
    p.add_argument("--hosts", type=int, default=32)
    p.add_argument("--per-host", type=int, default=4)
    p.add_argument("--pg-num", type=int, default=4096)
    p.add_argument("--size", type=int, default=3)
    p.add_argument("--test-map-pgs", action="store_true", default=True)
    p.add_argument("--test-map-pgs-dump", action="store_true")
    args = p.parse_args(argv)

    crush, _root, rule = build_two_level_map(args.hosts, args.per_host)
    m = OSDMap(crush=crush)
    n = args.hosts * args.per_host
    m.set_max_osd(n)
    for o in range(n):
        m.mark_up(o)
    m.pools[1] = PGPool(pool_id=1, size=args.size, crush_rule=rule,
                        pg_num=args.pg_num)
    test_map_pgs(m, dump=args.test_map_pgs_dump)
    return 0


if __name__ == "__main__":
    sys.exit(main())
