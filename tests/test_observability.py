"""Observability + wire tier: mgr prometheus exporter (HTTP /metrics),
on-wire frame compression negotiation, psim placement simulator."""

import time
import urllib.request

from ceph_tpu.tools.vstart import MiniCluster


def test_prometheus_exporter_end_to_end():
    c = MiniCluster(n_osds=2, ms_type="loopback").start()
    try:
        c.run_mgr()
        # restart osds so they report to the mgr
        for oid in list(c.osds):
            c.kill_osd(oid)
            c.run_osd(oid)
        c.wait_for_osd_count(2)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=4, size=2)
        io = client.open_ioctx(pool)
        io.write_full("p", b"prom" * 50)
        deadline = time.time() + 10
        while time.time() < deadline and len(c.mgr.reports) < 2:
            time.sleep(0.1)
        port = c.mgr.serve_prometheus()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "ceph_health_status" in body
        assert "ceph_osd_up 2" in body
        assert "ceph_osdmap_epoch" in body
        assert 'ceph_osd_perf{ceph_daemon="osd.0"' in body
        # proper exposition: headers on every family (the old exporter
        # emitted ceph_pg_states / ceph_cluster_* headerless), typed
        # daemon perf from the MMgrReport v3 payload, and the kernel
        # histogram families
        assert "# TYPE ceph_pg_states gauge" in body
        assert "# TYPE ceph_cluster_total_objects gauge" in body
        assert "# TYPE ceph_daemon_perf_latency summary" in body
        assert 'set="msgr.osd.0"' in body
        assert "# TYPE ceph_kernel_ec_encode_latency_seconds histogram" \
            in body
        assert "ceph_kernel_crush_map_latency_seconds_bucket" in body
        from test_kernel_telemetry import parse_exposition
        parse_exposition(body)   # every line parses, headers precede
        # 404 for other paths
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   timeout=5)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        c.stop()


def test_wire_compression_negotiated_roundtrip():
    """Both peers offer zlib: large frames shrink on the wire and
    decode identically; an off peer forces plaintext (min wins)."""
    from ceph_tpu.msg.async_tcp import COMP_ZLIB, AsyncMessenger
    from ceph_tpu.msg.messenger import (
        ConnectionPolicy, Dispatcher, EntityName)
    from ceph_tpu.osd.daemon import MOSDPGPush

    class Sink(Dispatcher):
        def __init__(self):
            self.got = []

        def ms_dispatch(self, msg):
            self.got.append(msg)
            return True

    a = AsyncMessenger(EntityName("osd", 1))
    b = AsyncMessenger(EntityName("osd", 2))
    sink = Sink()
    for m in (a, b):
        m.set_policy("osd", ConnectionPolicy.stateful_peer())
        m.set_compression("zlib")
    b.add_dispatcher_tail(sink)
    try:
        b.bind("127.0.0.1:0")
        b.start()
        a.bind("127.0.0.1:0")
        a.start()
        con = a.connect_to(b.my_addr, EntityName("osd", 2))
        payload = b"A" * 100000  # compresses hard
        con.send_message(MOSDPGPush(pgid=(1, 0), oid="big", data=payload))
        deadline = time.time() + 10
        while time.time() < deadline and not sink.got:
            time.sleep(0.02)
        assert sink.got and sink.got[0].data == payload
        assert con.comp == COMP_ZLIB
        # wire frame actually shrank
        assert len(con._frame(sink.got[0])) < len(payload) // 10
    finally:
        a.shutdown()
        b.shutdown()


def test_wire_compression_min_wins():
    from ceph_tpu.msg.async_tcp import COMP_NONE, AsyncMessenger
    from ceph_tpu.msg.messenger import (
        ConnectionPolicy, Dispatcher, EntityName)
    from ceph_tpu.osd.daemon import MOSDPGPush

    class Sink(Dispatcher):
        def __init__(self):
            self.got = []

        def ms_dispatch(self, msg):
            self.got.append(msg)
            return True

    a = AsyncMessenger(EntityName("osd", 1))
    b = AsyncMessenger(EntityName("osd", 2))   # does not offer
    sink = Sink()
    for m in (a, b):
        m.set_policy("osd", ConnectionPolicy.stateful_peer())
    a.set_compression("zlib")
    b.add_dispatcher_tail(sink)
    try:
        b.bind("127.0.0.1:0")
        b.start()
        a.bind("127.0.0.1:0")
        a.start()
        con = a.connect_to(b.my_addr, EntityName("osd", 2))
        con.send_message(MOSDPGPush(pgid=(1, 0), oid="o",
                                    data=b"B" * 50000))
        deadline = time.time() + 10
        while time.time() < deadline and not sink.got:
            time.sleep(0.02)
        assert sink.got and sink.got[0].data == b"B" * 50000
        assert con.comp == COMP_NONE
    finally:
        a.shutdown()
        b.shutdown()


def test_psim():
    from ceph_tpu.tools.psim import simulate
    res = simulate(hosts=8, per_host=4, objects=2048, numrep=3)
    assert res["placements"] == 2048 * 3
    assert res["min"] > 0
    # uniform weights: spread within a sane band
    assert res["stddev_pct"] < 40
