"""Compressor plugin registry (src/compressor/ analog — the same
named-plugin pattern as the erasure-code registry; the reference's QAT
hook is the precedent for hardware-offloaded plugins behind this API).

Plugins: zlib and lzma (stdlib-backed; the reference's
snappy/zstd/lz4 are external libs this image doesn't carry) plus an
identity "none".
"""

from __future__ import annotations

import lzma
import threading
import zlib


class Compressor:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes) -> bytes:
        return data


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 5):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)


class LzmaCompressor(Compressor):
    name = "lzma"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return lzma.decompress(data)


# analysis: allow[bare-lock] -- import-time plugin registry lock; leaf
_LOCK = threading.Lock()
_FACTORIES = {
    "none": Compressor,
    "zlib": ZlibCompressor,
    "lzma": LzmaCompressor,
}


def register(name: str, factory) -> None:
    with _LOCK:
        _FACTORIES[name] = factory


def create(name: str, **kw) -> Compressor:
    """Compressor::create (compressor/Compressor.h:97)."""
    with _LOCK:
        factory = _FACTORIES.get(name)
    if factory is None:
        raise KeyError(f"compressor {name!r} unknown; "
                       f"known: {sorted(_FACTORIES)}")
    return factory(**kw)


def names() -> list[str]:
    with _LOCK:
        return sorted(_FACTORIES)
