"""Pipeline phase profiler: per-batch where-did-the-time-go
attribution (ops.telemetry.PhaseStats + the ops.dispatch ledger), the
mapping service's epoch phase split, the exposition surfaces
(dump_pipeline_profile, prometheus phase/util/compile families, the
MMgrReport v4 profile carriage and the insights `profile` commands),
the profile_report renderer, and the tracing monotonic-clock fix."""

from __future__ import annotations

import json
import threading
import time
import unittest.mock as mock

import numpy as np
import pytest

from ceph_tpu.common import tracing
from ceph_tpu.ops import telemetry
from ceph_tpu.ops.dispatch import DeviceDispatchEngine

K1, M1, B1 = 4, 2, 64


def _jit_add():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x + 1
    return lambda b: f(jnp.asarray(b))


def _drive(engine, *, key=("ec_encode", 8), reqs=8, writers=2,
           stripes=8):
    """A short concurrent burst so the engine actually coalesces
    while busy (idle-flush would make every batch single-request)."""
    fn = _jit_add()
    op = np.ones((stripes, 8), dtype=np.uint8)
    start = threading.Barrier(writers + 1)
    errs: list = []

    def actor():
        start.wait()
        try:
            for _ in range(reqs):
                engine.submit(key, fn, op).result(timeout=60)
        except Exception as e:          # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=actor, daemon=True)
               for _ in range(writers)]
    for t in threads:
        t.start()
    start.wait()
    for t in threads:
        t.join()
    assert not errs, errs
    assert engine.flush(timeout=10)


# -- the ledger itself --------------------------------------------------------

def test_phase_sum_reconstructs_end_to_end_latency():
    """The acceptance pin: on a busy engine every flushed batch's
    named phases sum to (>= 95% of) its submit->delivery wall-clock —
    the ledger is contiguous by construction, so the sum matches to
    float noise, not just the 95% floor."""
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(name="prof-e2e", stats=stats)
    try:
        _drive(eng, reqs=10, writers=3)
    finally:
        eng.stop()
    recent = stats.phases.dump()["recent"]
    assert len(recent) >= 3, recent
    for rec in recent:
        total = sum(rec["phases"].values())
        assert total >= 0.95 * rec["e2e_s"], rec
        assert total <= rec["e2e_s"] * 1.01 + 1e-6, rec
        assert set(rec["phases"]) == set(telemetry.PHASES)
    # the burst coalesced at least once (busy-engine precondition)
    assert any(r["requests"] > 1 for r in recent), recent


def test_compile_cost_separate_from_steady_state():
    """First-call batches (jit trace+compile) land in the compile
    ledger; the steady-state launch/compute histograms only sample
    post-compile batches."""
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(name="prof-compile", stats=stats)
    import jax

    @jax.jit
    def f(x):
        return x + 1
    import jax.numpy as jnp
    op = np.ones((8, 8), dtype=np.uint8)
    try:
        for _ in range(4):   # serial: every flush is one request,
            eng.submit(("k", 8), lambda b: f(jnp.asarray(b)),
                       op).result(timeout=60)   # same bucket each time
    finally:
        eng.stop()
    d = stats.phases.dump()
    assert d["compile"]["k"]["events"] == 1, d["compile"]
    assert d["compile"]["k"]["seconds"] > 0.0
    # 4 batches total, 1 compiled: launch/compute sampled 3 times,
    # the always-steady phases 4 times
    fam = d["phases"]["k"]
    assert fam["launch"]["count"] == 3, fam["launch"]
    assert fam["compute"]["count"] == 3
    assert fam["queue_wait"]["count"] == 4
    recs = d["recent"]
    assert [r["compiled"] for r in recs] == [True, False, False, False]


def test_phase_stats_unit_busy_imbalance_and_ring():
    """Direct PhaseStats math: busy-seconds integral scales with
    devices, shard imbalance is the padded-lane share, the ring is
    bounded, and clear() re-arms first-call detection."""
    ps = telemetry.PhaseStats("unit")
    phases = {ph: 0.0 for ph in telemetry.PHASES}
    phases["compute"] = 0.5
    ps.record_batch("ec_encode", phases=phases, e2e_s=0.5, requests=3,
                    stripes=5, bucket=8, devices=4, misses=0)
    d = ps.dump()
    assert d["busy_seconds"] == pytest.approx(2.0)   # 0.5 s x 4 dev
    assert d["devices_seen"] == 4
    assert d["last_shard_imbalance"] == pytest.approx(1 - 5 / 8)
    assert d["shard_imbalance"]["count"] == 1
    assert 0.0 <= ps.utilization() <= 1.0
    # misses=0 says "probed, no retrace": no compile charged
    assert d["compile"] == {}
    # misses=None falls back to first-(family,bucket,devices) detection
    ps.record_batch("crush_rule", phases=phases, e2e_s=0.5, requests=1,
                    stripes=8, bucket=8, devices=1, misses=None)
    assert ps.dump()["compile"]["crush_rule"]["events"] == 1
    ps.record_batch("crush_rule", phases=phases, e2e_s=0.5, requests=1,
                    stripes=8, bucket=8, devices=1, misses=None)
    assert ps.dump()["compile"]["crush_rule"]["events"] == 1  # seen
    ps.clear()
    assert ps.dump()["recent"] == []
    ps.record_batch("crush_rule", phases=phases, e2e_s=0.5, requests=1,
                    stripes=8, bucket=8, devices=1, misses=None)
    assert ps.dump()["compile"]["crush_rule"]["events"] == 1  # re-armed


def test_profile_ring_knob_is_a_config_option():
    from ceph_tpu.common.context import CephTpuContext

    st = telemetry.dispatch_stats()
    try:
        ctx = CephTpuContext("client.profring")
        ctx.conf.set("kernel_profile_ring", "4")
        assert st.phases.records.maxlen == 4
        phases = {ph: 0.0 for ph in telemetry.PHASES}
        for i in range(9):
            st.phases.record_batch("k", phases=phases, e2e_s=0.0,
                                   requests=1, stripes=1, bucket=1,
                                   devices=1, misses=0)
        assert len(st.phases.dump()["recent"]) == 4
    finally:
        telemetry.set_profile_ring(telemetry.PROFILE_RING_DEFAULT)
        telemetry.reset()


# -- mapping epoch phase split ------------------------------------------------

def _small_map(epoch=2, pools=2, pg_num=32):
    from ceph_tpu.crush import build_two_level_map
    from ceph_tpu.osd import OSDMap, PGPool

    crush, _root, rule = build_two_level_map(4, 2)
    m = OSDMap(crush=crush, epoch=epoch)
    m.set_max_osd(8)
    for o in range(8):
        m.mark_up(o)
    for p in range(1, pools + 1):
        m.pools[p] = PGPool(pool_id=p, size=3, crush_rule=rule,
                            pg_num=pg_num)
    return m


def test_mapping_service_phase_split_live():
    """A live service's computed epochs split into device vs delta vs
    host-tail phases, readable from dump_mapping_stats — and the PR 10
    fused ladder COLLAPSES the host tail: the default (fused) service
    records zero host-tail seconds while an unfused twin of the same
    churn still pays it."""
    from ceph_tpu.osd import SharedPGMappingService

    def churn(svc, m):
        svc.update_to(m)
        for i in range(3):
            new = m.copy()
            new.epoch = m.epoch + 1
            new.osd_weight[i % 8] = 0x8000 if i % 2 == 0 else 0x10000
            upd = svc.update_to(new)
            assert not upd.full
            m = new

    telemetry.reset()
    churn(SharedPGMappingService(), _small_map())
    d = telemetry.mapping_dump()
    ph = d["phase_seconds"]
    assert set(ph) == {"device", "delta", "host_tail"}
    assert ph["device"]["count"] == 4          # first map + 3 epochs
    assert ph["device"]["sum"] > 0.0
    # the 3 churn epochs diffed fused outputs on device: the candidate
    # pass still costs delta time, the host tail contributes NOTHING
    assert ph["delta"]["sum"] > 0.0
    assert ph["host_tail"]["sum"] == 0.0
    assert d["host_tail_share"] == 0.0
    assert d["fused_epochs"] == 4
    assert d["unfused_epochs"] == 0
    summ = telemetry.mapping_stats().phase_summary()
    assert summ["epochs"] == 4
    assert summ["fused_epochs"] == 4
    assert sum(summ["share"].values()) == pytest.approx(1.0, abs=0.01)
    # the unfused twin (knob off) pays the per-candidate host tail
    telemetry.reset()
    churn(SharedPGMappingService(fused=False), _small_map())
    d = telemetry.mapping_dump()
    assert d["phase_seconds"]["host_tail"]["sum"] > 0.0
    assert d["host_tail_share"] > 0.0
    assert d["fused_epochs"] == 0
    assert d["unfused_epochs"] == 4
    telemetry.reset()


# -- admin socket -------------------------------------------------------------

def test_dump_pipeline_profile_admin_roundtrip():
    """The admin command serves the full profile — and, in this 8-dev
    test env, the context engine's mesh fan-out shows up in the
    utilization story."""
    from ceph_tpu.common.context import CephTpuContext

    telemetry.reset()
    ctx = CephTpuContext("prof-admin")
    eng = ctx.dispatch_engine()
    try:
        _drive(eng, reqs=4, writers=2)
        out = ctx.admin.execute("dump_pipeline_profile")
        assert set(out) == {"encode", "decode", "mapping"}
        enc = out["encode"]
        assert enc["recent"], enc
        fam = enc["phases"]["ec_encode"]
        assert set(telemetry.PHASES) >= set(fam)
        assert enc["busy_seconds"] > 0.0
        import jax
        if len(jax.devices()) > 1:
            assert enc["devices_seen"] > 1
            assert enc["shard_imbalance"]["count"] >= 1
        # payload is JSON-serializable end to end (the socket wire)
        json.dumps(out)
        # mapping split rides along
        assert set(out["mapping"]["seconds"]) == {"device", "delta",
                                                  "host_tail"}
    finally:
        eng.stop()
        telemetry.reset()


# -- prometheus families ------------------------------------------------------

def test_prometheus_phase_util_compile_families():
    from test_kernel_telemetry import _scrape, parse_exposition

    telemetry.reset()
    stats = telemetry.dispatch_stats()
    eng = DeviceDispatchEngine(name="prof-prom", stats=stats)
    try:
        _drive(eng, reqs=4, writers=2)
    finally:
        eng.stop()
    telemetry.mapping_stats().record_phases(
        device_s=0.01, delta_s=0.002, host_tail_s=0.001)
    fams = parse_exposition(_scrape())
    telemetry.reset()
    for want, typ in (
            ("ceph_kernel_phase_seconds", "histogram"),
            ("ceph_kernel_compile_seconds_total", "counter"),
            ("ceph_kernel_compile_events_total", "counter"),
            ("ceph_kernel_util_busy_seconds_total", "counter"),
            ("ceph_kernel_util_utilization", "gauge"),
            ("ceph_kernel_util_devices", "gauge"),
            ("ceph_kernel_util_shard_imbalance", "histogram"),
            ("ceph_kernel_mapping_phase_seconds", "histogram")):
        assert want in fams, (want, sorted(fams))
        assert fams[want]["type"] == typ, (want, fams[want]["type"])
    phase_labels = {(s[1].get("engine"), s[1].get("kernel"),
                     s[1].get("phase"))
                    for s in fams["ceph_kernel_phase_seconds"]["samples"]}
    assert ("encode", "ec_encode", "queue_wait") in phase_labels
    mapping_phases = {s[1].get("phase") for s in
                      fams["ceph_kernel_mapping_phase_seconds"]["samples"]}
    assert mapping_phases == {"device", "delta", "host_tail"}
    # utilization gauge is a sane fraction for both engines
    for _n, lab, v in fams["ceph_kernel_util_utilization"]["samples"]:
        assert lab["engine"] in ("encode", "decode")
        assert 0.0 <= v <= 1.0


# -- insights: cluster-wide merge ---------------------------------------------

def _digest(qw, comp, osd_busy, events=1):
    return {
        "encode": {"kernels": {"ec_encode": {
            "seconds": {"queue_wait": qw, "compute": comp},
            "share": {}, "batches": 5}},
            "compile": {"ec_encode": {"seconds": 0.25,
                                      "events": events}},
            "busy_seconds": osd_busy, "utilization": 0.5,
            "devices_seen": 8, "last_shard_imbalance": 0.1},
        "decode": {"kernels": {}, "compile": {}, "busy_seconds": 0.0,
                   "utilization": 0.0, "devices_seen": 1,
                   "last_shard_imbalance": 0.0},
        "mapping": {"seconds": {"device": 0.2, "delta": 0.05,
                                "host_tail": 0.01},
                    "share": {}, "epochs": 3},
    }


class _FeedMgr:
    def __init__(self, feed):
        self._feed = feed

    def get(self, name):
        assert name == "insights_feed"
        return self._feed


def test_insights_profile_merges_two_daemons_unit():
    """The merge math, pinned: seconds SUM across daemons, shares
    recomputed over merged totals, compile/mapping ledgers add up,
    and `profile top` ranks the cluster-wide stall first."""
    from ceph_tpu.mgr.modules.insights import Module

    feed = {0: {"profile": _digest(1.0, 3.0, 10.0), "slow_traces": [],
                "slow_ops": [], "stamp": 1.0},
            1: {"profile": _digest(2.0, 6.0, 20.0, events=2),
                "slow_traces": [], "slow_ops": [], "stamp": 1.0}}
    mod = Module(_FeedMgr(feed))
    merged = mod.profile_phases()
    row = merged["engines"]["encode"]["ec_encode"]
    assert row["seconds"]["queue_wait"] == pytest.approx(3.0)
    assert row["seconds"]["compute"] == pytest.approx(9.0)
    assert row["share"]["compute"] == pytest.approx(0.75)
    assert row["reported_by"] == [0, 1]
    assert row["batches"] == 10
    comp = merged["compile"]["encode"]["ec_encode"]
    assert comp == {"seconds": pytest.approx(0.5), "events": 3,
                    "reported_by": [0, 1]}
    assert merged["mapping"]["seconds"]["device"] == pytest.approx(0.4)
    assert merged["mapping"]["epochs"] == 6
    assert set(merged["utilization"]["encode"]) == {"osd.0", "osd.1"}
    top = mod.profile_top(3)
    assert top[0]["kernel"] == "ec_encode"
    assert top[0]["phase"] == "compute"
    assert top[0]["seconds"] == pytest.approx(9.0)
    # compile ranks as its own phase row
    assert any(r["phase"] == "compile" for r in mod.profile_top(20))
    # command tier round-trips JSON
    out, rc = mod.handle_command({"prefix": "profile top", "limit": 2})
    assert rc == 0
    assert len(json.loads(out)["stalls"]) == 2
    out, rc = mod.handle_command({"prefix": "profile phases"})
    assert rc == 0
    assert "engines" in json.loads(out)


def test_insights_profile_dedups_shared_registry_digests():
    """In-process daemons all ship the SAME process-global digest —
    the merge must count it once (every reporter listed), not inflate
    cluster totals by the daemon count."""
    from ceph_tpu.mgr.modules.insights import Module

    same = _digest(1.0, 3.0, 10.0)
    feed = {0: {"profile": same, "stamp": 1.0},
            1: {"profile": json.loads(json.dumps(same)), "stamp": 2.0},
            2: {"profile": _digest(5.0, 0.5, 1.0), "stamp": 3.0}}
    merged = Module(_FeedMgr(feed)).profile_phases()
    row = merged["engines"]["encode"]["ec_encode"]
    # osd 0+1 share one registry (identical digest): one contribution
    assert row["seconds"]["queue_wait"] == pytest.approx(1.0 + 5.0)
    assert row["seconds"]["compute"] == pytest.approx(3.0 + 0.5)
    assert sorted(row["reported_by"]) == [0, 1, 2]
    assert merged["mapping"]["epochs"] == 6     # 3 + 3, not 9
    assert set(merged["utilization"]["encode"]) == {"osd.0", "osd.1",
                                                    "osd.2"}


def test_insights_profile_top_e2e_two_daemons():
    """e2e: two OSDs ship pipeline-profile digests in MMgrReport v4
    and the mgr's `profile top` serves the cluster-wide merge."""
    from ceph_tpu.tools.vstart import MiniCluster

    telemetry.reset()
    c = MiniCluster(n_osds=2, ms_type="loopback").start()
    try:
        c.run_mgr()
        for oid in list(c.osds):       # osds re-report to the mgr
            c.kill_osd(oid)
            c.run_osd(oid)
        c.wait_for_osd_count(2)
        # engine traffic lands in the process-global profiler every
        # daemon's report reads (the in-process MiniCluster shares it)
        eng = DeviceDispatchEngine(name="prof-e2e-feed",
                                   stats=telemetry.dispatch_stats())
        try:
            _drive(eng, reqs=3, writers=2)
        finally:
            eng.stop()
        deadline = time.time() + 30
        mgr = c.mgr
        while time.time() < deadline:
            feed = mgr.insights_feed()
            ready = [o for o, e in feed.items()
                     if (e.get("profile") or {}).get(
                         "encode", {}).get("kernels")]
            if len(ready) >= 2:
                break
            time.sleep(0.2)
        assert len(ready) >= 2, feed.keys()
        out, rc = mgr._handle_command({"prefix": "profile top"})
        assert rc == 0, out
        stalls = json.loads(out)["stalls"]
        assert stalls, out
        enc = [r for r in stalls if r["kernel"] == "ec_encode"]
        assert enc, stalls
        # the merge really folded BOTH daemons' feeds
        assert sorted(enc[0]["reported_by"]) == sorted(ready)[:2] \
            or len(enc[0]["reported_by"]) >= 2
        out, rc = mgr._handle_command({"prefix": "profile phases"})
        assert rc == 0, out
        merged = json.loads(out)
        assert "ec_encode" in merged["engines"]["encode"]
    finally:
        c.stop()
        telemetry.reset()


# -- tracing: async batches re-join traces with phase events ------------------

def test_async_dispatch_span_carries_phase_events():
    """tracing show on an async submit explains its latency: the
    device span carries queue-wait/build/h2d/compute/d2h events."""
    tracing.reset()
    stats = telemetry.DispatchStats()
    eng = DeviceDispatchEngine(name="prof-span", stats=stats)
    import jax

    @jax.jit
    def f(x):
        return x + 1
    import jax.numpy as jnp
    try:
        with tracing.trace_ctx(name="traced ec write",
                               daemon="client") as tid:
            eng.submit(("ec_encode", 8),
                       lambda b: f(jnp.asarray(b)),
                       np.ones((8, 8), np.uint8)).result(timeout=60)
        eng.flush(timeout=10)
    finally:
        eng.stop()
    rows = tracing.dump(tid)
    dev = [r for r in rows if r.get("kind") == "span"
           and r["event"].startswith("device ")]
    assert dev, rows
    span_id = dev[0]["span_id"]
    events = [r["event"] for r in rows
              if r.get("kind") == "event" and r["span_id"] == span_id]
    for prefix in ("queue-wait ", "build ", "h2d ", "compute ",
                   "d2h "):
        assert any(e.startswith(prefix) for e in events), (prefix,
                                                           events)
    tracing.reset()


# -- tracing: monotonic duration math -----------------------------------------

def test_wall_clock_step_cannot_skew_durations():
    """An NTP step (wall clock jumping backwards mid-span) must not
    produce negative durations or mis-rank tail sampling: duration
    math pairs the monotonic clock, wall time is display-only."""
    tracing.reset()
    tracing.set_slow_threshold(0.0)
    base = time.time()
    try:
        with mock.patch("time.time", lambda: base):
            with tracing.trace_ctx(name="ntp victim",
                                   daemon="t") as tid:
                sp = tracing.begin_span("inner", "t")
                time.sleep(0.02)
                # the step: wall clock falls an hour mid-span
                with mock.patch("time.time", lambda: base - 3600.0):
                    tracing.finish_span(sp)
        assert sp.duration is not None and sp.duration >= 0.02, \
            sp.duration
        assert sp.end == base - 3600.0          # display preserved
        # the completed trace promoted with a sane (>= 0) duration
        snap = [s for s in tracing.slow_traces()
                if s["trace_id"] == tid]
        assert snap and snap[0]["duration"] >= 0.0, snap
        # the dumped row's dur is the monotonic one
        row = [r for r in tracing.dump(tid)
               if r.get("span_id") == sp.span_id
               and r.get("kind") == "span"][0]
        assert row["dur"] >= 0.02
    finally:
        tracing.reset()


def test_instantaneous_tx_span_has_zero_duration():
    """stamp()'s instantaneous hop marker (finish_span(t=start))
    still reads as zero duration under the monotonic pairing."""
    tracing.reset()
    with tracing.trace_ctx(name="root", daemon="t"):
        sp = tracing.begin_span("tx hop", "t")
        time.sleep(0.005)
        tracing.finish_span(sp, t=sp.start)
    assert sp.duration == 0.0
    tracing.reset()


# -- the report renderer ------------------------------------------------------

def test_profile_report_renders_all_input_shapes():
    from ceph_tpu.tools.profile_report import normalize, render

    telemetry.reset()
    stats = telemetry.dispatch_stats()
    eng = DeviceDispatchEngine(name="prof-render", stats=stats)
    try:
        _drive(eng, reqs=3, writers=2)
    finally:
        eng.stop()
    telemetry.mapping_stats().record_phases(
        device_s=0.01, delta_s=0.002, host_tail_s=0.001)
    dump = telemetry.pipeline_profile_dump()
    digest = telemetry.pipeline_profile_digest()
    telemetry.reset()
    for doc in (dump, digest, {"profile": digest, "metric": "x"}):
        n = normalize(doc)
        assert "ec_encode" in n["engines"]["encode"], doc.keys()
        text = render(doc)
        assert "ec_encode" in text
        assert "queue_wait" in text
        assert "compile ledger" in text
        assert "mapping epochs" in text
    # the insights merged shape renders too
    from ceph_tpu.mgr.modules.insights import Module
    mod = Module(_FeedMgr({0: {"profile": digest, "stamp": 1.0}}))
    text = render(mod.profile_phases())
    assert "ec_encode" in text
