"""ceph_erasure_code_benchmark analog
(src/test/erasure-code/ceph_erasure_code_benchmark.cc).

Same flags, same output contract — one line per run:

    <elapsed seconds>\t<total KiB processed>

Usage mirrors the reference (:40-65 usage text):
    python -m ceph_tpu.tools.ec_benchmark --plugin jerasure \
        --parameter k=4 --parameter m=2 --parameter technique=reed_sol_van \
        --size 1048576 --iterations 100 --workload encode
    ... --workload decode --erasures 2 [--erasures-generation exhaustive]

Additions over the reference: --batch (stripes per device call — the ECUtil
batch point) and --runtime tpu|cpu.
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time

import numpy as np

from ceph_tpu.ec import registry_instance


def bench_encode(codec, object_size: int, iterations: int,
                 batch: int) -> tuple[float, int]:
    k = codec.get_data_chunk_count()
    chunk = codec.get_chunk_size(object_size)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
    # warm (compile) then measure
    codec.encode_chunks(data)
    total_kib = 0
    t0 = time.perf_counter()
    done = 0
    while done < iterations:
        n = min(batch, iterations - done)
        out = codec.encode_chunks(data[:n])
        done += n
        total_kib += n * object_size // 1024
    np.asarray(out)  # materialize
    return time.perf_counter() - t0, total_kib


def bench_decode(codec, object_size: int, iterations: int, batch: int,
                 erasures: int, exhaustive: bool) -> tuple[float, int]:
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    chunk = codec.get_chunk_size(object_size)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
    parity = np.asarray(codec.encode_chunks(data))
    full = np.concatenate([data, parity], axis=1)
    if exhaustive:
        patterns = list(itertools.combinations(range(n), erasures))
    else:
        patterns = [tuple(sorted(rng.choice(n, erasures, replace=False)))]
    total_kib = 0
    t0 = time.perf_counter()
    done = 0
    while done < iterations:
        lost = patterns[done % len(patterns)]
        chosen = [i for i in range(n) if i not in lost][:k]
        m = min(batch, iterations - done)
        out = codec.decode_chunks(chosen, full[:m, chosen], list(lost))
        done += m
        total_kib += m * object_size // 1024
    np.asarray(out)
    return time.perf_counter() - t0, total_kib


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ec_benchmark")
    p.add_argument("--plugin", "-p", default="jerasure")
    p.add_argument("--parameter", "-P", action="append", default=[],
                   help="profile key=value (k=, m=, technique=, ...)")
    p.add_argument("--size", "-S", type=int, default=1024 * 1024,
                   help="object size in bytes")
    p.add_argument("--iterations", "-i", type=int, default=100)
    p.add_argument("--workload", "-w", choices=["encode", "decode"],
                   default="encode")
    p.add_argument("--erasures", "-e", type=int, default=1)
    p.add_argument("--erasures-generation", "-E",
                   choices=["random", "exhaustive"], default="random")
    p.add_argument("--batch", type=int, default=64,
                   help="stripes per device call")
    p.add_argument("--runtime", choices=["tpu", "cpu"], default="tpu")
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)

    profile = {"runtime": args.runtime}
    for kv in args.parameter:
        key, _, val = kv.partition("=")
        profile[key] = val
    codec = registry_instance().factory(args.plugin, profile)

    if args.workload == "encode":
        elapsed, kib = bench_encode(codec, args.size, args.iterations,
                                    args.batch)
    else:
        elapsed, kib = bench_decode(
            codec, args.size, args.iterations, args.batch, args.erasures,
            args.erasures_generation == "exhaustive")
    # the reference's output contract (:188, :326)
    print(f"{elapsed:.6f}\t{kib}")
    if args.verbose:
        print(f"# {kib / 1024 / max(elapsed, 1e-9):.1f} MB/s "
              f"{args.plugin} {profile}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
