"""Messenger abstraction (src/msg/Messenger.h:120, Connection, Dispatcher,
per-peer Policy — msg/Policy.h).

A Messenger owns an entity identity ("osd.3", "mon.0", "client.4123"), binds a
transport, hands out Connections keyed by peer address, and delivers inbound
messages to a dispatcher chain.  Policies mirror the reference knobs set in
ceph_osd.cc:531-545: lossy server-side client sessions, stateful cluster
peers, byte throttles.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.common import lockdep
from ceph_tpu.common.throttle import Throttle

from .message import Message


@dataclass(frozen=True, order=True)
class EntityName:
    """entity_name_t: type.id ("osd.3")."""

    type: str
    id: int

    def __str__(self):
        return f"{self.type}.{self.id}"

    @staticmethod
    def parse(s: str) -> "EntityName":
        t, i = s.rsplit(".", 1)
        return EntityName(t, int(i))


@dataclass
class ConnectionPolicy:
    """msg/Policy.h: lossy connections drop state on failure (server->client);
    stateful ones reconnect and resend (cluster peers)."""

    lossy: bool = False
    server: bool = False
    resend_on_reconnect: bool = True
    throttler_bytes: Throttle | None = None
    #: extra feature bits this peer type MUST speak
    #: (Policy::features_required; FEATURE_BASE is always required)
    features_required: int = 0

    @staticmethod
    def lossy_client() -> "ConnectionPolicy":
        return ConnectionPolicy(lossy=True, server=True,
                                resend_on_reconnect=False)

    @staticmethod
    def stateful_server() -> "ConnectionPolicy":
        return ConnectionPolicy(lossy=False, server=True)

    @staticmethod
    def stateful_peer() -> "ConnectionPolicy":
        return ConnectionPolicy(lossy=False, server=False)


class Connection:
    """One peer session; send_message is asynchronous and ordered
    (msg/Connection.h)."""

    def __init__(self, messenger: "Messenger", peer_addr: str):
        self.messenger = messenger
        self.peer_addr = peer_addr
        self.peer_name: EntityName | None = None
        #: cephx-authenticated identity (e.g. "client.admin"), set by
        #: wire handshakes; None on unauthenticated/loopback links
        self.auth_entity: str | None = None
        #: negotiated feature intersection; wire handshakes overwrite,
        #: in-process transports (loopback/ici) keep the full local set
        from ceph_tpu.msg.features import SUPPORTED_FEATURES
        self.features: int = SUPPORTED_FEATURES

    def send_message(self, msg: Message) -> None:
        raise NotImplementedError

    def mark_down(self) -> None:
        """Tear the session down (Connection::mark_down)."""
        raise NotImplementedError

    def is_connected(self) -> bool:
        raise NotImplementedError


class Dispatcher:
    """Callback interface (msg/Dispatcher.h).  Messengers walk the dispatcher
    chain until one returns True from ms_dispatch."""

    def ms_dispatch(self, msg: Message) -> bool:
        return False

    def ms_handle_reset(self, con: Connection) -> None:
        """Peer session dropped (stateful peer reset)."""

    def ms_handle_remote_reset(self, con: Connection) -> None:
        """Peer told us it reset."""


class Messenger:
    """Transport-agnostic base; create() picks the stack like
    Messenger::create(cct, type, ...)."""

    #: True for stacks that serialize to a real byte stream and bind
    #: host:port addresses (TCP); loopback/ici bind entity names
    is_wire = False

    def __init__(self, name: EntityName):
        self.my_name = name
        self.my_addr: str | None = None
        self._dispatchers: list[Dispatcher] = []
        self._policies: dict[str, ConnectionPolicy] = {}
        self._default_policy = ConnectionPolicy()
        from ceph_tpu.msg.features import SUPPORTED_FEATURES
        #: what this endpoint advertises; tests shrink it to simulate
        #: an old peer
        self.local_features: int = SUPPORTED_FEATURES
        self._lock = lockdep.make_lock(f"Messenger::lock({name})")
        # per-messenger wire counters (AsyncMessenger's l_msgr_* set);
        # daemons register this into their context's collection
        from ceph_tpu.common.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder(f"msgr.{name}")
                     .add_u64("msg_send").add_u64("msg_recv")
                     .add_u64("bytes_send").add_u64("bytes_recv")
                     .create_perf_counters())

    def count_sent(self, nbytes: int) -> None:
        """Transport send hook: one frame of nbytes left this endpoint."""
        self.perf.inc("msg_send")
        self.perf.inc("bytes_send", nbytes)

    @staticmethod
    def create(name: EntityName, mtype: str = "async", **kw) -> "Messenger":
        if mtype == "async":
            # the event-driven stack is the default AsyncMessenger, like
            # the reference (epoll event centers); the thread-per-
            # connection stack stays available as "threaded"
            from .event_tcp import EventMessenger
            return EventMessenger(name, **kw)
        if mtype == "threaded":
            from .async_tcp import AsyncMessenger
            return AsyncMessenger(name, **kw)
        if mtype == "loopback":
            from .loopback import LoopbackMessenger
            return LoopbackMessenger(name, **kw)
        if mtype == "ici":
            from .ici import IciMessenger
            return IciMessenger(name, **kw)
        if mtype == "ici-wire":
            # cross-process: TCP control plane, transfer-server bulk
            # data plane (msg/ici.make_wire_messenger)
            from .ici import make_wire_messenger
            return make_wire_messenger(name, **kw)
        raise ValueError(f"unknown messenger type {mtype!r}")

    # -- dispatcher chain (Messenger.h:337-352) -------------------------------

    def set_auth(self, key, required: bool = True) -> None:
        """cephx-lite shared-key authentication; only wire stacks
        enforce it (in-process loopback peers are the same trust
        domain)."""

    def set_auth_cephx(self, config) -> None:
        """Per-entity cephx (tickets + entity secrets, a CephxConfig);
        only wire stacks enforce it — in-process loopback peers are the
        same trust domain."""

    def set_compression(self, mode) -> None:
        """On-wire frame compression offer; only wire stacks compress
        (loopback/ici never serialize to a byte stream)."""

    def add_dispatcher_head(self, d: Dispatcher) -> None:
        with self._lock:
            self._dispatchers.insert(0, d)

    def add_dispatcher_tail(self, d: Dispatcher) -> None:
        with self._lock:
            self._dispatchers.append(d)

    def deliver(self, msg: Message) -> bool:
        self.perf.inc("msg_recv")
        self.perf.inc("bytes_recv", getattr(msg, "wire_bytes", 0))
        tb = None
        policy = self.policy_for(msg.connection.peer_name.type
                                 if msg.connection and msg.connection.peer_name
                                 else "client")
        if policy.throttler_bytes is not None:
            size = msg.frame_size()
            policy.throttler_bytes.get(size)
            tb = (policy.throttler_bytes, size)
        tid = getattr(msg, "trace_id", 0)
        rx_span = None
        prev_trace = (0, 0)
        if tid:
            # the handling thread JOINS the trace under an rx dispatch
            # span parented to the sender's span (the frame's
            # parent_span_id): everything it sends while dispatching
            # inherits the ids (common/tracing.stamp), and work handed
            # to shard queues re-parents here via the message
            from ceph_tpu.common import tracing
            rx_span = tracing.begin_span(
                f"rx {type(msg).__name__}", str(self.my_name),
                trace_id=tid,
                parent_span_id=getattr(msg, "parent_span_id", 0))
            if rx_span is not None:
                msg.parent_span_id = rx_span.span_id
            prev_trace = tracing.set_current(
                tid, rx_span.span_id if rx_span else 0)
        try:
            with self._lock:
                chain = list(self._dispatchers)
            for d in chain:
                if d.ms_dispatch(msg):
                    return True
            return False
        finally:
            if tid:
                from ceph_tpu.common import tracing
                tracing.finish_span(rx_span)
                tracing.set_current(prev_trace)
            if tb:
                tb[0].put(tb[1])

    def notify_reset(self, con: Connection) -> None:
        with self._lock:
            chain = list(self._dispatchers)
        for d in chain:
            d.ms_handle_reset(con)

    # -- policies -------------------------------------------------------------

    def set_policy(self, peer_type: str, policy: ConnectionPolicy) -> None:
        with self._lock:
            self._policies[peer_type] = policy

    def set_default_policy(self, policy: ConnectionPolicy) -> None:
        with self._lock:
            self._default_policy = policy

    def policy_for(self, peer_type: str) -> ConnectionPolicy:
        with self._lock:
            return self._policies.get(peer_type, self._default_policy)

    def required_for(self, peer_type: str) -> int:
        """Feature bits a peer of this type must speak: the global
        floor plus the per-type policy's features_required."""
        from ceph_tpu.msg.features import REQUIRED_DEFAULT
        return REQUIRED_DEFAULT | self.policy_for(
            peer_type).features_required

    # -- transport lifecycle --------------------------------------------------

    def bind(self, addr: str) -> None:
        raise NotImplementedError

    def start(self) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        raise NotImplementedError

    def connect_to(self, addr: str, peer_name: EntityName) -> Connection:
        raise NotImplementedError
