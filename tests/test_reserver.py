"""AsyncReserver (common/AsyncReserver.h analog) + reservation-gated,
windowed recovery on the cluster."""

import time

from ceph_tpu.osd.reserver import AsyncReserver


def test_grant_within_capacity():
    r = AsyncReserver(max_allowed=2)
    got = []
    r.request("a", lambda: got.append("a"))
    r.request("b", lambda: got.append("b"))
    r.request("c", lambda: got.append("c"))
    assert got == ["a", "b"]
    assert r.has("a") and r.has("b") and not r.has("c")


def test_release_grants_next_in_fifo():
    r = AsyncReserver(max_allowed=1)
    got = []
    for k in "abc":
        r.request(k, lambda k=k: got.append(k))
    assert got == ["a"]
    r.cancel("a")
    assert got == ["a", "b"]
    r.cancel("b")
    assert got == ["a", "b", "c"]


def test_priority_wins_over_fifo():
    r = AsyncReserver(max_allowed=1)
    got = []
    r.request("low1", lambda: got.append("low1"))
    r.request("low2", lambda: got.append("low2"), prio=0)
    r.request("high", lambda: got.append("high"), prio=10)
    r.cancel("low1")
    assert got == ["low1", "high"]


def test_cancel_queued_request():
    r = AsyncReserver(max_allowed=1)
    got = []
    r.request("a", lambda: got.append("a"))
    r.request("b", lambda: got.append("b"))
    r.cancel("b")          # abandon while queued
    r.cancel("a")
    assert got == ["a"]
    assert not r.has("b")


def test_duplicate_request_is_noop():
    r = AsyncReserver(max_allowed=1)
    got = []
    r.request("a", lambda: got.append("a"))
    r.request("a", lambda: got.append("dup"))
    assert got == ["a"]


def test_set_max_grants_backlog():
    r = AsyncReserver(max_allowed=1)
    got = []
    for k in "abc":
        r.request(k, lambda k=k: got.append(k))
    r.set_max(3)
    assert got == ["a", "b", "c"]


def test_windowed_recovery_completes():
    """A rejoining osd with many missing objects recovers them all
    through a 1-slot reservation and a 2-object pull window."""
    from ceph_tpu.tools.vstart import MiniCluster
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=4, size=3)
        io = client.open_ioctx(pool)
        for i in range(10):
            io.write_full(f"w{i}", f"windowed-{i}".encode() * 20)
        time.sleep(0.3)
        # rejoining osd recovers with a tight window
        c.kill_osd(2)
        rc, out = client.mon_command({"prefix": "osd down", "id": 2})
        assert rc == 0, out
        for i in range(10):
            io.write_full(f"w{i}", f"updated-{i}".encode() * 20)
        osd = c.run_osd(2)
        osd.ctx.conf.set("osd_recovery_max_active", 2)
        c.wait_for_osd_count(3)
        # every object converges on the rejoined osd
        deadline = time.time() + 20
        def clean():
            for pgid, pg in list(osd.pgs.items()):
                if pg.missing or pg.state != "active":
                    return False
            return len(osd.pgs) > 0
        while time.time() < deadline and not clean():
            time.sleep(0.1)
        assert clean(), "windowed recovery never converged"
        # reservation slots all released
        assert osd.local_reserver.dump()["granted"] == []
        for i in range(10):
            assert io.read(f"w{i}") == f"updated-{i}".encode() * 20
    finally:
        c.stop()
