"""pg_autoscaler module (pybind/mgr/pg_autoscaler analog, reduced to
the grow path our mon supports).

The reference sizes every pool's pg_num from its share of cluster
capacity: each pool's usage ratio times the cluster PG budget
(osd count x mon_target_pg_per_osd), divided by the pool's replication
factor, rounded to a power of two — and only acts when the pool is off
by more than a 3x threshold, so pg_num is not churned on noise.

Our mon only ever GROWS pg_num (PG merge does not exist here, as in
pre-Nautilus reference clusters), so the scaler raises undersized pools
and reports — but does not apply — shrink recommendations.
"""

from __future__ import annotations

import json
import time

from ceph_tpu.mgr.module import MgrModule


def _pow2_at_most(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class Module(MgrModule):
    NAME = "pg_autoscaler"
    COMMANDS = [{"prefix": "osd pool autoscale-status",
                 "help": "per-pool pg_num recommendations"}]
    MODULE_OPTIONS = [
        {"name": "target_pgs_per_osd", "default": 100},
        {"name": "threshold", "default": 3.0},
        {"name": "sleep_interval", "default": 5.0},
    ]

    def __init__(self, mgr):
        super().__init__(mgr)
        self._last_run = 0.0
        self._last_status: list[dict] = []

    # -- sizing model ---------------------------------------------------------

    def _pool_bytes(self) -> dict[int, int]:
        """Stored bytes per pool from the per-PG stat rows (pgid is
        'pool.ps')."""
        out: dict[int, int] = {}
        for row in self.get("pg_dump")["pg_stats"]:
            pid = int(row["pgid"].split(".")[0])
            out[pid] = out.get(pid, 0) + int(row.get("bytes", 0))
        return out

    def recommendations(self) -> list[dict]:
        m = self.get_osdmap()
        n_osd = sum(1 for o in range(m.max_osd) if m.is_up(o))
        if n_osd == 0 or not m.pools:
            return []
        budget = n_osd * int(self.get_module_option(
            "target_pgs_per_osd", 100))
        usage = self._pool_bytes()
        total = sum(usage.values())
        rows = []
        for pid, pool in sorted(m.pools.items()):
            size = max(getattr(pool, "size", 1), 1)
            if total > 0:
                ratio = usage.get(pid, 0) / total
            else:
                ratio = 1.0 / len(m.pools)   # empty cluster: equal share
            target = _pow2_at_most(max(
                int(ratio * budget / size), 1))
            rows.append({"pool": pid, "pg_num": pool.pg_num,
                         "bytes": usage.get(pid, 0),
                         "capacity_ratio": round(ratio, 4),
                         "target_pg_num": target})
        return rows

    def maybe_scale(self) -> list[dict]:
        """One pass: apply grow recommendations past the threshold.
        Returns the rows it acted on (tests + autoscale-status)."""
        threshold = float(self.get_module_option("threshold", 3.0))
        acted = []
        rows = self.recommendations()
        for row in rows:
            cur, target = row["pg_num"], row["target_pg_num"]
            row["action"] = "none"
            if target >= cur * threshold:
                rc, out = self.mon_command({
                    "prefix": "osd pool set", "pool": row["pool"],
                    "var": "pg_num", "val": target})
                row["action"] = ("grown" if rc == 0
                                 else f"grow failed rc={rc}")
                if rc == 0:
                    self.log(1, "pool %d pg_num %d -> %d "
                             "(capacity_ratio %.3f)", row["pool"],
                             cur, target, row["capacity_ratio"])
                    acted.append(row)
            elif cur > target * threshold:
                # shrink would need PG merge; recommend only
                row["action"] = "would-shrink (merge unsupported)"
        self._last_status = rows
        return acted

    # -- host hooks -----------------------------------------------------------

    def tick(self, now: float) -> None:
        if now - self._last_run < float(
                self.get_module_option("sleep_interval", 5.0)):
            return
        self._last_run = now
        self.maybe_scale()

    def handle_command(self, cmd: dict) -> tuple[str, int]:
        if not self._last_status:
            self._last_status = self.recommendations()
        return json.dumps({"pools": self._last_status}), 0
