"""ceph-kvstore-tool analog: inspect/patch a KeyValueDB (LogDB) store —
the mon store and BlueStore-lite metadata both live in this format.

    list [PREFIX]            keys (and sizes)
    get PREFIX KEY           value hexdump to stdout
    set PREFIX KEY VALUEHEX  write a key
    rm PREFIX KEY            delete a key
    compact                  checkpoint the append log

Usage: python -m ceph_tpu.tools.kvstore_tool PATH CMD [...]
"""

from __future__ import annotations

import json
import sys

from ceph_tpu.objectstore.kv import LogDB


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        print(__doc__)
        return 2
    path, cmd, rest = argv[0], argv[1], argv[2:]
    db = LogDB(path)
    db.open()
    try:
        if cmd == "list":
            prefix = rest[0] if rest else None
            rows = [{"prefix": p, "key": k, "size": len(v)}
                    for p, k, v in db.iterate(prefix)]
            print(json.dumps(rows, indent=1))
        elif cmd == "get":
            v = db.get(rest[0], rest[1])
            if v is None:
                print("(absent)", file=sys.stderr)
                return 1
            print(v.hex())
        elif cmd == "set":
            t = db.get_transaction()
            t.set(rest[0], rest[1], bytes.fromhex(rest[2]))
            db.submit_transaction(t)
            print(json.dumps({"set": rest[1]}))
        elif cmd == "rm":
            t = db.get_transaction()
            t.rmkey(rest[0], rest[1])
            db.submit_transaction(t)
            print(json.dumps({"removed": rest[1]}))
        elif cmd == "compact":
            db.compact()
            print(json.dumps({"compacted": path}))
        else:
            print(__doc__)
            return 2
        return 0
    finally:
        db.close()


if __name__ == "__main__":
    raise SystemExit(main())
