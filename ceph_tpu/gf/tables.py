"""GF(2^8) table construction.

The field is GF(2)[x]/(x^8 + x^4 + x^3 + x^2 + 1), i.e. reduction polynomial 0x11d,
with generator 2 — the same field the reference's EC plugins compute in (ISA-L ec_base /
gf-complete w=8; see SURVEY.md §2.1).  Tables are built once at import from first
principles (repeated multiplication by the generator), not copied from anywhere.

Three table families:

* exp/log and the dense 256x256 product table ``mul_table()`` — used by the numpy
  oracle plugin and by tests as the ground truth.
* ``bit_matrix(coeff)`` — the TPU-kernel operand (see its docstring): the coding
  matrix as a (k*8, m*8) GF(2) matrix, consumed by the fused Pallas/XLA MXU kernels
  in ops.gf_kernel.
* ``nibble_bit_table(coeff)`` — the earlier nibble one-hot operand, kept for the
  round-1/2 kernel formulation's tests; superseded by bit_matrix for the kernels
  (4x narrower expansion, full MXU lane utilization).
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D
GF_ORDER = 256


@functools.lru_cache(maxsize=None)
def _exp_log() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    # periodic extension so gf_mul can index log[a]+log[b] without a modulo
    exp[255:510] = exp[0:255]
    log[0] = -1  # log of zero is undefined; callers must special-case
    return exp, log


def gf_exp() -> np.ndarray:
    """exp table (length 512, periodically extended)."""
    return _exp_log()[0].copy()


def gf_log() -> np.ndarray:
    """log table (length 256; log[0] = -1 sentinel)."""
    return _exp_log()[1].copy()


def gf_mul(a: int, b: int) -> int:
    exp, log = _exp_log()
    if a == 0 or b == 0:
        return 0
    return int(exp[log[a] + log[b]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    exp, log = _exp_log()
    return int(exp[(log[a] - log[b]) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    exp, log = _exp_log()
    return int(exp[255 - log[a]])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    exp, log = _exp_log()
    return int(exp[(int(log[a]) * (n % 255)) % 255])


@functools.lru_cache(maxsize=None)
def _mul_table() -> np.ndarray:
    exp, log = _exp_log()
    a = np.arange(256)
    la = log[a]
    t = exp[np.add.outer(la, la)]
    t[0, :] = 0
    t[:, 0] = 0
    t = t.astype(np.uint8)
    t.flags.writeable = False
    return t


def mul_table() -> np.ndarray:
    """Dense 256x256 product table M[a, b] = a*b in GF(2^8).  64 KiB, read-only."""
    return _mul_table()


def bit_matrix(coeff: np.ndarray) -> np.ndarray:
    """Flatten a GF(2^8) coding matrix into a GF(2) bit matrix.

    GF(2^8) multiplication by a constant c is GF(2)-linear in the bits of the
    input byte: c * x = XOR_s bit_s(x) * (c * 2^s).  A whole (m, k) coding
    matrix therefore becomes one 0/1 matrix W of shape (k*8, m*8):

        W[j*8 + s, i*8 + r] = bit r of (coeff[i, j] * 2^s)

    and encoding is ``bits(data) @ W mod 2`` — an integer matmul whose 8-wide
    bit expansion is 4x narrower than the nibble one-hot form, which is what
    lets the MXU kernel hit full lane utilization (see ops.gf_kernel).
    Plays the role ISA-L's ``ec_init_tables`` expansion plays for PSHUFB
    (reference: src/erasure-code/isa/ErasureCodeIsa.cc:118-130).
    """
    coeff = np.asarray(coeff, dtype=np.uint8)
    m, k = coeff.shape
    mt = _mul_table()
    powers = (1 << np.arange(8)).astype(np.uint8)              # 2^s
    prods = mt[coeff.T[:, None, :], powers[None, :, None]]     # (k, 8, m)
    bits = (prods[..., None] >> np.arange(8)) & 1              # (k, 8, m, 8)
    return bits.reshape(k * 8, m * 8).astype(np.uint8)


def nibble_bit_table(coeff: np.ndarray) -> np.ndarray:
    """Flatten a GF(2^8) coding matrix into the MXU bit-table operand.

    Parameters
    ----------
    coeff : (m, k) uint8 — coding matrix (parity i = sum_j coeff[i, j] * data[j]).

    Returns
    -------
    W : (k*32, m*8) uint8 with 0/1 entries.
        Row (j*32 + p*16 + n)   — data chunk j, nibble half p (0=low, 1=high), value n.
        Col (i*8 + r)           — parity chunk i, output bit r.
        W[row, col] = bit r of coeff[i, j] * (n << 4p).

    Because a data byte contributes exactly one low-nibble row and one high-nibble row,
    `one_hot @ W` accumulates at most 2k ones per output — exactly representable in
    bf16/int8 accumulation, and `& 1` of the integer sum is the GF(2) (XOR) result.
    """
    coeff = np.asarray(coeff, dtype=np.uint8)
    m, k = coeff.shape
    mt = _mul_table()
    # products[j, p, n, i] = coeff[i, j] * (n << 4p)
    nib_vals = np.stack([np.arange(16), np.arange(16) << 4])  # (2, 16)
    prods = mt[coeff.T[:, None, None, :], nib_vals[None, :, :, None]]  # (k, 2, 16, m)
    bits = (prods[..., None] >> np.arange(8)) & 1  # (k, 2, 16, m, 8)
    return bits.reshape(k * 32, m * 8).astype(np.uint8)
