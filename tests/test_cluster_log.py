"""Central cluster log (LogMonitor + MLog analogs): daemons clog to
every mon, each mon persists and serves `ceph log last`, and a
kill/recover episode is reconstructible from the log alone."""

from __future__ import annotations

import json
import time

from ceph_tpu.common.clog import ClusterLogClient, LogStore, PRIO_WARN
from ceph_tpu.objectstore.kv import MemDB
from ceph_tpu.tools.vstart import MiniCluster


def _wait(pred, timeout=30.0, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_logstore_dedup_trim_and_filters():
    db = MemDB()
    store = LogStore(db)
    ents = [{"stamp": 1.0 + i, "seq": i + 1, "prio": (i % 5),
             "channel": "cluster", "message": f"m{i}"}
            for i in range(10)]
    store.append("osd.1", ents)
    store.append("osd.1", ents)      # resend: must not duplicate
    assert len(store.last(100)) == 10
    # priority filter
    warn_up = store.last(100, min_prio=PRIO_WARN)
    assert all(e["prio"] >= PRIO_WARN for e in warn_up)
    # trim keeps the newest CAP entries
    store.CAP = 6
    store.append("osd.2", [{"stamp": 50.0, "seq": 1, "prio": 1,
                            "channel": "cluster", "message": "new"}])
    rows = store.last(100)
    assert len(rows) == 6
    assert rows[-1]["message"] == "new"
    assert rows[0]["stamp"] >= 5.0   # oldest were trimmed


def test_story_reconstructible_from_log_last():
    c = MiniCluster(n_osds=3, ms_type="loopback",
                    heartbeats=True).start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=4, size=2)
        io = client.open_ioctx(pool)
        for i in range(12):
            io.write_full(f"obj-{i}", f"payload-{i}".encode() * 16)

        def log_messages():
            rc, out = client.mon_command({"prefix": "log last",
                                          "num": 200})
            assert rc == 0, out
            return [e["message"] for e in json.loads(out)]

        # boots were logged
        assert _wait(lambda: sum("boot" in m
                                 for m in log_messages()) >= 3)

        # kill an osd: the mon logs the down-marking; revive: boot +
        # pg recovery entries follow — the whole episode readable from
        # `ceph log last` alone
        c.kill_osd(2)
        assert _wait(
            lambda: any("osd.2 marked down" in m
                        for m in log_messages()), timeout=45.0), \
            log_messages()
        c.run_osd(2)
        assert _wait(
            lambda: any("osd.2 boot" in m
                        for m in log_messages()[-40:])), log_messages()
        assert _wait(
            lambda: any("recovered" in m for m in log_messages()),
            timeout=45.0), log_messages()

        # ordering: the down-marking precedes the recovery entries
        msgs = log_messages()
        down_i = next(i for i, m in enumerate(msgs)
                      if "osd.2 marked down" in m)
        rec_i = max(i for i, m in enumerate(msgs) if "recovered" in m)
        assert down_i < rec_i

        # operator-injected entry lands too
        rc, _ = client.mon_command({"prefix": "log",
                                    "message": "maintenance start"})
        assert rc == 0
        assert _wait(lambda: any("maintenance start" in m
                                 for m in log_messages()))

        # every surviving mon serves the same story (fan-out copies)
        for m in c.mons.values():
            entries = m.logstore.last(200)
            assert any("osd.2 marked down" in e["message"]
                       for e in entries)
    finally:
        c.stop()


def test_mgr_failover_logged():
    c = MiniCluster(n_osds=1, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(1)
        client = c.client(timeout=20.0)
        c.run_mgr(0)
        c.run_mgr(1)

        def messages():
            rc, out = client.mon_command({"prefix": "log last",
                                          "num": 100})
            return [e["message"] for e in json.loads(out)] \
                if rc == 0 else []

        assert _wait(lambda: any("mgr mgr.0 is now active" in m
                                 for m in messages()))
        c.kill_mgr(0)
        assert _wait(lambda: any(
            "mgr mgr.1 is now active (was mgr.0)" in m
            for m in messages()), timeout=40.0), messages()
    finally:
        c.stop()
