"""Registry-consistency lints (check family ``registry``).

Two sub-checks keyed by the codebase's two central registries:

* ``conf-key`` — every string-literal ``*.conf.get("key")`` /
  ``conf.get("key")`` / ``conf.set("key", ..)`` must name an option in
  ``common/config.py``'s table (``OPTIONS`` plus every
  ``register_options([Option(..)])`` call in the tree).  A typo'd key
  raises ``KeyError`` at runtime — on whatever rarely-exercised path
  reads it first.

* ``perf-counter`` — every counter mutation (``.inc/.dec/.tinc/
  .hinc(name)``, plus ``.set(name, v)`` on a ``perf``-named receiver)
  must name a counter registered via some ``PerfCountersBuilder``
  chain in the tree (an unregistered name raises ``KeyError`` inside
  the counter lock at runtime).  Membership is checked against the
  union of every declared set — object-precise matching is
  undecidable here, and a union miss is always a real bug.

* ``module-option`` — every ``get_module_option("mgr_*")`` and
  ``get_module_option("kernel_*")`` literal must ALSO be registered
  in common/config.py's option table: mgr-module knobs that mirror
  daemon-level options (the slo module's windows, the tenant-ledger
  knobs) stay discoverable through one registry instead of drifting
  into module-private names.

* ``doc-drift`` — every prometheus family name
  (``ceph_[a-z0-9_]+``) referenced in docs/OBSERVABILITY.md must be
  emitted by the exporter (a string literal — or an f-string
  prefix/suffix pair — in mgr/modules/prometheus.py), so the
  monitoring doc cannot document families a refactor renamed away.
"""

from __future__ import annotations

import ast
import os
import re

from ceph_tpu.analysis import Finding
from ceph_tpu.analysis.core import TreeIndex, name_chain

_MUTATORS = {"inc", "dec", "tinc", "hinc"}

#: get_module_option prefixes that must resolve in the option table
_MODULE_OPT_PREFIXES = ("mgr_", "kernel_")

#: a family reference, not a repo path: must not end in "_" (prefix
#: globs like ceph_scrub_* name a family SET, matched by their base),
#: and the ceph_tpu package name itself is excluded
_DOC_FAMILY_RE = re.compile(r"\bceph_[a-z0-9_]*[a-z0-9]\b")

#: exposition row suffixes a doc may name directly (the family base
#: name is what the exporter declares)
_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count")


def _option_names(index: TreeIndex) -> set:
    names: set = set()
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                ch = name_chain(node.func)
                if ch and ch[-1] == "Option" and node.args and \
                        isinstance(node.args[0], ast.Constant):
                    names.add(node.args[0].value)
    return names


def _registered_counters(index: TreeIndex) -> set:
    """Union of every counter name declared by a builder chain."""
    union: set = set()
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            # builder chains hang Attribute off a Call
            # (PerfCountersBuilder(..).add_u64("a").add_u64("b")), so
            # match on the method attribute alone, not a name chain
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("add_u64", "add_time_avg",
                                       "add_histogram") and \
                    node.args and isinstance(node.args[0],
                                             ast.Constant):
                union.add(node.args[0].value)
    return union


def _exporter_names(index: TreeIndex) -> tuple[set, list]:
    """(string literals, f-string (prefix, suffix) pairs) from the
    prometheus module — the vocabulary the doc-drift check matches
    family references against."""
    literals: set = set()
    fstrings: list = []
    for mod in index.modules.values():
        if not mod.modname.endswith("mgr.modules.prometheus"):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                literals.add(node.value)
            elif isinstance(node, ast.JoinedStr):
                parts = node.values
                prefix = parts[0].value if parts and isinstance(
                    parts[0], ast.Constant) else ""
                suffix = parts[-1].value if len(parts) > 1 and \
                    isinstance(parts[-1], ast.Constant) else ""
                if isinstance(prefix, str) and prefix:
                    fstrings.append((prefix, suffix
                                     if isinstance(suffix, str)
                                     else ""))
    return literals, fstrings


def _doc_drift(index: TreeIndex) -> list:
    doc = os.path.join(index.base, "docs", "OBSERVABILITY.md")
    try:
        with open(doc, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    literals, fstrings = _exporter_names(index)
    if not literals and not fstrings:
        return []   # exporter absent from the analyzed package

    def known(name: str) -> bool:
        cands = [name] + [name[:-len(s)] for s in _FAMILY_SUFFIXES
                          if name.endswith(s)]
        for c in cands:
            if c in literals:
                return True
            for pre, suf in fstrings:
                if c.startswith(pre) and c.endswith(suf):
                    return True
        return False

    findings = []
    seen: set = set()
    for lineno, line in enumerate(lines, 1):
        for name in _DOC_FAMILY_RE.findall(line):
            if name.startswith("ceph_tpu") or name in seen \
                    or known(name):
                continue
            seen.add(name)
            findings.append(Finding(
                "registry", "docs/OBSERVABILITY.md", lineno,
                "doc-drift",
                f"{name}: prometheus family referenced by the doc "
                f"but never emitted by mgr/modules/prometheus.py"))
    return findings


def check(index: TreeIndex):
    findings = []
    options = _option_names(index)
    counters = _registered_counters(index)
    findings.extend(_doc_drift(index))
    for relpath, mod in sorted(index.by_path.items()):
        if mod.modname.endswith("common.config"):
            continue     # the table itself (defaults, casts, errors)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = name_chain(node.func)
            if not chain or len(chain) < 2:
                continue
            tail = chain[-1]
            arg0 = node.args[0] if node.args else None
            literal = arg0.value if isinstance(arg0, ast.Constant) \
                and isinstance(getattr(arg0, "value", None), str) \
                else None
            if tail == "get_module_option" and literal is not None \
                    and literal.startswith(_MODULE_OPT_PREFIXES) \
                    and literal not in options:
                findings.append(Finding(
                    "registry", relpath, node.lineno, "module-option",
                    f"get_module_option({literal!r}): daemon-style "
                    f"knob not in common/config.py's option table"))
            elif tail in ("get", "set") and chain[-2] == "conf":
                if literal is not None and literal not in options:
                    findings.append(Finding(
                        "registry", relpath, node.lineno, "conf-key",
                        f"conf.{tail}({literal!r}): key not in "
                        f"common/config.py's option table "
                        f"(KeyError at runtime)"))
            elif literal is not None and (
                    tail in _MUTATORS
                    or (tail == "set" and "perf" in chain[:-1])):
                # counter mutation — receiver must not be a conf
                if chain[-2] == "conf":
                    continue
                if literal not in counters:
                    findings.append(Finding(
                        "registry", relpath, node.lineno,
                        "perf-counter",
                        f".{tail}({literal!r}): counter never "
                        f"registered by any PerfCountersBuilder chain "
                        f"(KeyError inside the counter lock)"))
    return findings
