"""Shared PG mapping service (osd.mapping.SharedPGMappingService):
oracle equality under random map churn, exact changed-PG deltas,
epoch-skip burst coalescing, the O(changed + local) OSD scan (scalar
pipeline calls stay flat across an epoch advance), and the
ceph_kernel_mapping_* prometheus families."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from ceph_tpu.crush import build_two_level_map
from ceph_tpu.ops import telemetry
from ceph_tpu.osd import OSDMap, PGPool, SharedPGMappingService
from ceph_tpu.osd.mapping import OSDMapMapping
from ceph_tpu.osd.osdmap import OSD_EXISTS, OSD_UP


def _base_map(hosts=3, per_host=3, epoch=2):
    crush, _root, rule = build_two_level_map(hosts, per_host)
    n = hosts * per_host
    m = OSDMap(crush=crush, epoch=epoch)
    m.set_max_osd(n)
    for o in range(n):
        m.mark_up(o)
    m.pools[1] = PGPool(pool_id=1, size=3, crush_rule=rule, pg_num=32)
    m.pools[2] = PGPool(pool_id=2, size=2, crush_rule=rule, pg_num=16)
    return m, rule


def _full_oracle(m: OSDMap) -> dict:
    return {(pid, pg): m.pg_to_up_acting_osds(pid, pg)
            for pid, pool in m.pools.items()
            for pg in range(pool.pg_num)}


def _churn(m: OSDMap, rng, rule: int) -> OSDMap:
    """One random epoch of churn: a NEW map (service contract: maps
    are immutable once published)."""
    new = m.copy()
    new.epoch = m.epoch + 1
    n = new.max_osd
    kind = int(rng.integers(0, 8))
    osd = int(rng.integers(0, n))
    if kind == 0:            # reweight
        new.osd_weight[osd] = int(rng.choice(
            (0, 0x4000, 0x8000, 0xC000, 0x10000)))
    elif kind == 1:          # down (state only)
        new.osd_state[osd] = new.osd_state[osd] & ~OSD_UP
    elif kind == 2:          # back up
        new.osd_state[osd] = OSD_EXISTS | OSD_UP
    elif kind == 3:          # primary affinity
        new.osd_primary_affinity[osd] = int(rng.choice(
            (0, 0x4000, 0x10000)))
    elif kind == 4:          # pg_temp inject / clear
        pid = int(rng.choice(list(new.pools)))
        pg = int(rng.integers(0, new.pools[pid].pg_num))
        if (pid, pg) in new.pg_temp:
            del new.pg_temp[(pid, pg)]
        else:
            new.pg_temp[(pid, pg)] = [osd, (osd + 1) % n]
    elif kind == 5:          # primary_temp inject / clear
        pid = int(rng.choice(list(new.pools)))
        pg = int(rng.integers(0, new.pools[pid].pg_num))
        if (pid, pg) in new.primary_temp:
            del new.primary_temp[(pid, pg)]
        else:
            new.primary_temp[(pid, pg)] = osd
    elif kind == 6:          # upmap pair inject / clear
        pid = int(rng.choice(list(new.pools)))
        pg = int(rng.integers(0, new.pools[pid].pg_num))
        if (pid, pg) in new.pg_upmap_items:
            del new.pg_upmap_items[(pid, pg)]
        else:
            frm = int(rng.integers(0, n))
            new.pg_upmap_items[(pid, pg)] = [(frm, (frm + 2) % n)]
    else:                    # pg_num growth (pool replaced wholesale)
        pid = int(rng.choice(list(new.pools)))
        old_pool = new.pools[pid]
        new.pools[pid] = PGPool(
            pool_id=pid, size=old_pool.size, crush_rule=rule,
            pg_num=old_pool.pg_num * 2, pgp_num=old_pool.pgp_num)
    return new


def test_shared_mapping_matches_oracle_under_churn():
    """Property test: after every random churn epoch (reweights, osd
    down/out, affinity, pg_num growth, upmap/pg_temp/primary_temp
    injection), (a) every get() equals the scalar oracle and (b) the
    changed-PG delta is EXACTLY the set of PGs whose oracle
    (up, up_primary, acting, acting_primary) moved."""
    rng = np.random.default_rng(1234)
    m, rule = _base_map()
    # scalar rebuild backend: identical cache/delta machinery without
    # paying a jit compile in the property loop (the device rebuild
    # path has its own test below)
    svc = SharedPGMappingService(backend="scalar")
    svc.update_to(m)
    oracle = _full_oracle(m)
    for (pid, pg), want in oracle.items():
        assert svc.lookup(m, pid, pg) == want
    for _ in range(12):
        new = _churn(m, rng, rule)
        upd = svc.update_to(new, from_epoch=m.epoch)
        new_oracle = _full_oracle(new)
        for (pid, pg), want in new_oracle.items():
            assert svc.lookup(new, pid, pg) == want, (pid, pg)
        exact = sorted(k for k, v in new_oracle.items()
                       if oracle.get(k) != v)
        assert not upd.full
        assert sorted(upd.changed) == exact
        m, oracle = new, new_oracle


def test_incremental_reuse_and_stats():
    """State-only churn reuses every pool table; weight churn
    recomputes; the MappingStats counters tell the story."""
    m, _rule = _base_map()
    svc = SharedPGMappingService(backend="scalar")
    st = telemetry.mapping_stats()
    d0 = st.dump()
    svc.update_to(m)
    # state-only epoch: all pools reused
    m2 = m.copy()
    m2.epoch = m.epoch + 1
    m2.osd_state[0] &= ~OSD_UP
    svc.update_to(m2, from_epoch=m.epoch)
    # weight epoch: pools sharing the rule recompute
    m3 = m2.copy()
    m3.epoch = m2.epoch + 1
    m3.osd_weight[1] = 0x8000
    svc.update_to(m3, from_epoch=m2.epoch)
    d = st.dump()
    assert d["epoch_updates"] - d0["epoch_updates"] == 3
    # epoch 2: both pools computed; epoch 3: both reused; epoch 4: both
    # recomputed (shared crush rule -> shared reachable set)
    assert d["pools_reused"] - d0["pools_reused"] == 2
    assert d["pools_recomputed"] - d0["pools_recomputed"] == 4
    assert d["cached_pools"] == 2


def test_epoch_skip_on_concurrent_burst(monkeypatch):
    """While one update computes, a burst of newer maps queues; only
    the NEWEST is ever computed (intermediates are skipped) and every
    waiter returns once the cache passes its epoch."""
    m, rule = _base_map()
    svc = SharedPGMappingService(backend="scalar")
    svc.update_to(m)
    orig = OSDMapMapping.update

    def slow_update(self, osdmap=None, engine=None):
        time.sleep(0.25)
        return orig(self, osdmap, engine)

    monkeypatch.setattr(OSDMapMapping, "update", slow_update)
    maps = [m]
    for _ in range(3):
        nm = maps[-1].copy()
        nm.epoch = maps[-1].epoch + 1
        nm.osd_weight[len(maps) % nm.max_osd] = 0x8000
        maps.append(nm)
    st = telemetry.mapping_stats()
    before = st.dump()
    threads = [threading.Thread(target=svc.update_to, args=(mm,),
                                daemon=True) for mm in maps[1:]]
    threads[0].start()
    time.sleep(0.05)           # let the first update begin computing
    for t in threads[1:]:
        t.start()
    for t in threads:
        t.join(timeout=30)
    after = st.dump()
    assert svc.epoch == maps[-1].epoch
    # first target computed + the newest; the middle epoch was skipped
    assert after["epoch_updates"] - before["epoch_updates"] == 2
    assert after["epoch_skips"] - before["epoch_skips"] >= 1
    # the skipped epoch's tables were never built
    assert maps[2].epoch not in svc._tables
    # ...but its mappings are still correct (scalar-oracle fallback)
    pid = 1
    assert (svc.lookup(maps[2], pid, 0)
            == maps[2].pg_to_up_acting_osds(pid, 0))


def test_delta_clamped_to_caller_epoch():
    """A reader whose map is OLDER than the cache head must get a
    delta ending at ITS epoch — a change that reverted by the head is
    visible in the reader's map and must not be masked by the
    head-spanning union — and a reader inside a skipped jump gets a
    full rescan, never a wrong delta."""
    m, _rule = _base_map()
    svc = SharedPGMappingService(backend="scalar")
    svc.update_to(m)
    m2 = m.copy()
    m2.epoch = m.epoch + 1
    m2.osd_weight[0] = 0x8000
    m3 = m2.copy()
    m3.epoch = m2.epoch + 1
    m3.osd_weight[0] = 0x10000        # revert: m3 mappings == m's
    svc.update_to(m2, from_epoch=m.epoch)
    svc.update_to(m3, from_epoch=m2.epoch)
    # reader still at m asking about m2 (cache head is m3)
    upd = svc.update_to(m2, from_epoch=m.epoch)
    assert upd.epoch_to == m2.epoch
    exact = sorted(
        (pid, pg) for pid, pool in m2.pools.items()
        for pg in range(pool.pg_num)
        if m.pg_to_up_acting_osds(pid, pg)
        != m2.pg_to_up_acting_osds(pid, pg))
    assert not upd.full
    assert sorted(upd.changed) == exact
    assert exact        # the revert scenario really changed something
    # reader at an epoch INSIDE a skipped jump: only full is safe
    m5 = m3.copy()
    m5.epoch = m3.epoch + 2           # jump over m3.epoch+1
    m5.osd_weight[1] = 0x8000
    svc.update_to(m5, from_epoch=m3.epoch)
    m4 = m3.copy()
    m4.epoch = m3.epoch + 1
    upd4 = svc.update_to(m4, from_epoch=m3.epoch)
    assert upd4.full


def test_same_epoch_map_copy_binds_to_cache():
    """Another consumer's decode of the same published epoch (equal
    content, different object) binds to the shared tables via the
    signature check — cross-consumer sharing — while a content-
    DIVERGENT map at the same epoch is rejected and served by the
    oracle."""
    m, _rule = _base_map()
    svc = SharedPGMappingService(backend="scalar")
    svc.update_to(m)
    st = telemetry.mapping_stats()
    twin = m.copy()                   # same epoch, same content
    before = st.dump()
    for pg in range(8):
        assert svc.lookup(twin, 1, pg) == twin.pg_to_up_acting_osds(1, pg)
    after = st.dump()
    assert after["lookups"] - before["lookups"] == 8
    assert after["lookup_fallbacks"] == before["lookup_fallbacks"]
    alien = m.copy()                  # same epoch, DIFFERENT weights
    alien.osd_weight[0] = 0x1234
    before = st.dump()
    for pg in range(8):
        assert svc.lookup(alien, 1, pg) \
            == alien.pg_to_up_acting_osds(1, pg)
    after = st.dump()
    assert after["lookup_fallbacks"] - before["lookup_fallbacks"] == 8


def test_warm_foreign_map_never_poisons_online_deltas():
    """An offline warm() with a foreign map (what-if run at an
    arbitrary epoch number) must not leak wrong deltas to online
    consumers: the chain is invalidated, the published epoch never
    regresses, and the online reader gets a FULL rescan with
    oracle-correct reads."""
    live, _rule = _base_map()
    svc = SharedPGMappingService(backend="scalar")
    svc.update_to(live)
    foreign = live.copy()
    foreign.epoch = live.epoch + 5
    foreign.osd_weight[2] = 0x2000
    svc.warm(foreign)
    assert svc.epoch == foreign.epoch      # monotonic ratchet
    live2 = live.copy()
    live2.epoch = live.epoch + 1
    live2.osd_state[1] &= ~OSD_UP
    upd = svc.update_to(live2, from_epoch=live.epoch)
    assert upd.full                        # never a garbage delta
    for pid, pool in live2.pools.items():
        for pg in range(pool.pg_num):
            assert svc.lookup(live2, pid, pg) \
                == live2.pg_to_up_acting_osds(pid, pg)


def test_failed_update_recovers_with_exact_delta(monkeypatch):
    """An update that dies mid-compute (device error, future timeout)
    must leave the service consistent: the exception propagates, a
    retry — including from OTHER waiters — makes progress (no
    livelock), and the retry's delta is computed against the REAL old
    tables, not the failed attempt's half-state."""
    m, _rule = _base_map()
    svc = SharedPGMappingService(backend="scalar")
    svc.update_to(m)
    orig = OSDMapMapping.update
    boom = {"on": True}

    def flaky(self, osdmap=None, engine=None):
        if boom["on"]:
            boom["on"] = False        # fail exactly once
            raise RuntimeError("device fell over")
        return orig(self, osdmap, engine)

    monkeypatch.setattr(OSDMapMapping, "update", flaky)
    m2 = m.copy()
    m2.epoch = m.epoch + 1
    m2.osd_weight[0] = 0x8000
    m2.osd_state[3] &= ~OSD_UP        # a state change the delta must see
    with pytest.raises(RuntimeError):
        svc.update_to(m2, from_epoch=m.epoch)
    assert svc.epoch == m.epoch       # nothing half-installed
    upd = svc.update_to(m2, from_epoch=m.epoch)   # retry succeeds
    assert svc.epoch == m2.epoch
    assert not upd.full
    exact = sorted(
        (pid, pg) for pid, pool in m2.pools.items()
        for pg in range(pool.pg_num)
        if m.pg_to_up_acting_osds(pid, pg)
        != m2.pg_to_up_acting_osds(pid, pg))
    assert sorted(upd.changed) == exact


def test_device_rebuild_path_rides_dispatch_engine():
    """The tpu backend submits per-pool remaps through the context's
    dispatch engine and the result is bit-identical to the oracle."""
    from ceph_tpu.common.context import CephTpuContext

    ctx = CephTpuContext("mapping-test")
    ctx.conf.set("osdmap_mapping_min_pgs", 0)   # force the device path
    m, _rule = _base_map(hosts=2, per_host=2, epoch=2)
    m.pools = {1: PGPool(pool_id=1, size=2,
                         crush_rule=m.pools[1].crush_rule, pg_num=16)}
    svc = ctx.mapping_service()
    d0 = telemetry.dispatch_stats().dump()
    svc.update_to(m)
    d1 = telemetry.dispatch_stats().dump()
    assert d1["batches"] > d0["batches"]        # remap rode the engine
    for pg in range(16):
        assert svc.lookup(m, 1, pg) == m.pg_to_up_acting_osds(1, pg)
    # weight change: recompute rides the engine again, still exact
    m2 = m.copy()
    m2.epoch = 3
    m2.osd_weight[0] = 0x8000
    upd = svc.update_to(m2, from_epoch=2)
    assert not upd.full
    exact = [(1, pg) for pg in range(16)
             if m.pg_to_up_acting_osds(1, pg)
             != m2.pg_to_up_acting_osds(1, pg)]
    assert sorted(upd.changed) == sorted(exact)
    eng = ctx._dispatch
    if eng is not None:
        eng.stop()


def _count_scan_scalar_calls(monkeypatch):
    """Count scalar pg_to_up_acting_osds calls, attributing those made
    from inside an OSD's _scan_pgs (the map-consumption path the
    shared cache is supposed to eliminate) separately from incidental
    callers (per-second stats ticks hitting the update window)."""
    import sys

    calls = {"scan": 0, "total": 0}
    orig = OSDMap.pg_to_up_acting_osds

    def counting(self, pool_id, ps):
        calls["total"] += 1
        f = sys._getframe(1)
        for _ in range(12):
            if f is None:
                break
            if f.f_code.co_name == "_scan_pgs":
                calls["scan"] += 1
                break
            f = f.f_back
        return orig(self, pool_id, ps)

    monkeypatch.setattr(OSDMap, "pg_to_up_acting_osds", counting)
    return calls


def test_scan_pgs_scalar_calls_stay_flat_across_epoch(monkeypatch):
    """Acceptance gate: with osdmap_mapping_shared on, an epoch advance
    over a large pool does NOT re-run the scalar pipeline per PG inside
    _scan_pgs — the OSDs consume the map from the shared cache (changed
    + local PGs, served by cached-raw pipeline tails), where the seed
    walked every PG scalar on every OSD (3 x 64 here)."""
    from ceph_tpu.tools.vstart import MiniCluster

    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client()
        pool = c.create_pool(client, pg_num=64, size=3)
        client.open_ioctx(pool).write_full("warm", b"x")
        st = telemetry.mapping_stats()
        before = st.dump()
        calls = _count_scan_scalar_calls(monkeypatch)
        res, _ = client.mon_command(
            {"prefix": "osd reweight", "id": "1", "weight": "0.5"})
        assert res == 0
        epoch = c.mon.osdmap.epoch
        c.wait_for_epoch(epoch)
        # wait_for_epoch returns once daemons SWAPPED the map; the
        # cache update + delta scan run right after — poll for the
        # scans' cache reads to land (1-core hosts need a moment)
        deadline = time.time() + 10
        while (st.dump()["lookups"] <= before["lookups"]
               and time.time() < deadline):
            time.sleep(0.05)
        time.sleep(0.2)
        after = st.dump()
        # seed behavior: every OSD walks every PG scalar in _scan_pgs
        # (>= 3*64 for the big pool alone).  Shared cache: zero — any
        # residual would be a sparse oracle fallback.
        assert calls["scan"] < 32, calls
        # ...and the scans really read the cache (lookup hits grew)
        assert after["lookups"] > before["lookups"]
        # the cluster still works after the delta-driven scan
        io = client.open_ioctx(pool)
        io.write_full("after", b"y")
        assert io.read("after") == b"y"
    finally:
        c.stop()


def test_epoch_burst_e2e_skip_and_peering():
    """A partitioned OSD misses a burst of epochs, then catches up via
    one subscription renewal (the mon ships the whole inc chain in ONE
    message): the shared service jumps straight to the newest epoch —
    the intermediate maps are never computed (epoch-skips) — while
    peering still converges and IO proceeds."""
    from ceph_tpu.tools.vstart import MiniCluster

    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(3)
        client = c.client()
        pool = c.create_pool(client, pg_num=32, size=3)
        victim = c.osds[2]
        orig_handle = victim._handle_map
        dropping = {"on": True}

        def flaky_handle(msg):
            if dropping["on"]:
                return          # partitioned: map pushes are lost
            orig_handle(msg)

        drops = {"n": 0}

        def flaky_counting(msg, _orig=flaky_handle):
            if dropping["on"]:
                drops["n"] += 1
            _orig(msg)

        # install the interceptor BEFORE reading e0: a push landing in
        # between would advance the epoch past the frozen baseline
        victim._handle_map = flaky_counting
        e0 = victim.osdmap.epoch
        for i, w in enumerate(("0.9", "0.8", "0.7", "0.6")):
            res, _ = client.mon_command(
                {"prefix": "osd reweight", "id": str(i % 2),
                 "weight": w})
            assert res == 0
        target = c.mon.osdmap.epoch
        assert target - e0 >= 4
        # drain the in-flight pushes INTO the partition before healing:
        # a push sent during the outage but delivered after the heal
        # would advance the victim piecemeal and shrink the one-jump
        # skip count this test is about (wait for the drop counter to
        # go quiet, not a fixed sleep — lockdep runs are slower)
        quiet = time.time() + 0.5
        deadline = time.time() + 10
        while time.time() < deadline and time.time() < quiet:
            n = drops["n"]
            time.sleep(0.1)
            if drops["n"] != n:
                quiet = time.time() + 0.5
        assert victim.osdmap.epoch == e0
        st = telemetry.mapping_stats()
        before = st.dump()
        # heal the partition; the renewal carries our stale epoch and
        # the mon answers with every missing incremental in one message
        dropping["on"] = False
        victim._renew_map_subscription(time.time(), force=True)
        deadline = time.time() + 10
        while victim.osdmap.epoch < target and time.time() < deadline:
            time.sleep(0.05)
        assert victim.osdmap.epoch >= target
        time.sleep(0.3)
        after = st.dump()
        # the jump e0 -> target computed ONE epoch; the intermediates
        # were skipped, never built
        assert after["epoch_skips"] - before["epoch_skips"] \
            >= target - e0 - 1
        svc = victim.ctx.mapping_service()
        for e in range(e0 + 1, target):
            assert e not in svc._tables
        # peering converged across the jump: IO lands on all members
        io = client.open_ioctx(pool)
        for i in range(8):
            io.write_full(f"burst-{i}", b"z" * 64)
            assert io.read(f"burst-{i}") == b"z" * 64
    finally:
        c.stop()


def test_mapping_families_in_prometheus_scrape():
    """ceph_kernel_mapping_* families appear in the mgr scrape with
    valid exposition structure."""
    from test_kernel_telemetry import _scrape, parse_exposition

    fams = parse_exposition(_scrape())
    for fam in ("ceph_kernel_mapping_epoch_updates_total",
                "ceph_kernel_mapping_epoch_skips_total",
                "ceph_kernel_mapping_pools_recomputed_total",
                "ceph_kernel_mapping_pools_reused_total",
                "ceph_kernel_mapping_lookups_total",
                "ceph_kernel_mapping_lookup_fallbacks_total",
                "ceph_kernel_mapping_cached_pgs"):
        assert fam in fams, fam
        assert fams[fam]["type"] in ("counter", "gauge")
    for fam in ("ceph_kernel_mapping_update_latency_seconds",
                "ceph_kernel_mapping_changed_pgs"):
        assert fam in fams, fam
        assert fams[fam]["type"] == "histogram"


def test_admin_socket_dump_mapping_stats():
    """Every context serves dump_mapping_stats."""
    from ceph_tpu.common.context import CephTpuContext

    ctx = CephTpuContext("mapping-admin-test")
    out = ctx.admin.execute("dump_mapping_stats")
    assert "epoch_updates" in out
    assert "changed_pgs" in out


def test_mapping_shared_off_uses_scalar_path(monkeypatch):
    """The osdmap_mapping_shared=False fallback: consumers run the
    scalar pipeline exactly as the seed did."""
    from ceph_tpu.tools.vstart import MiniCluster

    c = MiniCluster(n_osds=2, ms_type="loopback").start()
    try:
        c.wait_for_osd_count(2)
        for osd in c.osds.values():
            osd.ctx.conf.set("osdmap_mapping_shared", False)
        client = c.client()
        client.ctx.conf.set("osdmap_mapping_shared", False)
        calls = _count_scan_scalar_calls(monkeypatch)
        pool = c.create_pool(client, pg_num=16, size=2)
        io = client.open_ioctx(pool)
        io.write_full("obj", b"scalar")
        assert io.read("obj") == b"scalar"
        assert calls["scan"] >= 16   # full scalar scans are back
    finally:
        c.stop()
