"""Mon central config-db + structured health checks
(mon/ConfigMonitor.h:13 and mon/HealthMonitor.h:22 analogs): `ceph
config set` persists through Paxos and pushes to live daemons via the
config observer machinery; health checks are structured and transition
with cluster state.
"""

from __future__ import annotations

import json
import time

import pytest

from ceph_tpu.tools.vstart import MiniCluster


def _health(client, detail=False):
    rc, out = client.mon_command(
        {"prefix": "health detail" if detail else "health"})
    assert rc == 0, out
    return json.loads(out)


def _checks(h):
    return {c["check"] for c in h["checks"]}


def test_config_set_propagates_to_live_osd():
    c = MiniCluster(n_osds=3).start()
    try:
        c.wait_for_osd_count(3)
        client = c.client()
        # default before the change
        assert int(c.osds[1].ctx.conf.get("osd_recovery_max_active")) != 7
        rc, out = client.mon_command({
            "prefix": "config set", "who": "osd",
            "name": "osd_recovery_max_active", "value": "7"})
        assert rc == 0, out
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(int(o.ctx.conf.get("osd_recovery_max_active")) == 7
                   for o in c.osds.values()):
                break
            time.sleep(0.05)
        for o in c.osds.values():
            assert int(o.ctx.conf.get("osd_recovery_max_active")) == 7

        # per-daemon section outranks the type section
        rc, _ = client.mon_command({
            "prefix": "config set", "who": "osd.1",
            "name": "osd_recovery_max_active", "value": "9"})
        assert rc == 0
        deadline = time.time() + 10
        while time.time() < deadline:
            if int(c.osds[1].ctx.conf.get("osd_recovery_max_active")) == 9:
                break
            time.sleep(0.05)
        assert int(c.osds[1].ctx.conf.get("osd_recovery_max_active")) == 9
        assert int(c.osds[0].ctx.conf.get("osd_recovery_max_active")) == 7

        # config get / dump read back the persisted db
        rc, out = client.mon_command({
            "prefix": "config get", "who": "osd",
            "name": "osd_recovery_max_active"})
        assert rc == 0 and out == "7"
        rc, out = client.mon_command({"prefix": "config dump"})
        assert json.loads(out)["osd.1"]["osd_recovery_max_active"] == "9"

        # rm retracts; daemons fall back to the type section / default
        rc, _ = client.mon_command({
            "prefix": "config rm", "who": "osd.1",
            "name": "osd_recovery_max_active"})
        assert rc == 0
        deadline = time.time() + 10
        while time.time() < deadline:
            if int(c.osds[1].ctx.conf.get("osd_recovery_max_active")) == 7:
                break
            time.sleep(0.05)
        assert int(c.osds[1].ctx.conf.get("osd_recovery_max_active")) == 7
    finally:
        c.stop()


def test_config_survives_mon_restart(tmp_path):
    c = MiniCluster(n_osds=1, base_path=str(tmp_path)).start()
    try:
        c.wait_for_osd_count(1)
        client = c.client()
        rc, _ = client.mon_command({
            "prefix": "config set", "who": "global",
            "name": "osd_heartbeat_interval", "value": "2.5"})
        assert rc == 0
        c.kill_mon(0)
        c.run_mon(0)
        # the restarted mon binds a fresh port; dial it anew (clients
        # normally learn new monmaps from surviving quorum members)
        rc, out = -1, ""
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                c2 = c.client()
                rc, out = c2.mon_command({
                    "prefix": "config get", "who": "global",
                    "name": "osd_heartbeat_interval"})
                if rc == 0 and out == "2.5":
                    break
            except (TimeoutError, OSError):
                pass
            time.sleep(0.2)
        assert rc == 0 and out == "2.5"
    finally:
        c.stop()


def test_health_osd_down_and_pg_degraded_transitions():
    c = MiniCluster(n_osds=3, heartbeats=True).start()
    try:
        c.wait_for_osd_count(3)
        client = c.client(timeout=20.0)
        pool = c.create_pool(client, pg_num=8, size=3)
        io = client.open_ioctx(pool)
        for i in range(20):
            io.write_full(f"h{i}", b"data" * 100)
        deadline = time.time() + 20
        while time.time() < deadline:
            if _health(client)["status"] == "HEALTH_OK":
                break
            time.sleep(0.2)
        assert _health(client)["status"] == "HEALTH_OK"

        c.kill_osd(2)
        deadline = time.time() + 30
        seen = set()
        while time.time() < deadline:
            h = _health(client)
            seen |= _checks(h)
            if "OSD_DOWN" in seen:
                break
            time.sleep(0.3)
        assert "OSD_DOWN" in seen
        hd = _health(client, detail=True)
        dd = next(ch for ch in hd["checks"] if ch["check"] == "OSD_DOWN")
        assert "osd.2 is down" in dd["detail"]

        # revive: health returns to OK (degraded clears as recovery ends)
        c.run_osd(2)
        deadline = time.time() + 40
        while time.time() < deadline:
            if _health(client)["status"] == "HEALTH_OK":
                break
            time.sleep(0.3)
        assert _health(client)["status"] == "HEALTH_OK"
    finally:
        c.stop()


def test_health_mon_down():
    c = MiniCluster(n_osds=1, n_mons=3).start()
    try:
        c.wait_for_osd_count(1)
        client = c.client(timeout=20.0)
        assert _health(client)["status"] == "HEALTH_OK"
        c.kill_mon(2)
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                h = _health(client)
            except (TimeoutError, OSError):
                time.sleep(0.3)
                continue
            if "MON_DOWN" in _checks(h):
                break
            time.sleep(0.3)
        assert "MON_DOWN" in _checks(_health(client))
    finally:
        c.stop()


def test_auth_key_management(tmp_path):
    """AuthMonitor analog: get-or-create issues a stable random key,
    replicated through Paxos, surviving mon restart; ls/del round out
    the table."""
    c = MiniCluster(n_osds=1, base_path=str(tmp_path)).start()
    try:
        c.wait_for_osd_count(1)
        client = c.client()
        rc, kr = client.mon_command({"prefix": "auth get-or-create",
                                     "entity": "client.alice"})
        assert rc == 0 and kr.startswith("[client.alice]"), kr
        key = kr.split("key = ")[1].strip()
        # idempotent: same key back
        rc, kr2 = client.mon_command({"prefix": "auth get-or-create",
                                      "entity": "client.alice"})
        assert rc == 0 and kr2 == kr
        rc, pk = client.mon_command({"prefix": "auth print-key",
                                     "entity": "client.alice"})
        assert rc == 0 and pk == key
        client.mon_command({"prefix": "auth get-or-create",
                            "entity": "osd.5"})
        rc, out = client.mon_command({"prefix": "auth ls"})
        assert rc == 0 and json.loads(out) == ["client.alice", "osd.5"]

        # persists across mon restart
        c.kill_mon(0)
        c.run_mon(0)
        deadline = time.time() + 15
        pk2 = None
        while time.time() < deadline:
            try:
                rc, pk2 = c.client().mon_command(
                    {"prefix": "auth print-key", "entity": "client.alice"})
                if rc == 0:
                    break
            except (TimeoutError, OSError):
                pass
            time.sleep(0.2)
        assert pk2 == key

        c2 = c.client()
        rc, _ = c2.mon_command({"prefix": "auth del",
                                "entity": "osd.5"})
        assert rc == 0
        rc, out = c2.mon_command({"prefix": "auth ls"})
        assert json.loads(out) == ["client.alice"]
        rc, _ = c2.mon_command({"prefix": "auth get", "entity": "osd.5"})
        assert rc == -2
    finally:
        c.stop()
