"""DCN story: the cluster data path across OS-process boundaries.

The reference scales past one host with NCCL-less TCP messengers; the
TPU-native equivalent (SURVEY.md §5) is a two-plane design:

* data plane — `jax.distributed` multi-controller runtime: each process
  owns its local devices (ICI domain), XLA collectives ride DCN between
  processes.  One global `Mesh` spans every device of every process and
  `jit` over sharded global arrays inserts the cross-process collectives
  exactly as it inserts ICI ones inside a process.
* control plane — the same TCP messenger stack the daemons use
  (`msg/event_tcp.py`), carrying typed messages between processes.

`run_dcn_pair(n)` is the executable proof: it spawns TWO worker
processes, each with n/2 virtual CPU devices; the workers build the
global 2-process mesh, run the batched GF(2^8) erasure encode over
globally-sharded stripes with a cross-process reduction, verify the
result against the host oracle, and then cross-check their digests over
a TCP messenger session.  `__graft_entry__.dryrun_multichip` invokes it,
so the driver exercises the multi-process path on every round.

`pick_stack(peer_process, my_process)` is the SURVEY §5 routing rule the
messenger family uses: same process -> "ici" (device-buffer handoff),
different process -> "async" (TCP/DCN).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time


def pick_stack(peer_process: int, my_process: int) -> str:
    """Messenger stack per peer: ICI inside a process, TCP across."""
    return "ici" if peer_process == my_process else "async"


_DISTRIBUTED = {"params": None}


def init_distributed(coordinator: str | None, n_processes: int,
                     process_index: int) -> None:
    """Idempotent jax.distributed bring-up — the deployment-mode entry
    CephTpuContext(process_index=, n_processes=, coordinator=) calls.
    Must run before any jax backend initialization in the process;
    after it, jax.devices() spans every process and the context's
    kernel mesh is the GLOBAL mesh (engines place their own flushes
    over the process-local submesh).  A repeat call with the SAME
    topology is a no-op; a different coordinator/topology raises loudly
    here instead of failing far away on a mismatched device count
    (jax.distributed can only initialize once per process)."""
    if coordinator is None:
        raise ValueError(
            "multi-process CephTpuContext needs a coordinator address")
    params = (coordinator, int(n_processes), int(process_index))
    prev = _DISTRIBUTED["params"]
    if prev is not None:
        if prev != params:
            raise RuntimeError(
                f"jax.distributed already initialized as {prev}; "
                f"cannot re-initialize as {params}")
        return
    import jax
    jax.distributed.initialize(coordinator, n_processes, process_index)
    _DISTRIBUTED["params"] = params


def run_dcn_pair(n_devices: int = 8, timeout: float = 240.0,
                 retries: int = 1) -> None:
    """Spawn the two-process mesh proof; raises on any failure.
    One retry absorbs environment flakes (coordinator port races,
    jax startup stalls on a loaded host) — the assertion content is
    deterministic, only the process orchestration is not."""
    last: Exception | None = None
    for _attempt in range(retries + 1):
        try:
            _run_dcn_pair_once(n_devices, timeout)
            return
        except (RuntimeError, TimeoutError) as e:
            last = e
    raise last


def run_engine_pair(n_devices: int = 8, timeout: float = 240.0,
                    retries: int = 1) -> None:
    """The DEPLOYMENT-MODE proof: two OS processes, each constructing a
    CephTpuContext in multi-controller mode, sharing ONE global mesh.
    Each process drives an EC write workload through its mesh-sharded
    dispatch engine (flushes fan out over its local submesh — the ICI
    domain), runs one global-mesh collective over DCN, and cross-checks
    digests over the TCP messenger stack pick_stack routes to.  Raises
    on any failure."""
    last: Exception | None = None
    for _attempt in range(retries + 1):
        try:
            _run_pair_once(n_devices, timeout, engine=True)
            return
        except (RuntimeError, TimeoutError) as e:
            last = e
    raise last


def _run_dcn_pair_once(n_devices: int, timeout: float) -> None:
    _run_pair_once(n_devices, timeout, engine=False)


def _run_pair_once(n_devices: int, timeout: float,
                   engine: bool = False) -> None:
    assert n_devices >= 2 and n_devices % 2 == 0, \
        "need an even global device count of at least 2"
    from ceph_tpu.common import free_port
    coord = f"127.0.0.1:{free_port()}"
    ms_port = free_port()
    procs = []
    env = dict(os.environ)
    # the workers configure their own platform; a parent-forced platform
    # (e.g. the test conftest's cpu pin) must not leak conflicting
    # device counts into them
    env.pop("XLA_FLAGS", None)
    for pid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.parallel.dcn",
             "--coordinator", coord, "--num-processes", "2",
             "--process-id", str(pid),
             "--local-devices", str(n_devices // 2),
             "--ms-port", str(ms_port)]
            + (["--engine"] if engine else []),
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    deadline = time.time() + timeout
    outs = []
    for p in procs:
        remaining = max(1.0, deadline - time.time())
        try:
            out, _ = p.communicate(timeout=remaining)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise TimeoutError("dcn worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"dcn worker {pid} failed (rc={p.returncode}):\n{out}")


def _engine_worker(args) -> int:
    """Deployment-mode worker (run_engine_pair): a CephTpuContext in
    multi-controller mode, its mesh-sharded dispatch engine driven by a
    real EC write workload, one global-mesh collective, and a messenger
    digest cross-check on the stack pick_stack routes to."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ceph_tpu  # noqa: F401  (x64 for the GF kernels)
    from ceph_tpu.common.context import CephTpuContext

    # the context IS the deployment entry: it initializes
    # jax.distributed and hands every engine the global mesh
    ctx = CephTpuContext(f"dcn-engine{args.process_id}",
                         process_index=args.process_id,
                         n_processes=args.num_processes,
                         coordinator=args.coordinator)
    n_global = args.num_processes * args.local_devices
    assert len(jax.devices()) == n_global, (len(jax.devices()), n_global)
    mesh = ctx.kernel_mesh()
    assert mesh is not None and int(mesh.size) == n_global, mesh
    eng = ctx.dispatch_engine()
    place_mesh = eng.placement_mesh()
    assert place_mesh is not None \
        and int(place_mesh.size) == args.local_devices, place_mesh

    # EC write workload: both processes push the SAME deterministic
    # ops through their OWN engine (each flush shards over the local
    # submesh), so the parity digests must agree bit-exactly
    from ceph_tpu.ec import registry_instance
    from ceph_tpu.ops.gf_kernel import ec_encode_ref
    k, m, chunk = 4, 2, 256
    codec = registry_instance().factory(
        "isa", {"technique": "cauchy", "k": str(k), "m": str(m)})
    coding = codec.generator[k:]
    rng = np.random.default_rng(0)
    ops = [rng.integers(0, 256, (s, k, chunk), dtype=np.uint8)
           for s in (3, 8, 5, args.local_devices * 4)]
    futs = [codec.submit_chunks(eng, d) for d in ops]
    digest = 0
    for d, f in zip(ops, futs):
        got = np.asarray(f.result(timeout=120))
        want = ec_encode_ref(coding, d)
        assert (got == want).all(), "engine parity mismatch vs oracle"
        digest = (digest + int(got.astype(np.int64).sum())) & 0xFFFFFFFF
    st = eng.stats
    assert st.sharded_flushes >= 1, "no flush actually sharded"
    assert st.mesh_devices == n_global, st.mesh_devices

    # global-mesh collective: every process contributes its local rows
    # of one global array; the reduction rides DCN between processes
    from jax.sharding import NamedSharding, PartitionSpec as P
    rows = np.full((args.local_devices, 8), args.process_id + 1,
                   dtype=np.int64)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(tuple(mesh.axis_names), None)), rows)
    total = int(jax.jit(jnp.sum)(arr))
    want_total = 8 * args.local_devices * sum(
        p + 1 for p in range(args.num_processes))
    assert total == want_total, (total, want_total)

    # control plane: digests cross the process boundary on the stack
    # the deployment rule picks (tcp/async between processes)
    stack = ctx.messenger_stack_for(1 - args.process_id)
    assert stack == "async", stack
    assert ctx.messenger_stack_for(args.process_id) == "ici"
    from ceph_tpu.messages import MMonCommand, MMonCommandAck
    from ceph_tpu.msg.messenger import Dispatcher, EntityName, Messenger
    result = {}
    if args.process_id == 0:
        class D(Dispatcher):
            def ms_dispatch(self, msg):
                if isinstance(msg, MMonCommand):
                    ok = msg.cmd.get("digest") == digest
                    msg.connection.send_message(MMonCommandAck(
                        tid=msg.tid, result=0 if ok else -1,
                        output=str(digest)))
                    result["peer"] = msg.cmd
                    return True
                return False

        ms = Messenger.create(EntityName("mon", 0), stack)
        ms.add_dispatcher_tail(D())
        ms.bind(f"127.0.0.1:{args.ms_port}")
        ms.start()
        deadline = _time.time() + 60
        while "peer" not in result and _time.time() < deadline:
            _time.sleep(0.05)
        ms.shutdown()
        assert result.get("peer", {}).get("digest") == digest, result
    else:
        acked = {}

        class D(Dispatcher):
            def ms_dispatch(self, msg):
                if isinstance(msg, MMonCommandAck):
                    acked["rc"] = msg.result
                    acked["digest"] = msg.output
                    return True
                return False

        ms = Messenger.create(EntityName("osd", 1), stack)
        ms.add_dispatcher_tail(D())
        ms.start()
        con = ms.connect_to(f"127.0.0.1:{args.ms_port}",
                            EntityName("mon", 0))
        con.send_message(MMonCommand(tid=1, cmd={
            "digest": digest, "process": args.process_id}))
        deadline = _time.time() + 60
        while "rc" not in acked and _time.time() < deadline:
            _time.sleep(0.05)
        _time.sleep(0.1)     # let the frame flush before teardown
        ms.shutdown()
        assert acked.get("rc") == 0, acked
        assert acked.get("digest") == str(digest), acked
    eng.stop()
    print(f"dcn engine worker {args.process_id}: digest {digest}, "
          f"{st.sharded_flushes} sharded flushes over "
          f"{args.local_devices} local of {n_global} global devices")
    return 0


def worker_main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--local-devices", type=int, required=True)
    ap.add_argument("--ms-port", type=int, required=True)
    ap.add_argument("--engine", action="store_true",
                    help="run the dispatch-engine deployment-mode "
                         "worker instead of the raw mesh proof")
    args = ap.parse_args(argv)

    # platform setup MUST precede any jax backend initialization
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count="
        f"{args.local_devices}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    if args.engine:
        return _engine_worker(args)
    jax.distributed.initialize(args.coordinator, args.num_processes,
                               args.process_id)
    import functools

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import ceph_tpu  # noqa: F401  (x64 for the GF/CRUSH kernels)
    from ceph_tpu.gf.matrix import gen_cauchy1_matrix
    from ceph_tpu.gf.tables import bit_matrix
    from ceph_tpu.ops.gf_kernel import _encode_xla, ec_encode_ref

    n_global = args.num_processes * args.local_devices
    devs = jax.devices()
    assert len(devs) == n_global, (len(devs), n_global)
    mesh = Mesh(np.array(devs), ("dp",))

    # deterministic global workload; every process derives the same bytes
    k, m, chunk = 4, 2, 256
    stripes = 4 * n_global
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8)
    per_proc = stripes // args.num_processes
    local = data[args.process_id * per_proc:
                 (args.process_id + 1) * per_proc]
    sharding = NamedSharding(mesh, P("dp", None, None))
    arr = jax.make_array_from_process_local_data(sharding, local)

    coding = gen_cauchy1_matrix(k, m)[k:]
    w = jnp.asarray(bit_matrix(coding))
    enc = functools.partial(_encode_xla, w, k=k, m=m)

    # encode over the GLOBAL mesh; the jnp.sum is a cross-process
    # all-reduce riding the DCN backend
    total = int(jax.jit(
        lambda d: jnp.sum(enc(d).astype(jnp.int64)))(arr))
    expect = int(ec_encode_ref(coding, data).astype(np.int64).sum())
    assert total == expect, (total, expect)

    # control plane: cross-check digests over the TCP messenger.
    # data plane #2: each worker also stages a bulk chunk in its
    # IciTransport wire mode and hands the TOKEN to the peer, which
    # redeems it with a cross-process device pull — the ici-wire
    # messenger's EC-shard path exercised at the transport level
    from ceph_tpu.messages import MMonCommand, MMonCommandAck
    from ceph_tpu.msg.ici import IciTransport
    from ceph_tpu.msg.messenger import Dispatcher, EntityName, Messenger

    ici = IciTransport.instance()
    try:
        ici.enable_wire()
        my_chunk = bytes([args.process_id]) * 65536
        my_token = ici.stage(my_chunk,
                             EntityName("osd", 1 - args.process_id))
    except Exception:
        # backend without the transfer engine: the control-plane proof
        # still runs; token fields stay empty and both sides skip
        my_token = b""

    def check_peer_token(tok_hex: str, peer_pid: int) -> bool:
        if not (my_token and tok_hex):
            return True     # transfer engine unavailable: skip
        data = ici.redeem(bytes.fromhex(tok_hex))
        assert data == bytes([peer_pid]) * 65536, len(data)
        assert ici.pulls >= 1     # it really crossed processes
        return True

    stack = pick_stack(peer_process=1 - args.process_id,
                       my_process=args.process_id)
    assert stack == "async"
    result = {}
    if args.process_id == 0:
        class D(Dispatcher):
            def ms_dispatch(self, msg):
                if isinstance(msg, MMonCommand):
                    if msg.cmd.get("done"):
                        # the peer finished its pull of OUR token: we
                        # may tear the transfer server down now
                        result["done"] = True
                        return True
                    ok = (msg.cmd.get("total") == total
                          and check_peer_token(
                              msg.cmd.get("token", ""), 1))
                    msg.connection.send_message(MMonCommandAck(
                        tid=msg.tid, result=0 if ok else -1,
                        output=my_token.hex()))
                    # publish only AFTER the pull + ack: the main
                    # thread must not shut us down mid-handshake
                    result["peer"] = msg.cmd
                    return True
                return False

        ms = Messenger.create(EntityName("mon", 0), stack)
        ms.add_dispatcher_tail(D())
        ms.bind(f"127.0.0.1:{args.ms_port}")
        ms.start()
        want = {"peer"} | ({"done"} if my_token else set())
        deadline = time.time() + 60
        while not want <= result.keys() and time.time() < deadline:
            time.sleep(0.05)
        ms.shutdown()
        assert result.get("peer", {}).get("total") == total, result
        assert not my_token or result.get("done"), result
    else:
        acked = {}

        class D(Dispatcher):
            def ms_dispatch(self, msg):
                if isinstance(msg, MMonCommandAck):
                    acked["rc"] = msg.result
                    acked["token"] = msg.output
                    return True
                return False

        ms = Messenger.create(EntityName("osd", 1), stack)
        ms.add_dispatcher_tail(D())
        ms.start()
        con = ms.connect_to(f"127.0.0.1:{args.ms_port}",
                            EntityName("mon", 0))
        con.send_message(MMonCommand(tid=1, cmd={
            "total": total, "process": args.process_id,
            "devices": n_global, "token": my_token.hex()}))
        deadline = time.time() + 60
        while "rc" not in acked and time.time() < deadline:
            time.sleep(0.05)
        assert check_peer_token(acked.get("token", ""), 0)
        # release the stager: our pull of its token is complete
        con.send_message(MMonCommand(tid=2, cmd={"done": 1}))
        time.sleep(0.2)     # let the frame flush before teardown
        ms.shutdown()
        assert acked.get("rc") == 0, acked
    print(f"dcn worker {args.process_id}: global sum {total} over "
          f"{n_global} devices in {args.num_processes} processes")
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
