"""In-process messenger stack (the unit-test transport; testmsgr analog).

Delivery preserves per-connection ordering via one dispatch thread per
messenger; addresses live in a process-global registry.
"""

from __future__ import annotations

import queue
import threading

from ceph_tpu.common import lockdep

from .message import Message
from .messenger import Connection, EntityName, Messenger

_registry: dict[str, "LoopbackMessenger"] = {}
# import-time module lock: named under CEPH_TPU_LOCKDEP=1, plain
# otherwise (created before tests can call lockdep.enable())
_registry_lock = lockdep.make_lock("loopback::registry")


class LoopbackConnection(Connection):
    def __init__(self, messenger, peer_addr, peer_name):
        super().__init__(messenger, peer_addr)
        self.peer_name = peer_name
        self._down = False

    def send_message(self, msg: Message) -> None:
        if self._down:
            return
        from ceph_tpu.common import tracing
        tracing.stamp(msg, str(self.messenger.my_name))
        with _registry_lock:
            peer = _registry.get(self.peer_addr)
        if peer is None:
            self.messenger.notify_reset(self)
            return
        # wire round-trip keeps encode/decode honest even in-process
        data = msg.encode()
        self.messenger.count_sent(len(data))
        peer._enqueue(data, sender=self.messenger)

    def mark_down(self) -> None:
        self._down = True

    def is_connected(self) -> bool:
        return not self._down


class LoopbackMessenger(Messenger):
    def __init__(self, name: EntityName):
        super().__init__(name)
        self._q: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._stop = False

    def bind(self, addr: str) -> None:
        self.my_addr = addr
        with _registry_lock:
            _registry[addr] = self

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._stop = True
        self._q.put(None)
        if self.my_addr:
            with _registry_lock:
                _registry.pop(self.my_addr, None)

    def _make_connection(self, addr: str, peer_name):
        return LoopbackConnection(self, addr, peer_name)

    def connect_to(self, addr: str, peer_name: EntityName) -> Connection:
        return self._make_connection(addr, peer_name)

    # -- internals ------------------------------------------------------------

    def _enqueue(self, data: bytes, sender: "LoopbackMessenger") -> None:
        self._q.put((data, sender))

    def _loop(self) -> None:
        from ceph_tpu.common.logging import get_logger
        while not self._stop:
            item = self._q.get()
            if item is None:
                return
            data, sender = item
            # one bad frame or handler bug must not kill the delivery thread
            try:
                msg = Message.decode(data)
                msg.wire_bytes = len(data)
                msg.connection = self._make_connection(
                    sender.my_addr, sender.my_name)
                self.deliver(msg)
            except Exception:
                get_logger("ms").exception(
                    "%s: dispatch failed for frame from %s",
                    self.my_name, sender.my_name)
