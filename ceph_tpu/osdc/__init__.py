"""Client-side object services (src/osdc/ analog)."""

from .striper import StripeLayout, Striper, StripedObject

__all__ = ["StripeLayout", "Striper", "StripedObject"]
