"""`rados bench` analog (tools/rados/rados.cc:106-184 over
common/obj_bencher.h semantics): write / sequential-read / random-read
workloads with a bounded window of in-flight aio ops, reporting
bandwidth, IOPS, and latency like the reference's per-run summary.

Usage (mirrors `rados bench -p P SECONDS write -b SIZE -t N`):

    python -m ceph_tpu.tools.rados_bench --mon HOST -p POOL SECONDS \
        write|seq|rand [-b OBJ_SIZE] [-t CONCURRENT] [--run-name NAME]

seq/rand runs read the objects a prior `write` run left behind (the
reference stores a benchmark_last_metadata object for this; here the
object naming is deterministic: <run-name>_<i>).
"""

from __future__ import annotations

import argparse
import json
import random
import time


class ObjBencher:
    def __init__(self, ioctx, obj_size: int = 4 << 20,
                 concurrent: int = 16, run_name: str = "benchmark_data",
                 op_timeout: float = 30.0):
        self.io = ioctx
        self.obj_size = obj_size
        self.concurrent = max(1, concurrent)
        self.run_name = run_name
        self.op_timeout = op_timeout

    def _obj(self, i: int) -> str:
        return f"{self.run_name}_{i}"

    def _drive(self, seconds: float, submit) -> dict:
        """Window-bounded aio loop shared by all workloads.  `submit(i)`
        returns an AioCompletion for work item i."""
        start = time.perf_counter()
        deadline = start + seconds
        in_flight: list[tuple[int, float, object]] = []
        started = finished = errors = 0
        lat_sum = 0.0
        lat_max = 0.0
        while True:
            now = time.perf_counter()
            stop = now >= deadline
            # reap whatever is done (front-first keeps completion order
            # roughly FIFO, like obj_bencher's slot scan)
            still = []
            for i, t0, c in in_flight:
                if c.is_complete():
                    lat = time.perf_counter() - t0
                    lat_sum += lat
                    lat_max = max(lat_max, lat)
                    finished += 1
                    if c.get_return_value() < 0:
                        errors += 1
                elif now - t0 > self.op_timeout:
                    # a lost completion must not hang the bench forever
                    c.cancel()
                    finished += 1
                    errors += 1
                else:
                    still.append((i, t0, c))
            in_flight = still
            if stop and not in_flight:
                break
            while not stop and len(in_flight) < self.concurrent:
                c = submit(started)
                in_flight.append((started, time.perf_counter(), c))
                started += 1
            time.sleep(0.0005)
        elapsed = time.perf_counter() - start
        done = finished - errors
        return {
            "seconds": round(elapsed, 3),
            "total_writes_or_reads": finished,
            "errors": errors,
            "bandwidth_mb_s": round(done * self.obj_size / elapsed / 1e6, 2),
            "iops_avg": round(done / elapsed, 2),
            "latency_avg_s": round(lat_sum / finished, 5) if finished else 0,
            "latency_max_s": round(lat_max, 5),
            "object_size": self.obj_size,
            "concurrent": self.concurrent,
        }

    def write_bench(self, seconds: float) -> dict:
        payload = bytes(range(256)) * (self.obj_size // 256 + 1)
        payload = payload[:self.obj_size]
        res = self._drive(
            seconds,
            lambda i: self.io.aio_write_full(self._obj(i), payload))
        res["mode"] = "write"
        return res

    def seq_read_bench(self, seconds: float, n_objects: int) -> dict:
        res = self._drive(
            seconds,
            lambda i: self.io.aio_read(self._obj(i % max(1, n_objects))))
        res["mode"] = "seq"
        return res

    def rand_read_bench(self, seconds: float, n_objects: int) -> dict:
        rng = random.Random(0)
        res = self._drive(
            seconds,
            lambda i: self.io.aio_read(
                self._obj(rng.randrange(max(1, n_objects)))))
        res["mode"] = "rand"
        return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rados bench")
    ap.add_argument("--mon", required=True, help="mon host:port")
    ap.add_argument("-p", "--pool", type=int, required=True)
    ap.add_argument("seconds", type=float)
    ap.add_argument("mode", choices=["write", "seq", "rand"])
    ap.add_argument("-b", "--block-size", type=int, default=4 << 20)
    ap.add_argument("-t", "--concurrent", type=int, default=16)
    ap.add_argument("--run-name", default="benchmark_data")
    ap.add_argument("--n-objects", type=int, default=0,
                    help="object count for seq/rand (from a prior write)")
    args = ap.parse_args(argv)

    from ceph_tpu.client.rados import RadosClient
    client = RadosClient(args.mon)
    client.connect()
    try:
        io = client.open_ioctx(args.pool)
        b = ObjBencher(io, obj_size=args.block_size,
                       concurrent=args.concurrent, run_name=args.run_name)
        if args.mode != "write" and args.n_objects <= 0:
            ap.error("seq/rand need --n-objects (the count a prior "
                     "write run reported as total_writes_or_reads)")
        if args.mode == "write":
            res = b.write_bench(args.seconds)
        elif args.mode == "seq":
            res = b.seq_read_bench(args.seconds, args.n_objects)
        else:
            res = b.rand_read_bench(args.seconds, args.n_objects)
        print(json.dumps(res))
        return 0
    finally:
        client.shutdown()


if __name__ == "__main__":
    raise SystemExit(main())
