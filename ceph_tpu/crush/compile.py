"""CrushMap → dense-array compilation for the batched device mapper.

The scalar oracle walks Python objects; the batched mapper needs the map as
static dense arrays so every step is a gather.  A compiled map holds, per
bucket: id, type, size, and padded item/weight rows.  Devices are type 0;
negative items index buckets at -1-id, exactly the reference layout
(crush/crush.h:354 crush_map.buckets).

Batchability contract (checked at compile time, ValueError otherwise):
  * every bucket is straw2 — the modern default (the reference converts maps
    to straw2 for the same reason: deterministic O(size) draws, no per-call
    permutation state).  Other algs run through the scalar oracle fallback
    (ceph_tpu.crush.mapper_ref / OSDMapMapping's scalar path).
  * modern tunables: choose_local_tries=0 and choose_local_fallback_tries=0
    (the jewel+ profile, Tunables defaults) — the legacy local-retry ladder
    (mapper.c:497-503) and perm fallback are scalar-only.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .types import CRUSH_BUCKET_STRAW2, CrushMap


@dataclass
class CompiledCrushMap:
    """Dense form of a CrushMap.  All arrays are host numpy; the mapper moves
    them to device once per map epoch (like OSDMap distribution)."""

    n_buckets: int
    max_size: int
    max_devices: int
    bucket_id: np.ndarray      # (B,) int32  — crush bucket id (negative)
    bucket_type: np.ndarray    # (B,) int32
    bucket_size: np.ndarray    # (B,) int32
    items: np.ndarray          # (B, S) int32, padded with INT32_MIN
    weights: np.ndarray        # (B, S) int64 16.16, padded with 0
    tunables_tries: int        # choose_total_tries + 1 (mapper.c:906)
    vary_r: int
    stable: int
    descend_once: int

    def bucket_index(self, item: int) -> int:
        return -1 - item


def compile_map(m: CrushMap) -> CompiledCrushMap:
    t = m.tunables
    if t.choose_local_tries or t.choose_local_fallback_tries:
        raise ValueError(
            "batched mapper requires modern tunables (choose_local_tries=0, "
            "choose_local_fallback_tries=0); use the scalar oracle for legacy "
            "profiles")
    n = len(m.buckets)
    sizes = []
    for b in m.buckets:
        if b is None:
            sizes.append(0)
            continue
        if b.alg != CRUSH_BUCKET_STRAW2:
            raise ValueError(
                f"batched mapper supports straw2 buckets only; bucket "
                f"{b.id} has alg {b.alg} — use the scalar oracle")
        sizes.append(b.size)
    s_max = max(sizes, default=1) or 1
    bucket_id = np.zeros(n, dtype=np.int32)
    bucket_type = np.zeros(n, dtype=np.int32)
    bucket_size = np.zeros(n, dtype=np.int32)
    items = np.full((n, s_max), np.iinfo(np.int32).min, dtype=np.int32)
    weights = np.zeros((n, s_max), dtype=np.int64)
    for idx, b in enumerate(m.buckets):
        if b is None:
            continue
        bucket_id[idx] = b.id
        bucket_type[idx] = b.type
        bucket_size[idx] = b.size
        items[idx, :b.size] = b.items
        weights[idx, :b.size] = b.item_weights
    return CompiledCrushMap(
        n_buckets=n, max_size=s_max, max_devices=m.max_devices,
        bucket_id=bucket_id, bucket_type=bucket_type, bucket_size=bucket_size,
        items=items, weights=weights,
        tunables_tries=t.choose_total_tries + 1,
        vary_r=t.chooseleaf_vary_r, stable=t.chooseleaf_stable,
        descend_once=t.chooseleaf_descend_once,
    )
