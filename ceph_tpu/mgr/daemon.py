"""Manager daemon — non-consensus cluster aggregation (src/mgr/ analog).

OSDs stream MMgrReport (perf counters + per-PG states) on their tick;
the mgr aggregates into cluster-state views and hosts the MODULE
ecosystem that serves them (src/mgr/ActivePyModules.cc + DaemonServer,
see ceph_tpu.mgr.module).

Multi-mgr: every mgr beacons to the mon (MMgrBeacon); the mon's MgrMap
(osdmap.mgr_db) names ONE active and lists the rest as standbys.  A
standby runs no modules and receives no reports; when the active's
beacon dies the mon promotes a standby, OSDs re-target their reports by
the new map, and the promoted mgr loads the same module set from the
mon-persisted config — mgr state is deliberately mon-side only, which
is what makes failover a pure promotion (MgrMonitor.cc:47-120).
"""

from __future__ import annotations

import json
import queue
import threading
import time

from ceph_tpu.common.logging import dout
from ceph_tpu.messages import MOSDMapMsg
from ceph_tpu.mgr.module import ModuleHost
from ceph_tpu.msg.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)
from ceph_tpu.osd.map_codec import advance_map
from ceph_tpu.osd.osdmap import OSDMap


def _enc_pg_stat(e: Encoder, st: dict) -> None:
    e.str(st.get("state", ""))
    e.list(st.get("up", []), lambda e2, v: e2.s32(v))
    e.u64(st.get("num_objects", 0))
    e.u64(st.get("bytes", 0))
    e.u64(st.get("missing", 0))
    e.u64(st.get("log_size", 0))
    lh = st.get("log_head", (0, 0))
    lt = st.get("log_tail", (0, 0))
    e.u64(lh[0]).u64(lh[1]).u64(lt[0]).u64(lt[1])


def _dec_pg_stat(d: Decoder) -> dict:
    return {"state": d.str(),
            "up": d.list(lambda d2: d2.s32()),
            "num_objects": d.u64(), "bytes": d.u64(),
            "missing": d.u64(), "log_size": d.u64(),
            "log_head": (d.u64(), d.u64()),
            "log_tail": (d.u64(), d.u64())}


@register_message
class MMgrReport(Message):
    """osd -> mgr: perf counters + pg states (messages/MMgrReport.h).
    v2 adds per-PG stat records for the PGs this osd leads — the pg_dump
    / pg ls / iostat feed (pg_stat_t reduced); v3 adds the full TYPED
    perf dump of the daemon's whole counter collection (u64 counters,
    time-avg {avgcount, sum} pairs, histograms with bucket bounds —
    every set: osd, messenger, store), the payload the prometheus
    module turns into real histogram/summary families; v4 appends the
    observability tail — the daemon's tail-sampled slow-trace digests
    (span rows), historic slow-op digests, and the pipeline-profile
    phase digest (telemetry.pipeline_profile_digest), the insights
    module's cluster-wide `tracing ls` / `slow_ops` / `profile` feed.
    The tail is a JSON dict, so the profile key rides the SAME v4
    frame — old peers simply never read it.  Older peers
    interoperate: the versioned section skips trailing fields (old
    mgrs simply never see the v4 tail).  v5 adds the scrub key to the
    tail — the per-daemon background-integrity digest
    (``_scrub_digest_report``) feeding the mgr scrub_feed and the
    ``ceph_scrub_*`` prometheus families.  The tenant_usage key (same
    JSON-tail carriage — no version bump needed, old mgrs skip it) is
    the tenant device-time ledger digest
    (``telemetry.tenant_usage_digest``) feeding the mgr tenant_feed,
    the slo module's burn-rate engine, and the
    ``ceph_tenant_device_seconds_total`` prometheus family."""

    TYPE = 0x701
    HEAD_VERSION = 5
    COMPAT_VERSION = 1

    def __init__(self, osd_id: int = 0, counters: dict | None = None,
                 pg_states: dict | None = None, num_objects: int = 0,
                 bytes_used: int = 0, pg_stats: dict | None = None,
                 perf: dict | None = None,
                 slow_traces: list | None = None,
                 slow_ops: list | None = None,
                 profile: dict | None = None,
                 qos: dict | None = None,
                 faults: dict | None = None,
                 scrub: dict | None = None,
                 tenant_usage: dict | None = None):
        super().__init__()
        self.osd_id = osd_id
        self.counters = counters or {}
        self.pg_states = pg_states or {}
        self.num_objects = num_objects
        self.bytes_used = bytes_used
        #: pgid-str -> per-PG stat record (primary PGs only)
        self.pg_stats = pg_stats or {}
        #: set name -> typed `perf dump` payload (PerfCountersCollection)
        self.perf = perf or {}
        #: completed slow-trace digests (common/tracing slow ring)
        self.slow_traces = slow_traces or []
        #: slowest historic-op digests (OpTracker.slow_digests)
        self.slow_ops = slow_ops or []
        #: pipeline-profile phase digest (phase shares per kernel
        #: family, compile ledger, utilization, mapping phase split)
        self.profile = profile or {}
        #: per-tenant dmclock accounting digest (qos lanes: backlog,
        #: phase-served counts, wait totals) — rides the SAME v4 JSON
        #: tail as profile, so old peers simply never read it
        self.qos = qos or {}
        #: device-runtime fault digest (telemetry.fault_digest():
        #: per-engine breaker states, fallback/retry/probe counters) —
        #: same v4 JSON tail carriage; the mgr raises KERNEL_DEGRADED
        #: while any reported channel breaker is not closed
        self.faults = faults or {}
        #: per-daemon background-integrity counters (deep scrub /
        #: verified repair; v5 tail key) — the scrub_feed source
        self.scrub = scrub or {}
        #: tenant device-time ledger digest (per-tenant x engine x
        #: channel device-seconds + wait quantiles; JSON-tail key) —
        #: the tenant_feed / slo-module source
        self.tenant_usage = tenant_usage or {}

    def encode_payload(self, enc: Encoder):
        enc.versioned(5, 1, lambda e: (
            e.s32(self.osd_id),
            e.map(self.counters, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.u64(int(v))),
            e.map(self.pg_states, lambda e2, k: e2.str(k),
                  lambda e2, v: e2.u32(v)),
            e.u64(self.num_objects), e.u64(self.bytes_used),
            e.map(self.pg_stats, lambda e2, k: e2.str(k),
                  _enc_pg_stat),
            # typed counter trees are irregular (per-type shapes);
            # JSON inside the versioned frame keeps the wire stable
            e.str(json.dumps(self.perf)),
            e.str(json.dumps({"slow_traces": self.slow_traces,
                              "slow_ops": self.slow_ops,
                              "profile": self.profile,
                              "qos": self.qos,
                              "faults": self.faults,
                              "scrub": self.scrub,
                              "tenant_usage": self.tenant_usage}))))

    def decode_payload(self, dec: Decoder, version):
        # decode constructs via __new__: every field needs a default
        # here, v1 payloads carry no pg_stats, v2 no perf, v3 no tail
        self.pg_stats = {}
        self.perf = {}
        self.slow_traces = []
        self.slow_ops = []
        self.profile = {}
        self.qos = {}
        self.faults = {}
        self.scrub = {}
        self.tenant_usage = {}

        def body(d, v):
            self.osd_id = d.s32()
            self.counters = d.map(lambda d2: d2.str(),
                                  lambda d2: d2.u64())
            self.pg_states = d.map(lambda d2: d2.str(),
                                   lambda d2: d2.u32())
            self.num_objects = d.u64()
            self.bytes_used = d.u64()
            if v >= 2:
                self.pg_stats = d.map(lambda d2: d2.str(), _dec_pg_stat)
            if v >= 3:
                self.perf = json.loads(d.str())
            if v >= 4:
                tail = json.loads(d.str())
                self.slow_traces = tail.get("slow_traces", [])
                self.slow_ops = tail.get("slow_ops", [])
                self.profile = tail.get("profile", {})
                self.qos = tail.get("qos", {})
                self.faults = tail.get("faults", {})
                self.scrub = tail.get("scrub", {})
                self.tenant_usage = tail.get("tenant_usage", {})
        dec.versioned(5, body)


@register_message
class MMgrBeacon(Message):
    """mgr -> mon liveness + standby registration
    (messages/MMgrBeacon.h:25): name, dialable addr, active-readiness,
    and the module list the mon publishes in the MgrMap."""

    TYPE = 0x702

    def __init__(self, name: str = "", addr: str = "",
                 available: bool = True,
                 modules: list[str] | None = None):
        super().__init__()
        self.name = name
        self.addr = addr
        self.available = available
        self.modules = modules or []

    def encode_payload(self, enc: Encoder):
        enc.versioned(1, 1, lambda e: (
            e.str(self.name), e.str(self.addr),
            e.u8(1 if self.available else 0),
            e.list(self.modules, lambda e2, m: e2.str(m))))

    def decode_payload(self, dec: Decoder, version):
        def body(d, v):
            self.name = d.str()
            self.addr = d.str()
            self.available = bool(d.u8())
            self.modules = d.list(lambda d2: d2.str())
        dec.versioned(1, body)


class MgrDaemon(Dispatcher):
    """DaemonServer + ActivePyModules: collect reports, host modules,
    serve aggregate views."""

    def __init__(self, mon_addr: str, ms_type: str = "async",
                 addr: str = "127.0.0.1:0", auth_key=None,
                 cephx: tuple[str, str] | None = None, mgr_id: int = 0):
        self.mon_addr = mon_addr
        self.mgr_id = mgr_id
        self.name = EntityName("mgr", mgr_id)
        self.osdmap = OSDMap()
        # analysis: allow[bare-lock] -- mgr report-buffer leaf lock
        self._lock = threading.Lock()
        #: osd -> (last report time, MMgrReport)
        self.reports: dict[int, tuple[float, MMgrReport]] = {}
        #: osd -> (time, counters) of the PREVIOUS report (iostat rates)
        self._prev_counters: dict[int, tuple[float, dict]] = {}
        #: INCREMENTAL pg-row aggregation (the reference keeps
        #: pg_stat_t deltas, not per-query rebuilds): pgid -> (stamp,
        #: reporting osd, stat record), folded in at report intake so
        #: `pg dump` at 1M-PG scale is a snapshot, not an O(cluster)
        #: rebuild per query
        self._pg_best: dict[str, tuple[float, int, dict]] = {}
        #: osd -> pgids its latest report claimed: a pg absent from an
        #: osd's NEWER report (moved away / pool deleted) retires from
        #: the aggregate unless another osd claims it, so pg dump never
        #: serves permanent ghost rows
        self._pg_claims: dict[int, set] = {}
        self._pg_rows_cache: list[dict] | None = None
        self.host = ModuleHost(self)
        self._active = False
        #: peer mgr names ever seen in a published MgrMap (active +
        #: standbys, minus self).  An EMPTY map only implies "I am
        #: active" while this is empty — once peers are known, a map
        #: cleared by stale beacons during a mon election must NOT
        #: self-promote every standby at once (two actives racing
        #: mutating mon commands); wait for the mon to name one
        self._peer_mgrs_seen: set[str] = set()
        #: when the map first went (and stayed) empty, monotonic.  A
        #: RESTARTED standby has an empty _peer_mgrs_seen too, so the
        #: peers-seen guard alone can't stop it self-promoting next to
        #: an incumbent riding out a transiently cleared map — implicit
        #: active additionally waits out EMPTY_MAP_GRACE so a live mon
        #: (which names an active within a tick of hearing a beacon)
        #: always wins the race against self-promotion
        self._empty_map_since: float | None = None
        #: work the DISPATCH thread must never do itself (module
        #: start/stop, command handling): those paths block on mon
        #: round-trips whose acks only the dispatch thread delivers —
        #: doing them inline would deadlock until the timeout
        self._work_q: queue.Queue = queue.Queue()
        #: config-key read-through cache (a mon round-trip per
        #: get_store would otherwise dominate module ticks)
        self._store_cache: dict[str, tuple[float, object]] = {}
        self.msgr = Messenger.create(self.name, ms_type)
        self.msgr.set_auth(auth_key)
        self._cephx = cephx
        self._rotating: dict[int, str] = {}
        self._rotating_at = 0.0
        from ceph_tpu.common.moncmd import MonCommander
        self.mon_cmd = MonCommander(
            self.msgr, [x for x in mon_addr.split(",") if x],
            osdmap_fn=lambda: self.osdmap)
        if cephx is not None:
            from ceph_tpu.auth.cephx import TicketKeyring
            from ceph_tpu.auth.handshake import CephxConfig
            self.msgr.set_auth_cephx(CephxConfig(
                entity=cephx[0], key=cephx[1],
                keyring=TicketKeyring(self.mon_cmd.fetch_ticket),
                service="mgr", rotating=lambda: self._rotating))
        self.msgr.set_policy("osd", ConnectionPolicy.stateful_server())
        self.msgr.set_policy("mon", ConnectionPolicy.stateful_peer())
        self.msgr.add_dispatcher_tail(self)
        self._addr = addr

    def _refresh_rotating(self) -> None:
        keys = self.mon_cmd.fetch_rotating("mgr")
        if keys is not None:
            self._rotating = keys
            self._rotating_at = time.time()

    def _subscribe(self) -> None:
        from ceph_tpu.common.moncmd import mon_targets
        from ceph_tpu.mon.monitor import MMonSubscribe
        for rank, a in mon_targets(
                self.osdmap,
                [x for x in self.mon_addr.split(",") if x]):
            con = self.msgr.connect_to(a, EntityName("mon", rank))
            con.send_message(MMonSubscribe(name=str(self.name),
                                           addr=self.msgr.my_addr,
                                           epoch=self.osdmap.epoch))
            con.send_message(MMgrBeacon(
                name=str(self.name), addr=self.msgr.my_addr,
                available=True,
                modules=sorted(self.host.modules)))

    def _renew_tick(self) -> None:
        """Timer thread — NEVER the dispatch thread: the rotating
        refresh blocks on a mon ack only the dispatch thread delivers.
        Also renews the map subscription + beacon: pushes ride the
        mon-side session, so a dropped session must be
        re-established."""
        if getattr(self, "_stopped", False):
            return
        try:
            self._subscribe()
            if self._cephx is not None \
                    and time.time() - self._rotating_at > 55.0:
                self._refresh_rotating()
            if self._active:
                # module ticks run on the WORKER: a slow tick (mon
                # round-trips during an election) must never delay the
                # next beacon past the mon's grace and demote a
                # healthy active
                self._work_q.put(("tick", None))
            else:
                # activation is normally map-driven (ms_dispatch), but
                # implicit-active's EMPTY_MAP_GRACE can only expire
                # here when no further map ever arrives (mon down)
                self._check_activation()
        except (OSError, TimeoutError):
            pass
        self._rot_timer = threading.Timer(5.0, self._renew_tick)
        self._rot_timer.daemon = True
        self._rot_timer.start()

    def init(self) -> None:
        self.msgr.bind(self._addr)
        self.msgr.start()
        self._rot_timer = None
        self._worker = threading.Thread(target=self._work_loop,
                                        name=f"{self.name}-work",
                                        daemon=True)
        self._worker.start()
        if self._cephx is not None:
            self._refresh_rotating()
        self._renew_tick()

    def shutdown(self) -> None:
        self._stopped = True
        if getattr(self, "_rot_timer", None) is not None:
            self._rot_timer.cancel()
        if getattr(self, "_worker", None) is not None:
            self._work_q.put(None)
            self._worker.join(timeout=2.0)
        self.host.stop_all()
        self.msgr.shutdown()

    def _work_loop(self) -> None:
        while True:
            item = self._work_q.get()
            if item is None or getattr(self, "_stopped", False):
                return
            kind, payload = item
            try:
                if kind == "activation":
                    # apply only if the flag still agrees (a demote
                    # queued behind a promote supersedes it)
                    if payload and self._active:
                        self.host.start_all()
                    elif not payload and not self._active:
                        self.host.stop_all()
                elif kind == "tick":
                    if self._active:
                        self.host.tick()
                elif kind == "cmd":
                    msg = payload
                    out, rc = self._handle_command(msg.cmd)
                    if msg.connection is not None:
                        from ceph_tpu.messages import MMonCommandAck
                        msg.connection.send_message(MMonCommandAck(
                            tid=msg.tid, result=rc, output=out))
            except Exception as e:   # pragma: no cover
                dout("mgr", 0, "mgr worker %s failed: %r", kind, e)

    @property
    def addr(self) -> str:
        return self.msgr.my_addr

    # -- active/standby (MgrMap-driven) ---------------------------------------

    @property
    def is_active(self) -> bool:
        return self._active

    #: how long the map must be STABLY empty before a never-activated
    #: mgr self-promotes.  A live mon names an active within a tick
    #: (0.25 s) of hearing any beacon, and beacons ride the 5 s renew
    #: timer — so whenever a mon can hear us, the named path always
    #: beats this grace and implicit-active never fires.  It only
    #: fires when no mon is reachable at all, where a brief dual
    #: active cannot issue mutating mon commands anyway, and the mon's
    #: first published map demotes the loser
    EMPTY_MAP_GRACE = 3.0

    def _check_activation(self) -> None:
        """Compare the map's MgrMap against my name; load/unload the
        module set on the transition.  An EMPTY MgrMap (pre-first-
        publish, or no mon leader) counts as active ONLY while no peer
        mgr has ever appeared in a map AND the map has been empty past
        EMPTY_MAP_GRACE: single-mgr clusters must serve before the map
        exists (the mon publishes within a tick of the first beacon),
        but once standbys are known an empty map means the mon lost
        its beacons — every standby assuming the role would run two
        actives' worth of mutating module commands — and a RESTARTED
        standby (fresh peers-seen set) catching a transiently cleared
        map must give the mon the grace window to name one first.  The
        INCUMBENT active keeps the role across a transiently cleared
        map (mon election churn): demoting it would stop and reload
        every module seconds later for nothing."""
        db = self.osdmap.mgr_db or {}
        me = str(self.name)
        self._peer_mgrs_seen.update(
            n for n in ([db.get("active_name")]
                        + [s.get("name") for s in db.get("standbys", [])])
            if n and n != me)
        now = time.monotonic()
        if db:
            self._empty_map_since = None
        elif self._empty_map_since is None:
            self._empty_map_since = now
        with self._lock:
            # check-and-transition is atomic: this runs from both the
            # dispatch thread (map receipt) and the renew timer (grace
            # re-check when no further map arrives), and a double
            # enqueue would load the module set twice
            want = (db.get("active_name") == me
                    or (not db and (self._active
                                    or (not self._peer_mgrs_seen
                                        and self._empty_map_since
                                        is not None
                                        and now - self._empty_map_since
                                        >= self.EMPTY_MAP_GRACE))))
            if want and not self._active:
                self._active = True
                flip = True
            elif not want and self._active:
                self._active = False
                flip = False
            else:
                return
        if flip:
            dout("mgr", 1, "%s taking over as ACTIVE", self.name)
        else:
            dout("mgr", 1, "%s demoted to standby", self.name)
        self._work_q.put(("activation", flip))

    def module_should_stop(self, inst) -> bool:
        return getattr(self, "_stopped", False) \
            or self.host.should_stop(inst)

    # -- dispatch -------------------------------------------------------------

    def ms_dispatch(self, msg) -> bool:
        from ceph_tpu.messages import (
            MMonCommand, MMonCommandAck)
        if isinstance(msg, MMonCommandAck):
            self.mon_cmd.handle_ack(msg)
            return True
        if isinstance(msg, MMonCommand):
            # the mgr serves its own command tier (DaemonServer
            # handle_command): clients re-target here after `mgr dump`.
            # Handled on the WORKER thread — command paths may call
            # back into the mon (config-key), whose acks this dispatch
            # thread must stay free to deliver
            self._work_q.put(("cmd", msg))
            return True
        if isinstance(msg, MMgrReport):
            now = time.time()
            with self._lock:
                prev = self.reports.get(msg.osd_id)
                if prev is not None:
                    # keep one older counter sample per osd: the iostat
                    # rate window (current - previous) / dt
                    self._prev_counters[msg.osd_id] = (
                        prev[0], dict(prev[1].counters))
                self.reports[msg.osd_id] = (now, msg)
                # fold this osd's per-PG records into the aggregate
                # (newest report wins a contended pgid); rows this osd
                # STOPPED claiming retire unless someone else owns them
                changed = False
                claims = set((msg.pg_stats or {}))
                for pgid in self._pg_claims.get(msg.osd_id,
                                                set()) - claims:
                    cur = self._pg_best.get(pgid)
                    if cur is not None and cur[1] == msg.osd_id:
                        del self._pg_best[pgid]
                        changed = True
                self._pg_claims[msg.osd_id] = claims
                for pgid, st in (msg.pg_stats or {}).items():
                    cur = self._pg_best.get(pgid)
                    if cur is None or now >= cur[0]:
                        self._pg_best[pgid] = (now, msg.osd_id, st)
                        changed = True
                if changed:
                    self._pg_rows_cache = None
            self.host.notify_all("pg_stats", msg.osd_id)
            return True
        if isinstance(msg, MOSDMapMsg):
            newmap, gapped = advance_map(self.osdmap, msg)
            if newmap is not None:
                self.osdmap = newmap
                self._check_activation()
                self.host.notify_all("osd_map", newmap.epoch)
            elif gapped:
                self._subscribe()
            return True
        return False

    # -- module-facing state API (ActivePyModules::get_python) ----------------

    def get(self, data_name: str):
        """Named cluster-state snapshots modules program against."""
        if data_name == "osd_map":
            return self.osdmap
        if data_name == "pg_summary":
            return self.pg_summary()
        if data_name == "pg_dump":
            return self.pg_dump()
        if data_name == "df":
            return self.df()
        if data_name == "counters":
            return self.counters()
        if data_name == "perf_reports":
            return self.perf_reports()
        if data_name == "health":
            return self.health()
        if data_name == "insights_feed":
            return self.insights_feed()
        if data_name == "qos_feed":
            return self.qos_feed()
        if data_name == "tenant_feed":
            return self.tenant_feed()
        if data_name == "osdmap_slo_db":
            return dict(self.osdmap.slo_db)
        if data_name == "scrub_feed":
            return self.scrub_feed()
        if data_name == "faults_feed":
            # same cutoff health() applies: a daemon that died (or was
            # removed) mid-outage must not pin the per-daemon breaker
            # gauge open on every scrape forever
            return self.faults_feed(self.REPORT_STALE_AFTER)
        if data_name == "io_samples":
            with self._lock:
                return {"current": {o: (t, dict(r.counters))
                                    for o, (t, r) in
                                    self.reports.items()},
                        "prev": dict(self._prev_counters)}
        raise KeyError(f"unknown mgr data {data_name!r}")

    # -- persisted KV (config-key through the mon) ----------------------------

    STORE_CACHE_TTL = 2.0

    def get_store(self, key: str, default=None):
        now = time.time()
        hit = self._store_cache.get(key)
        if hit is not None and now - hit[0] < self.STORE_CACHE_TTL:
            return default if hit[1] is None else hit[1]
        try:
            rc, out = self.mon_cmd.cmd({"prefix": "config-key get",
                                        "key": key})
        except (OSError, TimeoutError):
            return default if hit is None or hit[1] is None else hit[1]
        val = out if rc == 0 else None
        self._store_cache[key] = (now, val)
        return default if val is None else val

    def set_store(self, key: str, value) -> None:
        if value is None:
            self.mon_cmd.cmd({"prefix": "config-key rm", "key": key})
        else:
            self.mon_cmd.cmd({"prefix": "config-key set", "key": key,
                              "value": str(value)})
        self._store_cache[key] = (time.time(),
                                  None if value is None else str(value))

    # -- command tier (DaemonServer::handle_command reduced) ------------------

    def _handle_command(self, cmd: dict) -> tuple[str, int]:
        prefix = cmd.get("prefix", "")
        try:
            if prefix == "pg dump":
                return json.dumps(self.pg_dump()), 0
            if prefix == "df":
                return json.dumps(self.df()), 0
            if prefix == "pg ls":
                pool = cmd.get("pool")
                states = cmd.get("states") or None
                if isinstance(states, str):
                    states = [states]
                return json.dumps(self.pg_ls(
                    pool=int(pool) if pool is not None else None,
                    states=states)), 0
            if prefix == "mgr module ls":
                return json.dumps({
                    "enabled_modules": self.host.enabled_set(),
                    "loaded_modules": sorted(self.host.modules),
                    "available_modules": ModuleHost.available()}), 0
            if prefix == "mgr module enable":
                return self._cmd_module_enable(str(cmd["module"]))
            if prefix == "mgr module disable":
                return self._cmd_module_disable(str(cmd["module"]))
            out = self.host.handle_command(cmd)
            if out is not None:
                return out
            # modules answer their commands even on a mgr driven
            # directly in tests (never promoted): load on demand.  A
            # stale name in the stored enabled list (module removed
            # upgrade-side) must not break routing for the rest
            for name in self.host.enabled_set():
                try:
                    cls = ModuleHost.resolve(name)
                except ImportError:
                    continue
                if any(c["prefix"] == prefix for c in cls.COMMANDS):
                    return self._module(name).handle_command(cmd)
            return f"unknown mgr command {prefix!r}", -22
        except Exception as e:
            return f"mgr command failed: {e!r}", -22

    def _cmd_module_enable(self, name: str) -> tuple[str, int]:
        try:
            ModuleHost.resolve(name)
        except ImportError as e:
            return f"no such module {name!r}: {e}", -2
        enabled = self._stored_modules()
        if name not in enabled:
            enabled.append(name)
            self.set_store("mgr/modules", json.dumps(enabled))
        if self._active and not self.host.load(name):
            return f"module {name!r} failed to load", -22
        return json.dumps({"enabled": enabled}), 0

    def _cmd_module_disable(self, name: str) -> tuple[str, int]:
        if name in ModuleHost.ALWAYS_ON:
            return f"module {name!r} is always on", -22
        enabled = self._stored_modules()
        if name in enabled:
            enabled.remove(name)
            self.set_store("mgr/modules", json.dumps(enabled))
        self.host.unload(name)
        return json.dumps({"enabled": enabled}), 0

    def _stored_modules(self) -> list[str]:
        raw = self.get_store("mgr/modules")
        if not raw:
            return []
        try:
            return list(json.loads(raw))
        except (ValueError, TypeError):
            return []

    def _module(self, name: str):
        """Module instance, loading on demand (tests drive view methods
        on a mgr that was never promoted)."""
        inst = self.host.modules.get(name)
        if inst is None:
            self.host.load(name)
            inst = self.host.modules[name]
        return inst

    # -- aggregate views (DaemonServer altitude: not module features) ---------

    def pg_summary(self) -> dict:
        """PG state histogram across OSD reports (`ceph status` pgs)."""
        out: dict[str, int] = {}
        with self._lock:
            for _t, rep in self.reports.values():
                for state, n in rep.pg_states.items():
                    out[state] = out.get(state, 0) + n
        return out

    def df(self) -> dict:
        with self._lock:
            return {
                "total_objects": sum(r.num_objects
                                     for _t, r in self.reports.values()),
                "total_bytes_used": sum(
                    r.bytes_used for _t, r in self.reports.values()),
                "per_osd": {o: {"objects": r.num_objects,
                                "bytes": r.bytes_used}
                            for o, (_t, r) in self.reports.items()},
            }

    def counters(self) -> dict:
        with self._lock:
            return {o: dict(r.counters)
                    for o, (_t, r) in self.reports.items()}

    def perf_reports(self) -> dict:
        """Typed perf dumps by reporting osd (MMgrReport v3 payload):
        {osd: {set_name: {counter: value | {avgcount, sum} |
        {bounds, buckets, sum}}}}."""
        with self._lock:
            return {o: dict(r.perf)
                    for o, (_t, r) in self.reports.items() if r.perf}

    # -- pg introspection (DaemonServer `pg dump` / `pg ls`) ------------------

    def _pg_rows(self) -> list[dict]:
        """Merged per-PG records, maintained INCREMENTALLY at report
        intake (newest report wins a contended pgid — the remap race
        window) and served from a cache a new report invalidates."""
        with self._lock:
            if self._pg_rows_cache is not None:
                # COPIES out: callers annotate rows (modules do), and a
                # shared cache must never be mutated under them
                return [dict(r) for r in self._pg_rows_cache]
            rows = []
            for pgid, (t, osd, st) in self._pg_best.items():
                row = dict(st)
                row["pgid"] = pgid
                row["reported_by"] = osd
                row["stamp"] = t
                rows.append(row)
            rows.sort(key=lambda r: tuple(
                int(x) for x in r["pgid"].split(".")))
            self._pg_rows_cache = rows
            return [dict(r) for r in rows]

    def pg_dump(self) -> dict:
        """`ceph pg dump` (DaemonServer::_handle_pg_dump reduced):
        every PG's state/acting/usage/log bounds plus per-osd totals."""
        rows = self._pg_rows()
        with self._lock:
            osd_stats = {o: {"num_objects": r.num_objects,
                             "bytes_used": r.bytes_used,
                             "stamp": t}
                         for o, (t, r) in self.reports.items()}
        return {"pg_stats": rows, "osd_stats": osd_stats,
                "num_pgs": len(rows)}

    def pg_ls(self, pool: int | None = None,
              states: list[str] | None = None) -> list[dict]:
        """`ceph pg ls [pool] [states...]`."""
        rows = self._pg_rows()
        if pool is not None:
            rows = [r for r in rows
                    if int(r["pgid"].split(".")[0]) == pool]
        if states:
            rows = [r for r in rows if r["state"] in states]
        return rows

    def insights_feed(self) -> dict:
        """Per-daemon observability tail from MMgrReport v4: slow-trace
        digests, historic slow-op digests, and the pipeline-profile
        phase digest (the insights module's cluster-wide ranking and
        where-did-the-time-go feed)."""
        with self._lock:
            return {o: {"slow_traces": list(r.slow_traces),
                        "slow_ops": list(r.slow_ops),
                        "profile": dict(r.profile),
                        "stamp": t}
                    for o, (t, r) in self.reports.items()}

    def qos_feed(self) -> dict:
        """Per-daemon dmclock accounting from the MMgrReport v4 tail:
        osd -> {lanes: {class: {backlog, served{phase}, wait_sum_s}},
        evicted rollup} — the prometheus ceph_qos_* source."""
        with self._lock:
            return {o: dict(r.qos)
                    for o, (_t, r) in self.reports.items() if r.qos}

    def tenant_feed(self) -> dict:
        """Per-daemon tenant device-time ledger digests from the
        MMgrReport JSON tail: osd -> {tenants: {tenant:
        {device_seconds, share, channels}}, total_device_seconds} —
        the prometheus ceph_tenant_* source and the slo module's
        usage feed."""
        with self._lock:
            return {o: dict(r.tenant_usage)
                    for o, (_t, r) in self.reports.items()
                    if r.tenant_usage}

    def scrub_feed(self) -> dict:
        """Per-daemon background-integrity counters from the
        MMgrReport v5 tail: osd -> {objects_scrubbed, inconsistent,
        repaired, repair_unverified, ...} — the prometheus
        ceph_scrub_* source and the insights integrity row."""
        with self._lock:
            return {o: dict(r.scrub)
                    for o, (_t, r) in self.reports.items() if r.scrub}

    def faults_feed(self, stale_after: float | None = None) -> dict:
        """Per-daemon device-runtime fault digests from the MMgrReport
        v4 tail (ctx.fault_digest per daemon) — the health
        KERNEL_DEGRADED and prometheus per-daemon breaker sources.
        With ``stale_after``, daemons whose last report is older are
        dropped: retained reports are never pruned, so a daemon that
        died (or was removed) mid-outage would otherwise pin its open
        breaker — and the health warning — forever."""
        now = time.time()
        with self._lock:
            return {o: dict(r.faults)
                    for o, (t, r) in self.reports.items()
                    if r.faults and (stale_after is None
                                     or now - t <= stale_after)}

    def _degraded_kernel_channels(self,
                                  stale_after: float | None = None
                                  ) -> dict:
        """osd -> [\"engine/channel\", ...] for every reported channel
        whose circuit breaker is not closed (the daemon is serving
        that kernel from the host oracle)."""
        out: dict[int, list[str]] = {}
        for osd, digest in self.faults_feed(stale_after).items():
            degraded = [
                f"{engine}/{ch}"
                for engine, d in sorted(digest.items())
                if isinstance(d, dict)
                for ch, st in sorted(d.get("breaker_states",
                                           {}).items())
                if st != 0]
            if degraded:
                out[osd] = degraded
        return out

    #: fraction of existing OSDs that must be exceeded for OSD_DOWN to
    #: escalate from WARN to ERR (mon_osd_down_out semantics reduced)
    OSD_DOWN_ERR_RATIO = 0.5

    #: seconds after which a daemon's retained report is treated as
    #: stale (MGR_STALE_REPORTS, and the cutoff for fault attribution:
    #: a silent daemon is STALE, not degraded-forever)
    REPORT_STALE_AFTER = 10.0

    def health(self, stale_after: float = REPORT_STALE_AFTER) -> dict:
        """Structured health with severities: each check carries
        severity "warn" or "error"; any error check makes the summary
        HEALTH_ERR (the prometheus module exports 0=OK 1=WARN 2=ERR)."""
        now = time.time()
        with self._lock:
            stale = [o for o, (t, _r) in self.reports.items()
                     if now - t > stale_after]
        checks = []
        if stale:
            checks.append({"check": "MGR_STALE_REPORTS", "osds": stale,
                           "severity": "warn"})
        summary = self.pg_summary()
        degraded = sum(n for s, n in summary.items()
                       if s not in ("active", "replica"))
        if degraded:
            checks.append({"check": "PG_DEGRADED", "count": degraded,
                           "severity": "warn"})
        m = self.osdmap
        existing = [o for o in range(m.max_osd) if m.exists(o)]
        down = [o for o in existing if not m.is_up(o)]
        if down:
            # strict majority down escalates to error (half down on an
            # even-sized cluster is still WARN; a 1-osd cluster fully
            # down IS a total outage and reads as error)
            err = len(down) > len(existing) * self.OSD_DOWN_ERR_RATIO
            checks.append({"check": "OSD_DOWN", "osds": down,
                           "severity": "error" if err else "warn"})
        failed = self.host.failed_modules()
        if failed:
            checks.append({"check": "MGR_MODULE_ERROR",
                           "modules": failed, "severity": "error"})
        # same cutoff MGR_STALE_REPORTS uses: a daemon that stopped
        # reporting mid-outage shows up as stale, not as degraded
        degraded_kernels = self._degraded_kernel_channels(stale_after)
        if degraded_kernels:
            # a daemon is serving kernel traffic from the host oracle
            # (open/half-open breaker): data stays correct (bit-exact
            # degradation) but the accelerator is out — surface it
            # like any degraded-redundancy state
            checks.append({"check": "KERNEL_DEGRADED",
                           "daemons": {str(o): chs for o, chs
                                       in degraded_kernels.items()},
                           "severity": "warn"})
        # QOS_SLO_BURN: the slo module owns the burn-rate math; a
        # missing/failed module must not take cluster health down with
        # it (it already surfaces via MGR_MODULE_ERROR)
        try:
            checks.extend(self._module("slo").health_checks())
        except Exception:
            pass
        if not checks:
            status = "HEALTH_OK"
        elif any(c["severity"] == "error" for c in checks):
            status = "HEALTH_ERR"
        else:
            status = "HEALTH_WARN"
        return {"status": status, "checks": checks}

    # -- module-feature delegates (pre-framework API kept working) ------------

    def iostat(self) -> dict:
        return self._module("iostat").rates()

    def balance_plan(self, **kw) -> list[dict]:
        return self._module("balancer").plan(**kw)

    def balancer_status(self) -> dict:
        return self._module("balancer").status()

    def telemetry_report(self) -> dict:
        return self._module("telemetry").report()

    def prometheus_text(self) -> str:
        return self._module("prometheus").scrape_text()

    def serve_prometheus(self, port: int = 0) -> int:
        """Start the HTTP exporter; returns the bound port (GET /metrics
        — the mgr prometheus module's endpoint)."""
        return self._module("prometheus").start_server(port)
