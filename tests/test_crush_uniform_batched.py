"""Batched CRUSH uniform buckets (bucket_perm_choose, mapper.c:73-138):
the Fisher-Yates permutation recomputed per lane must match the scalar
oracle bit-for-bit on mixed uniform/straw2 maps — the "identical hosts"
layout — for firstn AND indep (including mapper.c:720-728's uniform
retry-offset special case), under reweight rejections and device
counts that exercise retries."""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.builder import add_simple_rule, make_bucket
from ceph_tpu.crush.compile import compile_map
from ceph_tpu.crush.mapper_jax import BatchMapper
from ceph_tpu.crush.types import (
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW2, CRUSH_BUCKET_UNIFORM,
    CrushMap)

N_X = 3000


def _mixed_map(n_hosts=4, devs_per_host=4, uniform_hosts=True):
    """root (straw2) -> hosts (uniform: the identical-chassis layout)
    -> devices."""
    m = CrushMap()
    hosts = []
    dev = 0
    for h in range(n_hosts):
        items = list(range(dev, dev + devs_per_host))
        dev += devs_per_host
        alg = CRUSH_BUCKET_UNIFORM if uniform_hosts \
            else CRUSH_BUCKET_STRAW2
        b = make_bucket(-(2 + h), alg, 1, items,
                        [0x10000] * devs_per_host)
        m.add_bucket(b)
        hosts.append(b.id)
    root = make_bucket(-1, CRUSH_BUCKET_STRAW2, 10, hosts,
                       [0x10000 * devs_per_host] * n_hosts)
    m.add_bucket(root)
    m.max_devices = dev
    return m, dev


def _assert_oracle_equal(m, rno, ndev, result_max, weights=None):
    weights = weights or [0x10000] * ndev
    bm = BatchMapper(m)
    xs = np.arange(N_X, dtype=np.int64)
    got = np.asarray(bm.do_rule(rno, xs, result_max, weights))
    rule = m.rules[rno]
    from ceph_tpu.crush.types import RULE_CHOOSE_INDEP, \
        RULE_CHOOSELEAF_INDEP
    indep = any(s.op in (RULE_CHOOSE_INDEP, RULE_CHOOSELEAF_INDEP)
                for s in rule.steps)
    for k in range(N_X):
        ref = mapper_ref.crush_do_rule(m, rno, k, result_max, weights)
        if indep:
            mine = list(got[k][:len(ref)])
            assert mine == ref, (k, mine, ref)
        else:
            mine = [v for v in got[k] if v >= 0]
            assert mine == ref, (k, mine, ref)


def test_uniform_firstn_chooseleaf_matches_oracle():
    m, ndev = _mixed_map()
    rno = add_simple_rule(m, -1, 1, mode="firstn")
    _assert_oracle_equal(m, rno, ndev, 3)


def test_uniform_indep_matches_oracle():
    # devs_per_host == 4 and numrep 4 exercises the size %% numrep == 0
    # uniform retry-offset special case (mapper.c:720-728)
    m, ndev = _mixed_map(n_hosts=5, devs_per_host=4)
    rno = add_simple_rule(m, -1, 1, mode="indep")
    _assert_oracle_equal(m, rno, ndev, 4)


def test_uniform_with_reweight_rejections():
    m, ndev = _mixed_map()
    rno = add_simple_rule(m, -1, 1, mode="firstn")
    weights = [0x10000] * ndev
    weights[2] = 0          # out device: forces retries through perm
    weights[9] = 0x8000     # half-weight: probabilistic rejection
    _assert_oracle_equal(m, rno, ndev, 3, weights)


def test_pure_uniform_flat_rule():
    """Uniform bucket as the direct choose target (type-0 domain)."""
    m = CrushMap()
    b = make_bucket(-1, CRUSH_BUCKET_UNIFORM, 1, list(range(7)),
                    [0x10000] * 7)
    m.add_bucket(b)
    m.max_devices = 7
    rno = add_simple_rule(m, -1, 0, mode="firstn")
    _assert_oracle_equal(m, rno, 7, 3)


def test_uniform_sizes_not_dividing_numrep():
    # size 5 hosts with numrep 3: pr wraps differently per r
    m, ndev = _mixed_map(n_hosts=3, devs_per_host=5)
    rno = add_simple_rule(m, -1, 1, mode="firstn")
    _assert_oracle_equal(m, rno, ndev, 3)


def test_list_buckets_still_refused():
    m = CrushMap()
    b = make_bucket(-1, CRUSH_BUCKET_LIST, 1, [0, 1, 2],
                    [0x10000] * 3)
    m.add_bucket(b)
    m.max_devices = 3
    with pytest.raises(ValueError):
        compile_map(m)
