"""Cross-op device-call coalescing: the async dispatch engine.

The GF(2^8) kernel sustains TB/s device-resident while the end-to-end
headline sits near the remote-dispatch tunnel's floor: every OSD EC
write used to issue its own synchronous device call and eat the ~0.9 ms
dispatch latency alone (ops/gf_kernel.py header).  This module closes
that gap the way serving systems do (Clipper's adaptive batching;
"The Tail at Scale"'s keep-the-pipeline-full): concurrent requests from
DIFFERENT ops/PGs stack on the batch axis into ONE padded device call.

Three mechanisms, one engine:

* **cross-op coalescing** — ``submit(key, fn, data)`` queues the
  request; the dispatch thread collects every queued request with the
  same ``key`` (same kernel + operand identity + trailing shape) into
  one call.  Flush policy: immediately while the engine is idle (a lone
  op never waits — single-op latency cannot regress), else accumulate
  until ``max_stripes`` or ``max_delay_us``, whichever first.  The
  batch is self-clocking: while batch N computes, batch N+1's requests
  pile up, exactly the adaptive-batching feedback loop.

* **shape bucketing** — the coalesced batch rounds UP to a power-of-two
  stripe count with all-zero padding rows (bit-exact for every kernel
  here: zeros encode to zeros under a linear code, and padded CRUSH
  lanes are sliced off before delivery).  The jit compile cache is then
  bounded by the bucket table, not by the distribution of client write
  sizes.

* **async double-buffered submission** — the dispatch thread issues the
  device call (the runtime acks before execution: h2d of batch N+1
  overlaps compute of batch N) and a completion thread materializes
  results in FIFO order, resolving per-request futures/continuations.
  ``max_in_flight`` bounds outstanding device calls (2 = classic double
  buffering).

* **mesh-sharded fan-out** — an engine built with a device ``mesh``
  places every coalesced batch ACROSS the mesh before the kernel sees
  it: the shape bucket rounds up to a multiple of the mesh size (every
  shard non-empty, the jit cache still bounded by the bucket table —
  now keyed by (bucket, mesh) because committed input shardings are
  part of jax's compile-cache key), the batch is ``device_put`` with a
  ``NamedSharding`` splitting the stripe/PG axis over the ``("dp",
  "ec")`` axes, and aux side arrays shard in lockstep.  XLA partitions
  the jitted kernel (GSPMD), results stay device-resident and sharded
  until the completion thread materializes them.  One flush saturates
  every chip instead of one; bit-exactness is untouched because the
  kernels are elementwise/row-independent along the coalesce axis.  In
  a multi-controller deployment (jax.distributed) the engine's own
  flushes are process-local data, so placement uses the GLOBAL mesh's
  process-local submesh — each process's engine saturates its ICI
  domain while collective SPMD work spans the full mesh.

Delivery-order contract: completions for one ``key`` are delivered in
submission order, on a single completion thread.  The OSD leans on this
for per-object log/commit ordering (osd/daemon._ec_write_committed).

Everything here is numpy + threading; jax enters only through the
``fn`` callables the submitters pass — and, on mesh-sharded engines,
through the lazily-built ``_MeshPlacement`` scaffolding — so importing
this module never pulls in the kernel stack (same rule as
ops.telemetry).
"""

from __future__ import annotations

import random
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ceph_tpu.common import failpoint, lockdep
from ceph_tpu.ops import telemetry
from ceph_tpu.qos.dmclock import BACKGROUND_BEST_EFFORT


class EngineWedgedError(RuntimeError):
    """The engine's thread-restart budget is exhausted: every pending
    and in-flight waiter has been failed with this error, ``flush()``
    raises it, and new submits run inline (never silently dropped,
    never hung)."""


class DispatchFuture:
    """Completion handle for one submitted request.

    Callbacks added before completion run on the engine's completion
    thread, in batch order then submission order — the delivery-order
    contract continuations rely on.  Callbacks added after completion
    run inline on the caller.
    """

    __slots__ = ("_ev", "_value", "_exc", "_cbs", "_lock")

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._exc: BaseException | None = None
        self._cbs: list = []
        self._lock = lockdep.make_lock("DispatchFuture::lock")

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("dispatch result not ready")
        if self._exc is not None:
            raise self._exc
        return self._value

    def exception(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("dispatch result not ready")
        return self._exc

    def add_done_callback(self, cb) -> None:
        with self._lock:
            if not self._ev.is_set():
                self._cbs.append(cb)
                return
        cb(self)

    def _deliver(self, value, exc: BaseException | None) -> None:
        with self._lock:
            if self._ev.is_set():
                # first delivery wins: a revived run-loop re-fanning
                # its batch, or _wedge racing the live completion
                # thread, must never overwrite a delivered result
                # (an acked op's value flipping to EngineWedgedError
                # — or the reverse — after callbacks already fired)
                return
            self._value = value
            self._exc = exc
            self._ev.set()
            cbs, self._cbs = self._cbs, []
        for cb in cbs:
            try:
                cb(self)
            except Exception as e:
                from ceph_tpu.common.logging import dout
                dout("dispatch", 0, "dispatch continuation failed: %r", e)


class _Request:
    __slots__ = ("key", "fn", "data", "aux", "stripes", "future",
                 "t_submit", "label", "cache_entries", "trace", "span",
                 "place", "fallback", "cost_tag")

    def __init__(self, key, fn, data, stripes, label=None,
                 cache_entries=None, aux=None, place=True,
                 fallback=None, cost_tag=None):
        self.place = place
        #: (tenant, dmclock class) for the device-time ledger; None
        #: lands in the visible _untagged bucket at completion
        self.cost_tag = cost_tag
        #: bit-exact host-path oracle for this request's kernel channel
        #: (ec_encode_ref / the host pattern decode / scalar CRUSH /
        #: the numpy ladder): the supervised-recovery ladder runs it
        #: when the device path stays broken past the retry budget, and
        #: an OPEN channel breaker routes batches straight to it.
        #: Requests sharing a key must agree on it (same submitter).
        self.fallback = fallback
        self.key = key
        self.fn = fn
        self.data = data
        self.aux = aux
        self.stripes = stripes
        self.future = DispatchFuture()
        self.t_submit = time.monotonic()
        self.label = label if label is not None else (
            key[0] if isinstance(key, tuple) and key
            and isinstance(key[0], str) else "dispatch")
        self.cache_entries = cache_entries
        # a traced submitter gets a per-request device span covering
        # the coalesced call (timed_kernel's span runs on the engine
        # thread, outside every op's trace context)
        from ceph_tpu.common import tracing
        tid = tracing.current()
        self.trace = (tid, tracing.current_span()) if tid else None
        self.span = None


class _Batch:
    __slots__ = ("out", "reqs", "slices", "exc", "t_dispatch", "misses",
                 "profile", "via_fallback")

    def __init__(self, out, reqs, slices, exc=None, t_dispatch=0.0,
                 misses=None, profile=None, via_fallback=False):
        self.out = out
        self.reqs = reqs
        self.slices = slices
        self.exc = exc
        self.t_dispatch = t_dispatch
        self.misses = misses
        #: the dispatch thread already served this batch from the host
        #: oracle (open breaker): completion must not re-enter the
        #: device-retry ladder on its error
        self.via_fallback = via_fallback
        #: dispatch-side half of the phase ledger (telemetry.PHASES):
        #: monotonic anchors + build/place/launch durations; the
        #: completion thread closes compute/materialize/deliver and
        #: records the batch profile.  None when the dispatch died
        #: before the ledger started.
        self.profile = profile


def bucket_stripes(n: int) -> int:
    """Power-of-two shape bucket for a batch of n rows (n >= 1)."""
    return 1 << max(0, (n - 1).bit_length())


def mesh_bucket_stripes(n: int, devices: int) -> int:
    """Shape bucket for a mesh of ``devices``: the power-of-two bucket
    rounded UP to a multiple of the mesh size, so the sharded leading
    axis divides evenly (jax rejects uneven NamedSharding placement)
    and every device's shard is non-empty.  For power-of-two meshes
    this is just max(bucket, devices); the bucket table stays bounded
    either way (it is a function of the pow-2 bucket)."""
    b = bucket_stripes(n)
    if devices > 1 and b % devices:
        b += devices - b % devices
    return max(b, devices)


def _mesh_shape(mesh) -> tuple[int, int]:
    """(dp, ec) gauge values for a mesh — the ONE place the
    missing-axis defaults live (a dp-only mesh is dp x 1, never
    dp x 0): (0, 0) means no mesh."""
    if mesh is None:
        return 0, 0
    shape = dict(mesh.shape)
    ec = int(shape.get("ec", 1))
    dp = int(shape.get("dp", max(1, int(mesh.size) // max(ec, 1))))
    return dp, ec


class _MeshPlacement:
    """Host-side placement scaffolding for a mesh-sharded engine.

    Built lazily on the first flush of an engine holding a mesh (so
    engines without one never import jax), it caches one
    ``NamedSharding`` per operand rank: the leading (stripe/PG) axis
    splits over every mesh axis, trailing axes replicate.  In a
    multi-controller deployment the engine's own flushes are
    process-local host data, so placement targets the GLOBAL mesh's
    process-local submesh (the process's ICI domain); single-process
    engines place over the full mesh.
    """

    __slots__ = ("mesh", "place_mesh", "devices", "_shardings")

    def __init__(self, mesh):
        import jax
        self.mesh = mesh
        self.place_mesh = (mesh.local_mesh if jax.process_count() > 1
                           else mesh)
        self.devices = int(self.place_mesh.size)
        self._shardings: dict = {}

    def sharding(self, ndim: int):
        s = self._shardings.get(ndim)
        if s is None:
            from jax.sharding import NamedSharding, PartitionSpec
            spec = PartitionSpec(tuple(self.place_mesh.axis_names),
                                 *([None] * (ndim - 1)))
            s = NamedSharding(self.place_mesh, spec)
            self._shardings[ndim] = s
        return s

    def put(self, arr):
        import jax
        return jax.device_put(arr, self.sharding(arr.ndim))


#: exception classes the retry ladder treats as PERMANENT (programming
#: errors — shape mismatches, bad operands): retrying cannot help and
#: the host oracle would fail identically, so they fan immediately
_PERMANENT_ERRORS = (ValueError, TypeError, KeyError, IndexError,
                     AttributeError)


class _Breaker:
    """Per-channel circuit breaker state (guarded by the engine cv).

    closed -> open after ``breaker_threshold`` consecutive device-path
    batch failures (each already past its retry budget); while open
    (or half-open, mid-probe) batches with a host fallback skip the
    device entirely; the background probe replays a retained one-stripe
    sample of the last failed batch and a success re-closes."""

    __slots__ = ("state", "consecutive", "probe")

    def __init__(self):
        self.state = telemetry.BREAKER_CLOSED
        self.consecutive = 0
        self.probe = None        # (fn, data_sample, aux_sample)


class DeviceDispatchEngine:
    """Per-CephContext coalescing dispatcher for batched device kernels.

    ``submit(key, fn, data)``: data is a numpy array whose LEADING axis
    is the coalesce axis (stripes for EC, x-lanes for CRUSH); fn maps a
    batched array of the same trailing shape to a device (or host)
    array with the matching leading axis.  All requests sharing ``key``
    must be mutually batchable (same fn semantics, same trailing
    shape); the key should therefore encode the operand identity and
    the trailing dimensions.
    """

    def __init__(self, *, max_stripes: int = 2048,
                 max_delay_us: float = 250.0, max_in_flight: int = 2,
                 name: str = "dispatch", stats=None, mesh=None):
        self.max_stripes = int(max_stripes)
        self.max_delay_us = float(max_delay_us)
        self.max_in_flight = max(1, int(max_in_flight))
        self.name = name
        self.stats = stats if stats is not None \
            else telemetry.dispatch_stats()
        #: ledger "engine" dimension: the stats sink decides (the two
        #: context engines are distinguished exactly this way), so
        #: per-test engines with private sinks still label sensibly
        self._ledger_engine = ("decode" if isinstance(
            self.stats, telemetry.DecodeDispatchStats) else "encode")
        #: jax.sharding.Mesh (or None): batches fan out across it —
        #: see the module docstring's mesh-sharded fan-out mechanism
        self._mesh = mesh
        self._placement: _MeshPlacement | None = None
        self._cv = lockdep.make_condition(
            f"DeviceDispatchEngine::cv({name})")
        self._pending: deque[_Request] = deque()
        #: per-key pending stripe totals, maintained incrementally so
        #: the flush-policy checks never rescan the queue
        self._key_totals: dict = {}
        self._inflight: deque[_Batch] = deque()
        self._building = 0          # batches being built/dispatched
        self._stop = False
        #: role -> live thread ("submit" dispatches, "complete"
        #: materializes); supervised — see _thread_main
        self._threads: dict[str, threading.Thread] = {}
        # -- fault domain (retry / breaker / supervision knobs; the
        # context wires them to the kernel_fault_* options) ----------
        self.fault_max_retries = 2
        self.fault_backoff_ms = 5.0
        self.fault_backoff_max_ms = 200.0
        self.breaker_threshold = 3
        self.probe_interval = 0.5
        self.thread_restarts = 4
        #: a run-loop that stayed healthy this long since its last
        #: death earns its restart budget back (like the breaker's
        #: consecutive counter): the budget bounds death STORMS, not
        #: isolated recovered deaths spread over an engine's lifetime
        self.thread_restart_window = 300.0
        #: channel (kernel family label) -> _Breaker, under self._cv
        self._breakers: dict[str, _Breaker] = {}
        self._probe_thread: threading.Thread | None = None
        self._probe_wake = threading.Event()
        self._deaths: dict[str, int] = {}
        self._death_t: dict[str, float] = {}
        self._wedged = False
        self._wedge_exc: BaseException | None = None
        self._jitter = random.Random()

    # -- mesh -----------------------------------------------------------------

    def set_mesh(self, mesh) -> None:
        """Swap the engine's device mesh (knob hot-reload).  Takes
        effect from the next flush; in-flight batches keep the
        placement they were built with (their fns re-place operands to
        match whatever sharding the batch actually carries, so late
        completion stays correct)."""
        with self._cv:
            self._mesh = mesh
            self._placement = None
        try:
            self.stats.set_mesh_shape(*_mesh_shape(mesh))
        except Exception:
            pass

    def _mesh_placement(self) -> _MeshPlacement | None:
        """The live placement scaffolding, built lazily on first use.
        A build failure (single-device backend, jax unavailable)
        disables the mesh loudly ONCE instead of failing every flush.

        Lock-free fast paths for the two common cases — no mesh, and
        placement already built: submitters probe this per op
        (placement_mesh) and must not pay the engine condvar for it.
        The unlocked attribute reads race only with set_mesh, and
        benignly: a stale answer delays the new placement by at most
        one flush, and every fn re-places operands to match whatever
        sharding its batch actually carries."""
        mesh = self._mesh
        placement = self._placement
        if mesh is None:
            return None
        if placement is not None and placement.mesh is mesh:
            if self.stats.mesh_devices == 0:
                # a stats clear() (tests/bench isolation) zeroed the
                # shape gauges: republish so the mesh gauge cannot
                # read "no mesh" next to a growing sharded-flush count
                self._publish_mesh_shape(placement)
            return placement
        with self._cv:
            mesh = self._mesh
            placement = self._placement
        if mesh is None:
            return None
        if placement is not None and placement.mesh is mesh:
            return placement
        try:
            placement = _MeshPlacement(mesh)
            if placement.devices <= 1:
                placement = None
        except Exception as e:
            from ceph_tpu.common.logging import dout
            dout("dispatch", 0, "%s: mesh placement unavailable, "
                 "running single-device: %r", self.name, e)
            placement = None
        with self._cv:
            if self._mesh is mesh:
                self._placement = placement
                if placement is None:
                    self._mesh = None
        if placement is not None:
            self._publish_mesh_shape(placement)
        return placement

    def _publish_mesh_shape(self, placement: _MeshPlacement) -> None:
        try:
            self.stats.set_mesh_shape(*_mesh_shape(placement.mesh))
        except Exception:
            pass

    def placement_mesh(self):
        """The mesh this engine's batches are actually placed over (the
        process-local submesh under jax.distributed), or None.
        Submitters use it to pre-replicate operand tables so the jitted
        kernel sees mesh-consistent shardings."""
        p = self._mesh_placement()
        return p.place_mesh if p is not None else None

    # -- lifecycle ------------------------------------------------------------

    def _ensure_threads(self) -> None:
        if self._threads:
            return
        for role, tgt in (("submit", self._dispatch_loop),
                          ("complete", self._complete_loop)):
            t = threading.Thread(target=self._thread_main,
                                 args=(role, tgt), daemon=True,
                                 name=f"{self.name}-{role}")
            self._threads[role] = t
            t.start()

    def _thread_main(self, role: str, tgt) -> None:
        """Run-loop supervisor: a loop death (failpoint-injected
        InjectedThreadDeath, or any escaped BaseException) is counted
        and the loop RE-ENTERED on this thread up to ``thread_restarts``
        times — the queued requests and in-flight batches stay where
        they are, so the revived loop re-fans them instead of wedging
        every waiter.  Past the budget the engine wedges: every pending
        future is failed with a loud EngineWedgedError and flush()
        raises it."""
        while True:
            try:
                tgt()
                return                      # clean exit (stop)
            except BaseException as e:      # noqa: BLE001 — supervised
                from ceph_tpu.common.logging import dout
                with self._cv:
                    now = time.monotonic()
                    prev = self._death_t.get(role)
                    if (prev is not None and now - prev
                            > float(self.thread_restart_window)):
                        # healthy since the last death: budget earned
                        # back — only a death STORM may wedge
                        self._deaths[role] = 0
                    self._death_t[role] = now
                    self._deaths[role] = n = self._deaths.get(role, 0) + 1
                    revive = (not self._stop
                              and n <= self.thread_restarts)
                try:
                    self.stats.record_thread_death(restarted=revive)
                except Exception:
                    pass
                dout("dispatch", 0,
                     "%s: %s run-loop died (%d/%d): %r%s", self.name,
                     role, n, self.thread_restarts, e,
                     " — reviving" if revive else " — WEDGED")
                if revive:
                    continue
                self._wedge(role, e)
                return

    def _wedge(self, role: str, cause: BaseException) -> None:
        """Restart budget exhausted: fail every waiter loudly (a
        stranded future wedges OSD wpend gates and client ops behind a
        silent timeout — the exact failure mode this forbids)."""
        exc = EngineWedgedError(
            f"{self.name}: {role} thread died "
            f"{self._deaths.get(role, 0)} times "
            f"(thread_restarts={self.thread_restarts}); last: {cause!r}")
        with self._cv:
            self._wedged = True
            self._wedge_exc = exc
            victims = [r.future for r in self._pending]
            self._pending.clear()
            self._key_totals.clear()
            for b in self._inflight:
                victims.extend(r.future for r in b.reqs)
            self._inflight.clear()
            self._cv.notify_all()
        self._probe_wake.set()
        for fut in victims:
            if not fut.done():
                fut._deliver(None, exc)

    def stop(self) -> bool:
        """Drain queued work, then stop both threads.  Returns True
        when both exited; a thread surviving its join timeout (wedged
        device call) stays in _threads so a later stop() can re-join.
        On a WEDGED engine every outstanding future has already been
        failed with EngineWedgedError — stop() returns False so
        shutdown paths log it."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._probe_wake.set()
        for t in list(self._threads.values()):
            t.join(timeout=5.0)
        self._threads = {r: t for r, t in self._threads.items()
                         if t.is_alive()}
        pt = self._probe_thread
        if pt is not None:
            pt.join(timeout=2.0)
        return not self._threads and not self._wedged

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for the queues to drain (futures may still be resolving
        for the last popped batch — wait on them for hard ordering).
        Raises EngineWedgedError instead of silently timing out when
        the engine's thread-restart budget is exhausted — a wedged
        engine can never drain, and the waiters have already been
        failed with the same error."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while (self._pending or self._building or self._inflight):
                if self._wedged:
                    raise self._wedge_exc
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.05))
            if self._wedged:
                raise self._wedge_exc
        return True

    def owns_current_thread(self) -> bool:
        """True when the caller IS one of this engine's own worker
        threads (dispatch/completion).  A submitter that would BLOCK on
        a future from such a thread must take a host path instead: the
        wait would starve the very thread that materializes batches and
        delivers results — a guaranteed self-deadlock.  BlueStore's
        batched-csum flush checks this before riding the engine (store
        commits run on engine completion threads via EC-write and
        recovery continuations)."""
        with self._cv:
            return threading.current_thread() in self._threads.values()

    # -- submit ---------------------------------------------------------------

    def submit(self, key, fn, data, *, label=None,
               cache_entries=None, aux=None,
               place: bool = True, fallback=None,
               cost_tag=None) -> DispatchFuture:
        """``aux``: optional tuple of per-stripe side arrays (each with
        the SAME leading axis as ``data``) that coalesce alongside it —
        concatenated per component, edge-padded (last row repeated) to
        the shape bucket, and passed to ``fn(batch, *aux_batches)``.  The batched GF
        decode rides this: the per-stripe erasure-pattern index travels
        as aux so requests with DIFFERENT recovery matrices still share
        one device call.  All requests under one key must agree on aux
        arity and trailing shapes (encode that in the key).

        ``place=False`` opts this request out of mesh-sharded placement
        (host-runtime fns — numpy/native codecs — would only gather the
        sharded batch straight back).  Requests sharing a key must
        agree on it (encode the runtime in the key, as the codecs do).

        ``fallback``: optional bit-exact host oracle
        ``fallback(batch, *aux) -> array`` for this kernel channel.
        With one, a batch whose device path fails past the bounded
        retry ladder is served by the oracle instead of fanning the
        error, and an open channel breaker routes batches straight to
        it while the background probe retries the device (see the
        module's failure-domain notes).

        ``cost_tag``: optional (tenant, dmclock_class) pair for the
        tenant-attributed device-time ledger.  Batches still coalesce
        ACROSS tenants exactly as before (the tag plays no part in
        batching); at completion the batch's busy integral
        (compute_s × devices) is apportioned to each request by stripe
        share and accounted under its tag in
        ``telemetry.TenantDeviceStats``.  Untagged requests land in
        the visible ``_untagged`` bucket — never dropped, so the
        ledger's tenant sum conserves the engine's busy-seconds."""
        # analysis: allow[blocking] -- caller-input normalization: submit() receives host arrays (numpy/bytes), not device values
        data = np.asarray(data)
        stripes = int(data.shape[0]) if data.ndim else 1
        if aux is not None:
            # analysis: allow[blocking] -- aux side arrays are host numpy by contract
            aux = tuple(np.asarray(a) for a in aux)
            for a in aux:
                if not a.ndim or a.shape[0] != stripes:
                    raise ValueError(
                        f"aux leading axis {a.shape} != stripes {stripes}")
        req = _Request(key, fn, data, stripes, label=label,
                       cache_entries=cache_entries, aux=aux, place=place,
                       fallback=fallback, cost_tag=cost_tag)
        with self._cv:
            if not self._stop and not self._wedged:
                self._ensure_threads()
                self._pending.append(req)
                self._key_totals[req.key] = (
                    self._key_totals.get(req.key, 0) + stripes)
                self.stats.record_submit(stripes)
                self._cv.notify_all()
                return req.future
        # engine stopped: run inline so callers never hang.  First wait
        # out any still-draining queues — stop() lets the threads finish
        # every queued batch, and an inline run jumping that drain would
        # break the per-key submission-order contract the OSD's EC
        # log/commit ordering rides on.  Timed waits, not a bare wait:
        # the exiting threads' last notify may already have fired.
        # EXCEPTION: a continuation re-submitting from one of this
        # engine's OWN threads (an OSD completion callback re-entering
        # the engine mid-stop) must not wait on a drain only itself can
        # advance — that is a guaranteed self-deadlock wedging the
        # completion thread and stranding every outstanding future.
        # Running inline immediately forfeits ordering against the
        # still-queued work, which is strictly better than the wedge.
        # (A WEDGED engine takes the same inline path: its queues were
        # already failed and drained, so the wait below is a no-op and
        # new work is served host-side rather than dropped or hung.)
        me = threading.current_thread()
        with self._cv:
            if me not in self._threads.values():
                while self._pending or self._building or self._inflight:
                    self._cv.wait(0.05)
        # inline OUTSIDE the engine lock, so a device call here never
        # serializes concurrent submit()/flush()/stop() callers
        # (and future callbacks never fire under the lock)
        req.future._deliver(*self._run_inline(fn, data, aux, fallback))
        return req.future

    @staticmethod
    def _run_inline(fn, data, aux=None, fallback=None):
        try:
            out = fn(data) if aux is None else fn(data, *aux)
            # analysis: allow[blocking] -- stopped-engine inline fallback materializes deliberately (no pipeline left to stall)
            return np.asarray(out), None
        except BaseException as e:     # noqa: BLE001 — delivered to waiter
            if fallback is not None and not isinstance(
                    e, _PERMANENT_ERRORS):
                try:
                    out = (fallback(data) if aux is None
                           else fallback(data, *aux))
                    # analysis: allow[blocking] -- host-oracle result is already numpy
                    return np.asarray(out), None
                except BaseException as e2:  # noqa: BLE001 — to waiter
                    return None, e2
            return None, e

    # -- dispatch thread ------------------------------------------------------

    def _key_stripes(self, key) -> int:
        return self._key_totals.get(key, 0)

    def _dispatch_loop(self) -> None:
        while True:
            # thread-death injection site: OUTSIDE every handler, so
            # the raise reaches _thread_main's supervisor (the real
            # failure this models is a loop bug, not a batch error)
            failpoint.hit("dispatch.dispatch_thread_death")
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait()
                if not self._pending:
                    if self._stop:
                        self._cv.notify_all()
                        return
                    continue
                first = self._pending[0]
                deadline = first.t_submit + self.max_delay_us * 1e-6
                # accumulate while the pipeline is busy; an idle engine
                # flushes immediately (lone ops never wait).  A ripe
                # batch (full OR past deadline) still waits for a free
                # in-flight slot — max_in_flight is a hard bound on
                # outstanding device calls, not just a deadline gate
                while not self._stop:
                    now = time.monotonic()
                    in_use = len(self._inflight) + self._building
                    if in_use == 0:
                        break              # idle: flush immediately
                    if in_use < self.max_in_flight and (
                            self._key_stripes(first.key)
                            >= self.max_stripes
                            or now >= deadline):
                        break              # ripe + slot free
                    self._cv.wait(max(1e-4, min(deadline - now, 0.05))
                                  if now < deadline else 0.05)
                # collect the batch in ONE pass, partitioning the
                # oldest request's key out of the deque: per-key FIFO
                # is preserved (once size-capped, no later same-key
                # request may jump into this batch), and nothing is
                # rescanned or removed one-by-one
                reqs: list[_Request] = []
                keep: deque[_Request] = deque()
                total = 0
                capped = False
                for r in self._pending:
                    if r.key != first.key or capped:
                        keep.append(r)
                    elif reqs and total + r.stripes > self.max_stripes:
                        capped = True
                        keep.append(r)
                    else:
                        reqs.append(r)
                        total += r.stripes
                self._pending = keep
                left = self._key_totals.get(first.key, 0) - total
                if left > 0:
                    self._key_totals[first.key] = left
                else:
                    self._key_totals.pop(first.key, None)
                if self._stop:
                    reason = "stop"
                elif capped or total >= self.max_stripes:
                    reason = "full"    # size-capped, incl. next-would-overflow
                elif not (self._inflight or self._building):
                    reason = "idle"
                else:
                    reason = "timeout"
                depth = len(self._pending) + len(reqs)
                self._building += 1
            self._dispatch_batch(reqs, total, reason, depth)

    def _dispatch_batch(self, reqs: list[_Request], total: int,
                        reason: str, depth: int) -> None:
        """Build the padded batch and issue the device call (runs
        OUTSIDE the engine lock: a first-shape call traces+compiles)."""
        now = time.monotonic()
        # slices first (pure arithmetic, cannot fail): the completion
        # thread zips reqs against slices, so every request must have
        # one even when the batch build below dies
        slices, off = [], 0
        for r in reqs:
            slices.append((off, off + r.stripes))
            off += r.stripes
        exc = None
        out = None
        misses = None
        profile = None
        placement = None
        devices = 1
        bucket, pad = total, 0
        via_fallback = False
        channel = reqs[0].label
        try:
            # EVERYTHING fallible sits inside this try — mesh lookup,
            # bucketing, breaker routing, the profile dict, pad
            # allocation / concatenate (MemoryError under pressure,
            # shape mismatch), span bookkeeping, the device call itself
            # — and lands in exc to fan to the batch's futures.  An
            # exception escaping this frame would reach the supervisor
            # with _building already incremented and the reqs already
            # partitioned out of _pending: the revived loop could never
            # re-fan them, flush() would time out silently forever —
            # the exact silent-wedge failure mode this PR forbids.
            #
            # mesh-sharded engines round the bucket up to a multiple of
            # the mesh size (every shard non-empty, even NamedSharding
            # split); place=False requests keep the seed's pure pow-2
            # bucket, and 0-d submits (no batch axis to split — padding
            # would have to concatenate onto a scalar) always run
            # unplaced
            placement = (self._mesh_placement()
                         if reqs[0].place and reqs[0].data.ndim
                         else None)
            devices = placement.devices if placement is not None else 1
            bucket = (mesh_bucket_stripes(total, devices)
                      if devices > 1 else bucket_stripes(total))
            pad = bucket - total
            # an OPEN (or half-open) breaker routes the batch straight
            # to the host oracle — no device attempt, no retry ladder;
            # the background probe owns re-trying the device path
            via_fallback = (reqs[0].fallback is not None
                            and self._breaker_routed(channel))
            if via_fallback:
                placement = None
                devices = 1
                bucket = bucket_stripes(total)
                pad = bucket - total
            # phase ledger (telemetry.PHASES): contiguous monotonic
            # marks — queue_wait ended at `now`; build/place/launch
            # close below; the completion thread closes compute/
            # materialize/deliver so the phase sum reconstructs
            # submit→delivery wall-clock exactly
            profile = {"t_submit0": reqs[0].t_submit, "t0": now,
                       "build": 0.0, "place": 0.0, "launch": 0.0,
                       "t_launch_end": now, "bucket": bucket,
                       "devices": devices, "stripes": total,
                       "family": reqs[0].label}
            batch_arr, aux_batch = self._assemble(reqs, pad)
            t_build_end = time.monotonic()
            profile["build"] = t_build_end - now
            if not via_fallback:
                # h2d boundary failpoint: fires for EVERY device-path
                # batch — on an unmeshed engine the transfer is
                # implicit in the kernel call, but the fault being
                # modeled (h2d failure) exists regardless, and chaos
                # coverage must not silently shrink to meshed hosts
                failpoint.hit("dispatch.device_put", tag=channel)
            if placement is not None:
                # device_put with the sharding on dispatch: the batch
                # (and its aux arrays, in lockstep) split their leading
                # axis across the mesh BEFORE the kernel fn runs, so
                # the jitted call compiles partitioned (GSPMD) and its
                # result stays sharded until the completion thread
                # materializes it.  A placement failure lands in exc
                # and fans to the batch's futures like any build error.
                batch_arr = placement.put(batch_arr)
                aux_batch = tuple(placement.put(a) for a in aux_batch)
            t_place_end = time.monotonic()
            profile["place"] = t_place_end - t_build_end
            traced = [r for r in reqs if r.trace is not None]
            if traced:
                from ceph_tpu.common import tracing
                for r in traced:
                    r.span = tracing.begin_span(
                        f"device {r.label}", "device",
                        trace_id=r.trace[0], parent_span_id=r.trace[1])
                    if r.span is not None:
                        # the per-phase story a slow traced op needs:
                        # how long it queued for coalescing company and
                        # how long the padded batch took to assemble,
                        # next to the existing h2d/compute/d2h events
                        tracing.span_event(
                            r.span, "queue-wait "
                            f"{(now - r.t_submit) * 1e3:.3f}ms")
                        tracing.span_event(
                            r.span,
                            f"build {profile['build'] * 1e3:.3f}ms")
                        tracing.span_event(r.span, f"h2d {r.data.nbytes}B")
            before = None
            if reqs[0].cache_entries is not None and not via_fallback:
                try:
                    before = reqs[0].cache_entries()
                except Exception:
                    before = None
            if via_fallback:
                # host oracle on the dispatch thread — exactly where a
                # cpu-runtime fn would run; the result is already host
                # numpy, so the completion thread's materialize is free
                out = reqs[0].fallback(batch_arr, *aux_batch)
            else:
                failpoint.hit("dispatch.launch", tag=channel)
                out = reqs[0].fn(batch_arr, *aux_batch)  # async dispatch
            profile["t_launch_end"] = time.monotonic()
            # span bookkeeping + the cache probe sit between place and
            # launch: charge them to launch so the ledger stays gapless
            profile["launch"] = profile["t_launch_end"] - t_place_end
            if before is not None:
                try:
                    misses = max(0, reqs[0].cache_entries() - before)
                except Exception:
                    misses = None
        except BaseException as e:          # noqa: BLE001 — fan to futures
            exc = e
        finally:
            try:
                self.stats.record_batch(
                    requests=len(reqs), stripes=total, padded=pad,
                    reason=reason, delays=[now - r.t_submit for r in reqs],
                    depth=depth, devices=devices,
                    shard_stripes=(bucket // devices if devices > 1
                                   else 0))
            except Exception:
                pass
            victims = None
            with self._cv:
                self._building -= 1
                if self._wedged:
                    # the completion side wedged while this batch was
                    # building: queueing it would strand its futures
                    # behind a thread that will never come back
                    victims = [r.future for r in reqs]
                else:
                    self._inflight.append(
                        _Batch(out, reqs, slices, exc,
                               t_dispatch=time.monotonic(),
                               misses=misses, profile=profile,
                               via_fallback=via_fallback))
                self.stats.set_in_flight(len(self._inflight)
                                         + self._building)
                self._cv.notify_all()
            if victims is not None:
                for fut in victims:
                    if not fut.done():
                        fut._deliver(None, self._wedge_exc)

    # -- completion thread ----------------------------------------------------

    def _complete_loop(self) -> None:
        while True:
            # thread-death injection site: outside every handler (see
            # _dispatch_loop) — the satellite regression this guards:
            # a dead completion thread used to wedge flush()/stop()
            # into silent timeouts with every waiter stranded
            failpoint.hit("dispatch.complete_thread_death")
            with self._cv:
                while not self._inflight:
                    if (self._stop and not self._pending
                            and not self._building):
                        return
                    self._cv.wait(0.05 if self._stop else None)
                batch = self._inflight[0]
            channel = batch.reqs[0].label
            host, exc = None, batch.exc
            t_ready = t_mat = 0.0
            if exc is None:
                try:
                    # split device compute from d2h: waiting out the
                    # async execution first (free — the work is already
                    # in flight) leaves np.asarray measuring only the
                    # materialize copy.  compute is anchored at launch
                    # end, so completion-thread pickup wait (which
                    # overlaps execution under double buffering) is
                    # attributed to compute, keeping the ledger gapless.
                    if not batch.via_fallback:
                        failpoint.hit("dispatch.block_until_ready",
                                      tag=channel)
                    wait = getattr(batch.out, "block_until_ready", None)
                    if wait is not None:
                        try:
                            wait()
                        except Exception:
                            pass   # np.asarray below surfaces the error
                    t_ready = time.monotonic()
                    host = np.asarray(batch.out)   # d2h materialize
                    t_mat = time.monotonic()
                except BaseException as e:         # noqa: BLE001
                    exc = e
            # supervised recovery: a failed device-path batch walks the
            # bounded retry ladder, then the channel's host oracle; a
            # batch the dispatch thread already served via the oracle
            # never re-enters (its error is final)
            if batch.via_fallback:
                # same rule as the recovery ladder below: the "launch"
                # anchor timed the host oracle, not a device call —
                # recording it would let an outage dominate the steady
                # device phase histograms with host-path runtimes
                batch.profile = None
                if exc is None:
                    total = batch.slices[-1][1] if batch.slices else 0
                    self.stats.record_fallback(total)
            elif exc is not None:
                host, exc, how = self._recover_batch(batch, exc)
                if how is not None:
                    batch.profile = None   # phase anchors now span the
                    # recovery ladder: keep the steady-state ledger
                    # clean rather than record a fabricated profile
                    t_ready = t_mat = time.monotonic()
            else:
                self._record_device_ok(channel)
            with self._cv:
                if self._inflight and self._inflight[0] is batch:
                    self._inflight.popleft()
                self.stats.set_in_flight(len(self._inflight)
                                         + self._building)
                self._cv.notify_all()
            dt = time.monotonic() - batch.t_dispatch
            for req, (a, b) in zip(batch.reqs, batch.slices):
                if req.span is not None:
                    # the batch is already popped from _inflight: an
                    # escaped span-sink error here would revive the
                    # loop with this batch's remaining futures stranded
                    # forever — tracing must never wedge completions
                    try:
                        from ceph_tpu.common import tracing
                        if exc is None:
                            tracing.span_event(req.span,
                                               f"compute {dt * 1e3:.3f}ms")
                            tracing.span_event(
                                req.span, f"d2h {host[a:b].nbytes}B")
                        attrs = {"kernel": req.label,
                                 "batch": len(batch.reqs),
                                 "coalesced": len(batch.reqs) > 1,
                                 "error": exc is not None}
                        if batch.misses is not None:
                            attrs["retrace"] = batch.misses > 0
                        tracing.set_attrs(req.span, **attrs)
                        tracing.finish_span(req.span)
                    except Exception:
                        pass
                try:
                    if exc is not None:
                        req.future._deliver(None, exc)
                    else:
                        req.future._deliver(host[a:b], None)
                except BaseException as e:  # noqa: BLE001 — see below
                    # _deliver shields continuations with `except
                    # Exception` only; one raising past that (SystemExit
                    # in a done-callback) would escape here AFTER the
                    # batch was popped — the supervisor would revive the
                    # loop, but nothing could ever re-fan this batch, so
                    # its remaining futures would hang forever.  The
                    # future itself is already resolved (value set
                    # before callbacks run): log loudly and keep fanning.
                    from ceph_tpu.common.logging import dout
                    dout("dispatch", 0,
                         "%s: continuation for %s raised past Exception"
                         " (swallowed to protect the batch fan-out): %r",
                         self.name, req.label, e)
            self.stats.record_complete(len(batch.reqs))
            if exc is None and batch.profile is not None:
                pr = batch.profile
                t_end = time.monotonic()
                try:
                    self.stats.phases.record_batch(
                        pr["family"],
                        phases={"queue_wait": pr["t0"] - pr["t_submit0"],
                                "build": pr["build"],
                                "place": pr["place"],
                                "launch": pr["launch"],
                                "compute": t_ready - pr["t_launch_end"],
                                "materialize": t_mat - t_ready,
                                "deliver": t_end - t_mat},
                        e2e_s=t_end - pr["t_submit0"],
                        requests=len(batch.reqs),
                        stripes=pr["stripes"], bucket=pr["bucket"],
                        devices=pr["devices"], misses=batch.misses)
                except Exception:
                    pass   # profiling must never wedge completions
                try:
                    # tenant apportionment: the SAME busy integral the
                    # phase ledger just accumulated (compute × devices),
                    # split across the batch's requests by stripe share
                    # — shares sum to 1 over the real stripes (padding
                    # carries no tag and no share), so the per-tenant
                    # ledger conserves busy_seconds exactly
                    busy = (t_ready - pr["t_launch_end"]) * pr["devices"]
                    total = max(1, pr["stripes"])
                    groups: dict = {}
                    for req in batch.reqs:
                        tag = req.cost_tag
                        if tag is None:
                            tenant, klass = None, ""
                        elif isinstance(tag, str):
                            tenant, klass = tag, ""
                        else:
                            tenant, klass = tag[0], tag[1]
                        g = groups.setdefault(
                            (tenant, klass, req.label), [0, 0, []])
                        g[0] += req.stripes
                        g[1] += 1
                        g[2].append(pr["t0"] - req.t_submit)
                    ledger = telemetry.tenant_stats()
                    for (tenant, klass, chan), (s, n, waits) \
                            in groups.items():
                        ledger.record_batch(
                            tenant, klass,
                            engine=self._ledger_engine, channel=chan,
                            device_seconds=busy * (s / total),
                            requests=n, stripes=s, queue_waits=waits)
                except Exception:
                    pass   # the ledger must never wedge completions


    # -- supervised recovery (retry ladder, breaker, probe) -------------------

    @staticmethod
    def _assemble(reqs: list[_Request], pad: int):
        """THE batch-assembly contract, shared by the dispatch path and
        the recovery ladder (a retried/fallback batch must present the
        exact layout the original device batch had, or the completion
        thread's slices lie).  Data pads with zero stripes; aux side
        arrays coalesce in lockstep with data — same concatenation
        order — but padding REPEATS the last row (edge padding) rather
        than writing zeros: aux rows are categorical (the decode's
        pattern index), and zero rows would invent category 0 in every
        padded batch — inflating the distinct-patterns telemetry and
        gathering a matrix no live stripe asked for.  Repeating a real
        row keeps the category set exact; the padded DATA rows are
        still all-zero, so whatever the repeated row selects computes
        zeros that are sliced off before delivery."""
        arrays = [r.data for r in reqs]
        if pad:
            arrays.append(np.zeros((pad,) + reqs[0].data.shape[1:],
                                   dtype=reqs[0].data.dtype))
        data = arrays[0] if len(arrays) == 1 \
            else np.concatenate(arrays, axis=0)
        aux = ()
        if reqs[0].aux is not None:
            for j in range(len(reqs[0].aux)):
                parts = [r.aux[j] for r in reqs]
                if pad:
                    parts.append(np.repeat(parts[-1][-1:], pad, axis=0))
                aux += (parts[0] if len(parts) == 1
                        else np.concatenate(parts, axis=0),)
        return data, aux

    @classmethod
    def _build_host_batch(cls, reqs: list[_Request]):
        """Rebuild the padded HOST batch for a retry/fallback run (the
        original batch may be a device-placed array whose backing
        devices are exactly what failed).  Pure pow-2 bucket, no
        placement — recovery runs single-device; every kernel here is
        bit-exact regardless of sharding."""
        total = sum(r.stripes for r in reqs)
        pad = (bucket_stripes(total) - total) if reqs[0].data.ndim else 0
        return cls._assemble(reqs, pad)

    def _recover_batch(self, batch: _Batch, exc: BaseException):
        """The failure ladder for one device-path batch: bounded
        retries with exponential backoff + jitter (transient errors
        only), then the channel's bit-exact host oracle, then fan the
        error.  Runs on the completion thread — holding the FIFO head
        during recovery is exactly the delivery-order contract.
        Returns (host_result, exc, how) with how in
        {"retry", "fallback", None}."""
        reqs = batch.reqs
        channel = reqs[0].label
        transient = not isinstance(exc, _PERMANENT_ERRORS)
        if transient and not self._breaker_routed(channel):
            for attempt in range(max(0, int(self.fault_max_retries))):
                delay = min(float(self.fault_backoff_max_ms),
                            float(self.fault_backoff_ms)
                            * (2 ** attempt)) / 1e3
                # jittered exponential backoff: decorrelates retry
                # storms across engines/channels (Tail at Scale rule)
                time.sleep(delay * (0.5 + 0.5 * self._jitter.random()))
                try:
                    data, aux = self._build_host_batch(reqs)
                    failpoint.hit("dispatch.launch", tag=channel)
                    out = reqs[0].fn(data, *aux)
                    failpoint.hit("dispatch.block_until_ready",
                                  tag=channel)
                    wait = getattr(out, "block_until_ready", None)
                    if wait is not None:
                        wait()
                    # analysis: allow[blocking] -- recovery materializes synchronously by design (the pipeline head is already stalled on this batch)
                    host = np.asarray(out)
                except BaseException as e:    # noqa: BLE001 — ladder
                    exc = e
                    self.stats.record_retry(False)
                    if isinstance(e, _PERMANENT_ERRORS):
                        break
                    continue
                self.stats.record_retry(True)
                self._record_device_ok(channel)
                return host, None, "retry"
        if transient:
            self._record_device_failure(channel, reqs)
        fb = reqs[0].fallback
        if fb is not None and transient:
            try:
                data, aux = self._build_host_batch(reqs)
                # analysis: allow[blocking] -- host-oracle result is already numpy
                host = np.asarray(fb(data, *aux))
            except BaseException as e:        # noqa: BLE001 — to waiters
                return None, e, None
            total = batch.slices[-1][1] if batch.slices else 0
            self.stats.record_fallback(total)
            return host, None, "fallback"
        return None, exc, None

    def _breaker_routed(self, channel: str) -> bool:
        """True while this channel's batches must take the host oracle
        (breaker open or mid-probe).  Lock-free empty-dict fast path:
        the common case is no breaker has ever tripped."""
        if not self._breakers:
            return False
        with self._cv:
            b = self._breakers.get(channel)
            return (b is not None
                    and b.state != telemetry.BREAKER_CLOSED)

    def _record_device_ok(self, channel: str) -> None:
        if not self._breakers:
            return
        with self._cv:
            b = self._breakers.get(channel)
            if b is None or (b.consecutive == 0
                             and b.state == telemetry.BREAKER_CLOSED):
                return
            b.consecutive = 0
            changed = b.state != telemetry.BREAKER_CLOSED
            b.state = telemetry.BREAKER_CLOSED
            b.probe = None
        if changed:
            self.stats.record_breaker(channel,
                                      telemetry.BREAKER_CLOSED)

    def _record_device_failure(self, channel: str,
                               reqs: list[_Request]) -> None:
        """One batch exhausted its device retries.  Past the threshold
        the channel breaker OPENS: a one-stripe sample of this batch is
        retained for the background probe, and every later batch with a
        fallback routes host-side until a probe heals the device."""
        opened = False
        with self._cv:
            b = self._breakers.get(channel)
            if b is None:
                b = self._breakers[channel] = _Breaker()
            b.consecutive += 1
            if (b.state == telemetry.BREAKER_CLOSED
                    and reqs[0].fallback is not None
                    and b.consecutive
                    >= max(1, int(self.breaker_threshold))):
                b.state = telemetry.BREAKER_OPEN
                r0 = reqs[0]
                sample = (r0.data[:1].copy() if r0.data.ndim
                          else r0.data.copy())
                auxs = (None if r0.aux is None
                        else tuple(a[:1].copy() for a in r0.aux))
                b.probe = (r0.fn, sample, auxs)
                opened = True
        if opened:
            self.stats.record_breaker(channel, telemetry.BREAKER_OPEN)
            self._ensure_probe_thread()

    def _ensure_probe_thread(self) -> None:
        with self._cv:
            if self._stop or self._wedged:
                return
            t = self._probe_thread
            if t is not None and t.is_alive():
                return
            self._probe_wake.clear()
            t = threading.Thread(target=self._probe_loop, daemon=True,
                                 name=f"{self.name}-probe")
            self._probe_thread = t
            t.start()

    def _probe_loop(self) -> None:
        """Background device-path probe: while any channel breaker is
        open, periodically replay its retained one-stripe sample
        through the device path; success re-closes the breaker and
        traffic returns to the device on the next flush.  Exits (and
        is respawned on the next open) once every breaker is closed."""
        while True:
            self._probe_wake.wait(max(0.05, float(self.probe_interval)))
            probes = []
            with self._cv:
                if self._stop or self._wedged:
                    self._probe_thread = None
                    return
                for ch, b in self._breakers.items():
                    if (b.state != telemetry.BREAKER_CLOSED
                            and b.probe is not None):
                        b.state = telemetry.BREAKER_HALF_OPEN
                        probes.append((ch, b, b.probe))
                if not probes:
                    self._probe_thread = None
                    return
            for ch, b, (fn, data, aux) in probes:
                self.stats.record_breaker(
                    ch, telemetry.BREAKER_HALF_OPEN)
                ok = False
                try:
                    failpoint.hit("dispatch.device_put", tag=ch)
                    failpoint.hit("dispatch.launch", tag=ch)
                    out = fn(data) if aux is None else fn(data, *aux)
                    failpoint.hit("dispatch.block_until_ready", tag=ch)
                    wait = getattr(out, "block_until_ready", None)
                    if wait is not None:
                        wait()
                    # analysis: allow[blocking] -- probe thread materializes its own one-stripe sample; nothing queues behind it
                    np.asarray(out)
                    ok = True
                except Exception:
                    ok = False
                self.stats.record_probe(ok)
                with self._cv:
                    if b.state == telemetry.BREAKER_HALF_OPEN:
                        if ok:
                            b.state = telemetry.BREAKER_CLOSED
                            b.consecutive = 0
                            b.probe = None
                        else:
                            b.state = telemetry.BREAKER_OPEN
                    state = b.state
                self.stats.record_breaker(ch, state)

    def breaker_states(self) -> dict[str, int]:
        """channel -> telemetry.BREAKER_* for this engine (tests and
        the thrasher's reconvergence gate)."""
        with self._cv:
            return {ch: b.state for ch, b in self._breakers.items()}


# ---------------------------------------------------------------------------
# CRUSH bulk-remap submit API (ops.crush_kernel's flat_firstn, coalesced)
# ---------------------------------------------------------------------------

#: mesh-replicated CRUSH operand tables, LRU-cached per (mesh, engine
#: key): the engine key already digests the operand content (bucket
#: ids/weights/reweight or mapper+rule+reweight), so repeated flushes
#: against the same map state reuse one broadcast instead of
#: re-uploading the tables per flush — the same residency rule
#: make_encoder and the decode pattern snapshot follow
_PLACED_OPS_CAP = 32
_placed_ops: OrderedDict = OrderedDict()
_placed_ops_lock = lockdep.make_lock("dispatch::placed_operands")


def _replicate_cached(mesh, cache_key, build):
    """build() -> operands device_put-replicated over ``mesh``, cached
    under (mesh, cache_key) — true LRU (move-to-end on hit, evict the
    single least-recent entry past the cap), the same OrderedDict
    idiom the codec recovery caches use; meshes are hashable.
    build() runs OUTSIDE the lock; a racing duplicate broadcast is
    idempotent."""
    k = (mesh, cache_key)
    with _placed_ops_lock:
        v = _placed_ops.get(k)
        if v is not None:
            _placed_ops.move_to_end(k)
            return v
    v = build()
    with _placed_ops_lock:
        _placed_ops[k] = v
        _placed_ops.move_to_end(k)
        while len(_placed_ops) > _PLACED_OPS_CAP:
            _placed_ops.popitem(last=False)
    return v


def submit_flat_firstn(engine: DeviceDispatchEngine, x, ids, weights,
                       reweight, *, numrep: int, tries: int = 51,
                       key=None, cost_tag=None) -> DispatchFuture:
    """Submit a bulk PG remap through the engine: concurrent remap
    requests against the SAME map state coalesce on the x axis into one
    device call (the ParallelPGMapper thread pool collapsed into one
    batched kernel invocation).  Padded lanes (x=0) compute garbage
    placements that are sliced off before delivery — bit-exactness of
    the delivered rows is untouched.

    ``key`` defaults to a digest of the bucket/reweight operands; pass
    an explicit (epoch, rule)-style key when the caller already knows
    the map identity to skip the hashing.
    """
    ids = np.asarray(ids, dtype=np.int32)
    weights = np.asarray(weights, dtype=np.int64)
    reweight = np.asarray(reweight, dtype=np.int64)
    if key is None:
        key = ("crush_firstn", numrep, tries,
               hash(ids.tobytes()), hash(weights.tobytes()),
               hash(reweight.tobytes()))

    def fn(xs, key=key):
        from ceph_tpu.ops.crush_kernel import flat_firstn
        i, w, rw = ids, weights, reweight
        mesh = getattr(getattr(xs, "sharding", None), "mesh", None)
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            # host-side placement scaffolding, not traced compute: the
            # engine handed us a mesh-sharded batch, so replicate the
            # bucket/reweight operands over the same mesh — the jitted
            # kernel then compiles with consistent shardings (sharded
            # x, replicated tables) instead of erroring on mixed
            # committed device sets.  Cached per (mesh, key): the key
            # digests the operand content, so same-map flushes reuse
            # one broadcast.
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            i, w, rw = _replicate_cached(
                mesh, key,
                lambda: jax.device_put(
                    (i, w, rw), NamedSharding(mesh, PartitionSpec())))
        return flat_firstn(xs, i, w, rw, numrep=numrep, tries=tries)

    def host_oracle(xs, numrep=numrep, tries=tries):
        # bit-exact scalar CRUSH (crush.mapper_ref) — the breaker's
        # host-path degradation for this channel
        from ceph_tpu.crush.mapper_ref import flat_firstn_ref
        rows = flat_firstn_ref(np.asarray(xs), ids, weights, reweight,
                               numrep=numrep, tries=tries)
        return np.asarray(rows, dtype=np.int32)

    return engine.submit(key, fn, np.asarray(x, dtype=np.uint32),
                         label="crush_firstn", fallback=host_oracle,
                         cost_tag=cost_tag)


def submit_do_rule(engine: DeviceDispatchEngine, mapper, ruleno: int,
                   xs, result_max: int, reweight, *,
                   key=None, cost_tag=None) -> DispatchFuture:
    """Submit a general-rule bulk PG remap (BatchMapper.do_rule)
    through the engine.  Pool remaps for the SAME (map, rule, size,
    reweight) — e.g. several pools sharing one crush rule, or several
    OSD daemons in one context advancing the same epoch — coalesce on
    the x axis into ONE device call.  Padded lanes (x=0) compute
    garbage placements that are sliced off before delivery, exactly
    like submit_flat_firstn.

    ``mapper`` is a crush.mapper_jax.BatchMapper (or anything with its
    ``do_rule`` signature); ``key`` defaults to the mapper identity +
    rule + shape + a reweight digest, so callers holding one mapper
    per crush-map identity get cross-request coalescing for free.
    """
    reweight = np.asarray(reweight, dtype=np.int64)
    if key is None:
        key = ("crush_rule", id(mapper), ruleno, result_max,
               hash(reweight.tobytes()))

    def fn(batch, key=key):
        rw = reweight
        mesh = getattr(getattr(batch, "sharding", None), "mesh", None)
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            # host-side placement scaffolding (see submit_flat_firstn):
            # replicate the reweight vector over the batch's mesh so
            # do_rule's jitted evaluator sees consistent shardings (the
            # mapper's compiled-map arrays are uncommitted and follow);
            # cached per (mesh, key) — the key digests mapper identity,
            # rule and reweight content
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            rw = _replicate_cached(
                mesh, key,
                lambda: jax.device_put(
                    rw, NamedSharding(mesh, PartitionSpec())))
        return mapper.do_rule(ruleno, batch, result_max, rw)

    host_oracle = None
    cmap = getattr(mapper, "map", None)
    if cmap is not None:
        def host_oracle(batch, cmap=cmap):
            # scalar rule interpreter per lane, NONE-padded to the
            # batched mapper's row shape (dense prefix for firstn,
            # positional holes for indep — crush.mapper_jax contract)
            from ceph_tpu.crush.mapper_ref import crush_do_rule
            none = 0x7FFFFFFF
            rw = [int(v) for v in np.asarray(reweight)]
            out = np.full((np.asarray(batch).shape[0], result_max),
                          none, dtype=np.int32)
            for i, x in enumerate(np.asarray(batch)):
                row = crush_do_rule(cmap, ruleno, int(x), result_max,
                                    rw)
                if row:
                    out[i, :len(row)] = np.asarray(row,
                                                   dtype=np.int32)
            return out

    return engine.submit(key, fn, np.asarray(xs, dtype=np.uint32),
                         label="crush_rule", fallback=host_oracle,
                         cost_tag=cost_tag)


def submit_finish_ladder(engine: DeviceDispatchEngine, operands, *,
                         key=None, cost_tag=None) -> DispatchFuture:
    """Submit one pool's fused placement-pipeline tail (raw -> up ->
    acting; ops.placement_kernel) through the engine.  ``operands`` is
    a placement_kernel.LadderOperands: the raw table is the data
    channel, the per-PG override/pps tables ride aux in lockstep, and
    the per-OSD state/weight/affinity vectors are captured operands —
    mesh-replicated on sharded batches exactly like the CRUSH reweight
    vector.  Pools (and daemons) sharing one epoch's operand digest
    and table widths coalesce on the PG axis into ONE device call.

    ``key`` defaults to a digest of the captured vectors plus the
    static table shape; pass an explicit (epoch, widths)-style key when
    the caller already knows the map identity."""
    state, weight, affinity = (operands.state, operands.weight,
                               operands.affinity)
    if key is None:
        key = ("pg_finish", operands.erasure, operands.width,
               operands.items.shape[1], hash(state.tobytes()),
               hash(weight.tobytes()), hash(affinity.tobytes()))

    def fn(batch, *aux, key=key):
        from ceph_tpu.ops.placement_kernel import _ladder_jit
        st, w, af = state, weight, affinity
        mesh = getattr(getattr(batch, "sharding", None), "mesh", None)
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            # host-side placement scaffolding (see submit_flat_firstn):
            # replicate the per-OSD vectors over the batch's mesh so
            # the jitted ladder compiles with consistent shardings
            # (sharded PG tables, replicated osd vectors); cached per
            # (mesh, key) — the key digests the vector content
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            st, w, af = _replicate_cached(
                mesh, key,
                lambda: jax.device_put(
                    (st, w, af), NamedSharding(mesh, PartitionSpec())))
        return _ladder_jit(operands.erasure)(batch, *aux, st, w, af)

    def host_oracle(batch, *aux, erasure=operands.erasure):
        # numpy twin of the fused ladder (placement_kernel.ladder_ref):
        # same packed-row output, bit for bit, no device involved
        from ceph_tpu.ops.placement_kernel import ladder_ref
        return ladder_ref(batch, *aux, state, weight, affinity,
                          erasure=erasure)

    from ceph_tpu.ops.placement_kernel import ladder_cache_entries
    return engine.submit(key, fn, operands.raw, aux=operands.aux(),
                         label="pg_finish",
                         cache_entries=ladder_cache_entries,
                         fallback=host_oracle, cost_tag=cost_tag)


def submit_scrub_digest(engine: DeviceDispatchEngine, blobs,
                        key=None, cost_tag=None) -> DispatchFuture:
    """Submit a batch of byte blobs (object payloads / omap blobs) for
    integrity digesting through the engine — the FIFTH kernel channel
    (``scrub_digest``), with everything the other four have: a
    bit-exact host oracle (the literal ``shard_crc`` loop), the
    device-boundary failpoint sites (which fire by channel tag with no
    extra code here), the bounded retry ladder, and a per-channel
    circuit breaker.  Returns a DispatchFuture of (len(blobs), 2)
    uint32 — col 0 crc32 (== ``osd.ec_util.shard_crc``), col 1 the
    packed GF shard digest.

    Rows zero-pad to a shared pow-2 width (checksum_kernel.row_width)
    and the key is just that width, so concurrent scrubs of DIFFERENT
    PGs — or different daemons in one context — coalesce into one
    device call; the per-row unpad operands (the crc Z^-pad matrix
    columns and the GF alpha^-t lane multipliers) ride the aux channel
    in lockstep, which is what makes zero-padding bit-exact here
    despite crc32 not being linear in the padded row."""
    from ceph_tpu.ops import checksum_kernel as ck
    lengths = np.array([len(b) for b in blobs], dtype=np.int64)
    w = ck.row_width(int(lengths.max()) if len(blobs) else 0)
    data = np.zeros((len(blobs), w), dtype=np.uint8)
    for i, b in enumerate(blobs):
        if len(b):
            data[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
    mats, invp = ck.digest_operands(lengths, w)
    if key is None:
        key = ("scrub_digest", w)

    def fn(batch, lens, m, p):
        from ceph_tpu.ops.checksum_kernel import scrub_digest_batched
        return scrub_digest_batched(batch, m, p)

    def host_oracle(batch, lens, m, p):
        from ceph_tpu.ops.checksum_kernel import scrub_digest_ref
        return scrub_digest_ref(batch, lens)

    return engine.submit(key, fn, data, aux=(lengths, mats, invp),
                         label="scrub_digest",
                         cache_entries=ck.digest_jit_entries,
                         fallback=host_oracle,
                         cost_tag=cost_tag if cost_tag is not None
                         else (BACKGROUND_BEST_EFFORT,
                               BACKGROUND_BEST_EFFORT))


def submit_bluestore_data(engine: DeviceDispatchEngine, blobs,
                          key=None, cost_tag=None) -> DispatchFuture:
    """Submit a batch of STORED block payloads (raw padded blocks or
    compressed bodies — lengths vary, which is exactly what the unpad
    epilogue absorbs) for checksumming through the engine — the SIXTH
    kernel channel (``bluestore_data``), the objectstore's write/read
    hot path.  Same contract as ``submit_scrub_digest``: returns a
    DispatchFuture of (len(blobs), 2) uint32 with col 0 the crc32 of
    each blob (== the scalar ``zlib.crc32`` loop BlueStore ran per
    block in the seed), a bit-exact host oracle as the breaker
    fallback, the channel-tagged device-boundary failpoints
    (``dispatch.launch:bluestore_data``), the bounded retry ladder and
    a per-channel circuit breaker.

    The key is just the padded width, so concurrent transaction
    batches — different stores, different daemons on one context —
    coalesce into one device call, like every other channel.  The
    digest math IS the scrub kernel's (one checksum definition for
    store and scrub); only the channel label and telemetry family
    differ, so the store path's health is observable on its own."""
    from ceph_tpu.ops import checksum_kernel as ck
    lengths = np.array([len(b) for b in blobs], dtype=np.int64)
    w = ck.row_width(int(lengths.max()) if len(blobs) else 0)
    data = np.zeros((len(blobs), w), dtype=np.uint8)
    for i, b in enumerate(blobs):
        if len(b):
            data[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
    mats, invp = ck.digest_operands(lengths, w)
    if key is None:
        key = ("bluestore_data", w)

    def fn(batch, lens, m, p):
        from ceph_tpu.ops.checksum_kernel import bluestore_digest_batched
        return bluestore_digest_batched(batch, m, p)

    def host_oracle(batch, lens, m, p):
        from ceph_tpu.ops.checksum_kernel import scrub_digest_ref
        return scrub_digest_ref(batch, lens)

    return engine.submit(key, fn, data, aux=(lengths, mats, invp),
                         label="bluestore_data",
                         cache_entries=ck.digest_jit_entries,
                         fallback=host_oracle,
                         cost_tag=cost_tag if cost_tag is not None
                         else ("_bluestore", "client"))
