"""Pipeline where-did-the-time-go report: render a captured profile
window as a human-readable phase-attribution table.

Accepts any of the profiler's JSON surfaces and normalizes them to one
view:

  * ``dump_pipeline_profile`` admin-socket output (full histograms),
  * ``telemetry.pipeline_profile_digest()`` (the MMgrReport carriage,
    also what ``bench.py --sections profile`` embeds under "profile"),
  * the mgr insights module's ``profile phases`` command output
    (cluster-merged), and
  * a whole bench JSON line (the "profile" key is found and used).

It also accepts the tenant device-time ledger's surfaces — the
``dump_tenant_usage`` admin output, the MMgrReport ``tenant_usage``
digest, the mgr slo module's ``usage top`` merge, or a bench JSON
line carrying a ``tenant_usage`` key — and renders a per-tenant
where-did-the-DEVICE-go table (device-seconds, cluster share, and
the per-engine/channel split) next to the phase table.

Output: per engine × kernel family, total attributed seconds and the
percentage each phase contributed (queue-wait, build, place, launch,
compute, materialize, deliver), the compile ledger (first-call jit
cost, separate from steady-state compute), device utilization, and
the mapping service's per-epoch device/delta/host-tail split.

Usage: python -m ceph_tpu.tools.profile_report [FILE|-]
"""

from __future__ import annotations

import json
import sys

from ceph_tpu.ops.telemetry import PHASES

#: mapping-service epoch phases, in pipeline order
MAPPING_PHASES = ("device", "delta", "host_tail")


def _from_hist_dump(d: dict) -> dict:
    """One engine's dump_pipeline_profile entry -> {kernel: {seconds,
    batches}}."""
    out = {}
    for kernel, per in (d.get("phases") or {}).items():
        secs = {ph: h.get("sum", 0.0) for ph, h in per.items()}
        batches = max((h.get("count", 0) for h in per.values()),
                      default=0)
        out[kernel] = {"seconds": secs, "batches": batches}
    return out


def normalize(doc: dict) -> dict:
    """Any accepted JSON shape -> {"engines", "compile",
    "utilization", "mapping"} (the insights ``profile phases``
    shape)."""
    if "profile" in doc and isinstance(doc["profile"], dict):
        doc = doc["profile"]          # bench JSON line
    if "engines" in doc:              # insights profile phases output
        return {"engines": doc.get("engines", {}),
                "compile": doc.get("compile", {}),
                "utilization": doc.get("utilization", {}),
                "mapping": doc.get("mapping", {})}
    engines: dict = {}
    compile_: dict = {}
    util: dict = {}
    for engine in ("encode", "decode"):
        d = doc.get(engine)
        if not isinstance(d, dict):
            continue
        if "kernels" in d:            # digest form
            engines[engine] = {
                k: {"seconds": dict(row.get("seconds") or {}),
                    "batches": row.get("batches", 0)}
                for k, row in (d.get("kernels") or {}).items()}
        elif "phases" in d:           # full dump form
            engines[engine] = _from_hist_dump(d)
        if d.get("compile"):
            compile_[engine] = {
                k: {"seconds": c.get("seconds", 0.0),
                    "events": c.get("events", 0)}
                for k, c in d["compile"].items()}
        util[engine] = {"local": {
            "busy_seconds": d.get("busy_seconds", 0.0),
            "utilization": d.get("utilization", 0.0),
            "devices_seen": d.get("devices_seen", 1)}}
    return {"engines": engines, "compile": compile_,
            "utilization": util, "mapping": doc.get("mapping", {})}


def _pct(s: float, total: float) -> str:
    return f"{100.0 * s / total:5.1f}%" if total else "    --"


def normalize_tenant(doc: dict) -> dict | None:
    """Any tenant-usage surface -> {"tenants": {tenant:
    {"device_seconds", "channels": {(engine, channel): row}}},
    "total"} — or None when the document carries no tenant ledger.

    Accepts the admin dump / MMgrReport digest (``tenants`` mapping),
    the slo module's ``usage top`` output (``tenants`` LIST of ranked
    rows), and any wrapper carrying a ``tenant_usage`` key (a bench
    JSON line)."""
    if isinstance(doc.get("tenant_usage"), dict):
        doc = doc["tenant_usage"]
    tenants = doc.get("tenants")
    if tenants is None:
        return None
    if isinstance(tenants, list):     # `usage top` ranked rows
        tenants = {r.get("tenant", "?"): r for r in tenants
                   if isinstance(r, dict)}
    if not isinstance(tenants, dict):
        return None
    out: dict = {}
    total = float(doc.get("total_device_seconds", 0.0) or 0.0)
    for tenant, trec in tenants.items():
        if not isinstance(trec, dict):
            continue
        channels = {}
        for eng, chans in (trec.get("engines") or {}).items():
            for ch, row in (chans or {}).items():
                channels[(eng, ch)] = row
        out[str(tenant)] = {
            "device_seconds": float(trec.get("device_seconds", 0.0)),
            "channels": channels}
    if not total:
        total = sum(t["device_seconds"] for t in out.values())
    return {"tenants": out, "total": total}


def render_tenant(doc: dict) -> str | None:
    """The per-tenant where-did-the-device-go table, or None when the
    document carries no tenant ledger."""
    n = normalize_tenant(doc)
    if n is None:
        return None
    lines: list[str] = []
    header = (f"{'tenant':<20} {'device_s':>10} {'share':>7} "
              f"{'engine':<8} {'channel':<14} {'chan_s':>10} "
              f"{'batches':>8} {'requests':>9}")
    lines.append("tenant device-time ledger (busy integral "
                 "apportioned by stripe share):")
    lines.append(header)
    lines.append("-" * len(header))
    total = n["total"]
    ranked = sorted(n["tenants"].items(),
                    key=lambda kv: -kv[1]["device_seconds"])
    for tenant, trec in ranked:
        first = True
        chans = sorted(trec["channels"].items()) or [((None, None), {})]
        for (eng, ch), row in chans:
            head = (f"{tenant:<20} {trec['device_seconds']:>10.4f} "
                    f"{_pct(trec['device_seconds'], total):>7}"
                    if first else f"{'':<20} {'':>10} {'':>7}")
            first = False
            if eng is None:
                lines.append(head)
                continue
            lines.append(
                f"{head} {eng:<8} {ch:<14} "
                f"{row.get('device_seconds', 0.0):>10.4f} "
                f"{row.get('batches', 0):>8} "
                f"{row.get('requests', 0):>9}")
    if not ranked:
        lines.append("(no tenant-attributed device time in this "
                     "window)")
    lines.append(f"{'total':<20} {total:>10.4f}")
    return "\n".join(lines)


def render(doc: dict) -> str:
    """The where-did-the-time-go table, as one printable string."""
    n = normalize(doc)
    lines: list[str] = []
    header = (f"{'engine':<8} {'kernel':<14} {'total_s':>9} "
              + " ".join(f"{ph:>11}" for ph in PHASES)
              + f" {'batches':>8}")
    lines.append(header)
    lines.append("-" * len(header))
    for engine in sorted(n["engines"]):
        for kernel in sorted(n["engines"][engine]):
            row = n["engines"][engine][kernel]
            secs = row.get("seconds") or {}
            total = sum(secs.values())
            cells = " ".join(
                f"{_pct(secs.get(ph, 0.0), total):>11}"
                for ph in PHASES)
            lines.append(f"{engine:<8} {kernel:<14} {total:>9.4f} "
                         f"{cells} {row.get('batches', 0):>8}")
    if not any(n["engines"].values()):
        lines.append("(no engine batches profiled in this window)")
    comp_rows = [(e, k, c) for e, per in sorted(n["compile"].items())
                 for k, c in sorted(per.items())]
    if comp_rows:
        lines.append("")
        lines.append("compile ledger (first-call jit cost, separate "
                     "from steady-state compute):")
        for engine, kernel, c in comp_rows:
            lines.append(f"  {engine:<8} {kernel:<14} "
                         f"{c.get('seconds', 0.0):>9.4f}s over "
                         f"{c.get('events', 0)} first-call batches")
    util_rows = [(e, who, u)
                 for e, per in sorted((n["utilization"] or {}).items())
                 for who, u in sorted(per.items())]
    if util_rows:
        lines.append("")
        lines.append("device utilization (busy-seconds integral over "
                     "the profiling window):")
        for engine, who, u in util_rows:
            lines.append(
                f"  {engine:<8} {who:<10} "
                f"util {100.0 * u.get('utilization', 0.0):5.1f}%  "
                f"busy {u.get('busy_seconds', 0.0):.4f}s  "
                f"devices {u.get('devices_seen', 1)}")
    mp = n.get("mapping") or {}
    secs = mp.get("seconds") or {}
    if secs:
        total = sum(secs.values())
        cells = "  ".join(
            f"{ph} {_pct(secs.get(ph, 0.0), total).strip()}"
            for ph in MAPPING_PHASES)
        lines.append("")
        lines.append(f"mapping epochs ({mp.get('epochs', 0)} computed,"
                     f" {total:.4f}s): {cells}")
    tenant = render_tenant(doc)
    if tenant is not None:
        lines.append("")
        lines.append(tenant)
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 2
    if not argv or argv[0] == "-":
        text = sys.stdin.read()
    else:
        try:
            with open(argv[0]) as f:
                text = f.read()
        except OSError as e:
            print(f"profile_report: {e}", file=sys.stderr)
            return 1
    try:
        doc = json.loads(text)
    except ValueError as e:
        print(f"profile_report: input is not JSON: {e}",
              file=sys.stderr)
        return 1
    print(render(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
