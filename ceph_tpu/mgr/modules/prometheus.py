"""Prometheus exporter module (src/pybind/mgr/prometheus analog): every
aggregated counter and gauge in the text exposition format, served over
HTTP on the module's configured port."""

from __future__ import annotations

import http.server
import socketserver
import threading

from ceph_tpu.mgr.module import MgrModule


class Module(MgrModule):
    NAME = "prometheus"
    MODULE_OPTIONS = [{"name": "server_port", "default": 0}]

    def __init__(self, mgr):
        super().__init__(mgr)
        self._httpd: socketserver.ThreadingTCPServer | None = None
        self._port = 0

    # -- payload --------------------------------------------------------------

    def scrape_text(self) -> str:
        lines = [
            "# HELP ceph_health_status cluster health (0=OK 1=WARN)",
            "# TYPE ceph_health_status gauge",
            f"ceph_health_status "
            f"{0 if self.get('health')['status'] == 'HEALTH_OK' else 1}",
        ]
        m = self.get_osdmap()
        lines += [
            "# TYPE ceph_osd_up gauge",
            f"ceph_osd_up "
            f"{sum(1 for o in range(m.max_osd) if m.is_up(o))}",
            "# TYPE ceph_osd_in gauge",
            f"ceph_osd_in "
            f"{sum(1 for o in range(m.max_osd) if m.exists(o) and m.osd_weight[o] > 0)}",
            "# TYPE ceph_osdmap_epoch gauge",
            f"ceph_osdmap_epoch {m.epoch}",
        ]
        for state, n in sorted(self.get("pg_summary").items()):
            lines.append(f'ceph_pg_states{{state="{state}"}} {n}')
        df = self.get("df")
        lines.append(f"ceph_cluster_total_objects {df['total_objects']}")
        lines.append(f"ceph_cluster_bytes_used {df['total_bytes_used']}")
        for osd, counters in sorted(self.get("counters").items()):
            for name, val in sorted(counters.items()):
                lines.append(
                    f'ceph_osd_perf{{ceph_daemon="osd.{osd}",'
                    f'counter="{name}"}} {int(val)}')
        return "\n".join(lines) + "\n"

    # -- lifecycle ------------------------------------------------------------

    def start_server(self, port: int | None = None) -> int:
        """Bind + serve; returns the bound port (GET /metrics)."""
        if self._httpd is not None:
            return self._port
        if port is None:
            port = int(self.get_module_option("server_port", 0))
        module = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = module.scrape_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._httpd = Server(("127.0.0.1", port), Handler)
        self._port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="mgr-prometheus-http", daemon=True)
        t.start()
        return self._port

    def start(self) -> None:
        self.start_server()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
