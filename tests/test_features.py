"""Wire feature bits (ceph_features.h / msg/Policy.h analog): the
handshake exchanges (supported, required) vectors on both TCP stacks;
unmet requirements reject cleanly before any message flows, optional
capabilities degrade (wire compression), and the default path
interoperates at the full feature set."""

from __future__ import annotations

import time

import pytest

from ceph_tpu.msg.features import (
    FEATURE_BASE,
    FEATURE_WIRE_COMPRESSION,
    SUPPORTED_FEATURES,
    check_compat,
    feature_names,
)
from ceph_tpu.msg.message import Message, register_message
from ceph_tpu.msg.messenger import (
    ConnectionPolicy, Dispatcher, EntityName, Messenger)


@register_message
class MPing2(Message):
    TYPE = 0x7f01

    def __init__(self, n: int = 0):
        super().__init__()
        self.n = n

    def encode_payload(self, enc):
        enc.u32(self.n)

    def decode_payload(self, dec, version):
        self.n = dec.u32()


class Sink(Dispatcher):
    def __init__(self):
        self.got = []

    def ms_dispatch(self, msg):
        if isinstance(msg, MPing2):
            self.got.append(msg)
            return True
        return False


def _pair(ms_type: str, a_kw=None, b_kw=None):
    a = Messenger.create(EntityName("client", 1), ms_type)
    b = Messenger.create(EntityName("osd", 7), ms_type)
    for m, kw in ((a, a_kw or {}), (b, b_kw or {})):
        for k, v in kw.items():
            setattr(m, k, v)
    sink = Sink()
    b.add_dispatcher_tail(sink)
    b.bind("127.0.0.1:0")
    b.start()
    a.start()
    return a, b, sink


STACKS = ["threaded", "async"]


def test_check_compat_unit():
    assert check_compat("x", 0b111, 0b001, 0b011, 0b001) == 0b011
    with pytest.raises(ConnectionError):
        check_compat("x", 0b001, 0b010, 0b001, 0b001)  # they lack mine
    with pytest.raises(ConnectionError):
        check_compat("x", 0b001, 0b001, 0b011, 0b010)  # I lack theirs
    assert "wire-compression" in feature_names(FEATURE_WIRE_COMPRESSION)


@pytest.mark.parametrize("ms_type", STACKS)
def test_full_feature_peers_interoperate(ms_type):
    a, b, sink = _pair(ms_type)
    try:
        con = a.connect_to(b.my_addr, EntityName("osd", 7))
        con.send_message(MPing2(5))
        deadline = time.time() + 5
        while time.time() < deadline and not sink.got:
            time.sleep(0.02)
        assert sink.got and sink.got[0].n == 5
        assert con.features == SUPPORTED_FEATURES
    finally:
        a.shutdown()
        b.shutdown()


@pytest.mark.parametrize("ms_type", STACKS)
def test_old_peer_cleanly_rejected(ms_type):
    # B is an "old" build lacking a bit A's osd-policy requires: the
    # handshake must fail cleanly — no message flows, no hang
    a, b, sink = _pair(
        ms_type, b_kw={"local_features": FEATURE_BASE})
    novel = 1 << 20
    a.local_features = SUPPORTED_FEATURES | novel
    a.set_policy("osd", ConnectionPolicy(features_required=novel))
    try:
        con = a.connect_to(b.my_addr, EntityName("osd", 7))
        con.send_message(MPing2(9))
        time.sleep(1.0)
        assert sink.got == []
    finally:
        a.shutdown()
        b.shutdown()


@pytest.mark.parametrize("ms_type", STACKS)
def test_peer_requiring_what_i_lack_rejected(ms_type):
    # the acceptor requires a bit the initiator lacks: also rejected
    novel = 1 << 21
    a, b, sink = _pair(ms_type)
    b.local_features = SUPPORTED_FEATURES | novel
    b.set_policy("client", ConnectionPolicy(features_required=novel))
    try:
        con = a.connect_to(b.my_addr, EntityName("osd", 7))
        con.send_message(MPing2(3))
        time.sleep(1.0)
        assert sink.got == []
    finally:
        a.shutdown()
        b.shutdown()


@pytest.mark.parametrize("ms_type", STACKS)
def test_compression_degrades_without_feature(ms_type):
    # both OFFER zlib, but B lacks the wire-compression feature bit:
    # the session degrades to uncompressed and still delivers
    a, b, sink = _pair(
        ms_type,
        b_kw={"local_features":
              SUPPORTED_FEATURES & ~FEATURE_WIRE_COMPRESSION})
    a.set_compression("zlib")
    b.set_compression("zlib")
    try:
        con = a.connect_to(b.my_addr, EntityName("osd", 7))
        con.send_message(MPing2(11))
        deadline = time.time() + 5
        while time.time() < deadline and not sink.got:
            time.sleep(0.02)
        assert sink.got and sink.got[0].n == 11
        from ceph_tpu.msg.async_tcp import COMP_NONE
        assert con.comp == COMP_NONE
        assert not con.features & FEATURE_WIRE_COMPRESSION
    finally:
        a.shutdown()
        b.shutdown()


@pytest.mark.parametrize("ms_type", STACKS)
def test_compression_still_negotiates_with_feature(ms_type):
    a, b, sink = _pair(ms_type)
    a.set_compression("zlib")
    b.set_compression("zlib")
    try:
        con = a.connect_to(b.my_addr, EntityName("osd", 7))
        con.send_message(MPing2(2))
        deadline = time.time() + 5
        while time.time() < deadline and not sink.got:
            time.sleep(0.02)
        assert sink.got
        from ceph_tpu.msg.async_tcp import COMP_ZLIB
        assert con.comp == COMP_ZLIB
    finally:
        a.shutdown()
        b.shutdown()
