"""Bulk PG -> OSD mapping on device (OSDMapMapping / ParallelPGMapper analog).

The reference computes the full PG->OSD table with a thread pool over pgid
batches (src/osd/OSDMapMapping.h:17 ParallelPGMapper, used by the mgr balancer
and OSDMonitor).  Here the whole pool maps in one device call: the pps seeds
are a vectorized stable_mod + rjenkins hash, and placement is the batched rule
engine (ceph_tpu.crush.mapper_jax.BatchMapper).

Post-CRUSH overrides (upmap, primary affinity, temps) are sparse per-PG state
and apply host-side on the dense result — the same split the reference uses
(its mapping cache also stores raw CRUSH output and applies overrides on read).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.crush.mapper_jax import BatchMapper
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ops.crush_kernel import hash32_2

from .osdmap import CEPH_NOSD, OSDMap, PGPool, ceph_stable_mod


def pps_batch(pool: PGPool, pgids: np.ndarray) -> np.ndarray:
    """Vectorized raw_pg_to_pps over pg ids (osd_types.cc:1505-1521)."""
    import jax.numpy as jnp
    ps = np.asarray(pgids, dtype=np.uint32)
    bmask = pool.pgp_num_mask
    low = ps & bmask
    stable = np.where(low < pool.pgp_num, low, ps & (bmask >> 1))
    return np.asarray(hash32_2(jnp.asarray(stable),
                               jnp.uint32(pool.pool_id & 0xFFFFFFFF)))


class OSDMapMapping:
    """Full-map PG->OSD cache, updated per epoch (OSDMapMapping.h:324-332)."""

    def __init__(self, osdmap: OSDMap):
        self.osdmap = osdmap
        self._mappers: dict[int, BatchMapper] = {}
        self._raw: dict[int, np.ndarray] = {}    # pool -> (pg_num, size) raw
        self._pps: dict[int, np.ndarray] = {}    # pool -> (pg_num,) pps seeds
        self.epoch = -1

    def update(self) -> None:
        """Recompute every pool's raw placements (start_update/update)."""
        m = self.osdmap
        self._mappers.clear()
        self._raw.clear()
        self._pps.clear()
        bm = BatchMapper(m.crush)
        weights = np.zeros(max(m.max_osd, 1), dtype=np.int64)
        weights[:len(m.osd_weight)] = m.osd_weight
        for pool_id, pool in m.pools.items():
            if (pool.crush_rule < 0 or pool.crush_rule >= m.crush.max_rules
                    or m.crush.rules[pool.crush_rule] is None):
                # invalid rule -> empty raw, matching _pg_to_raw_osds's []
                self._raw[pool_id] = np.zeros((pool.pg_num, 0), dtype=np.int32)
                continue
            pgids = np.arange(pool.pg_num, dtype=np.uint32)
            pps = pps_batch(pool, pgids)
            out = bm.do_rule(pool.crush_rule, pps, pool.size, weights)
            self._raw[pool_id] = np.asarray(out)
            self._pps[pool_id] = pps
        self.epoch = m.epoch

    def get_raw(self, pool_id: int) -> np.ndarray:
        """(pg_num, size) int32 raw CRUSH output, CRUSH_ITEM_NONE holes."""
        return self._raw[pool_id]

    def get(self, pool_id: int, pgid: int
            ) -> tuple[list[int], int, list[int], int]:
        """Full pipeline for one PG using the cached raw placement."""
        m = self.osdmap
        pool = m.pools[pool_id]
        raw = [int(o) for o in self._raw[pool_id][pgid]]
        if not pool.is_erasure():
            raw = [o for o in raw if o != CRUSH_ITEM_NONE]
        pps = int(self._pps[pool_id][pgid]) if pool_id in self._pps else None
        return m._finish_pg_mapping(pool, (pool_id, pgid), raw, pps)

    def pg_counts(self, pool_id: int) -> np.ndarray:
        """Per-OSD PG count histogram for a pool (balancer input)."""
        raw = self._raw[pool_id]
        valid = raw[(raw != CRUSH_ITEM_NONE) & (raw >= 0)]
        return np.bincount(valid, minlength=self.osdmap.max_osd)
