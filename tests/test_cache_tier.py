"""Cache tiering (PrimaryLogPG promote_object + TierAgent, lite): a
replicated cache pool fronts a base pool via the osdmap overlay; the
Objecter redirects, the cache OSD promotes on miss, writes stamp dirty,
and the agent flushes dirty objects back to base (flush+evict) and
evicts clean ones over the target."""

import time

import pytest

from ceph_tpu.tools.vstart import MiniCluster


@pytest.fixture
def tiered():
    c = MiniCluster(n_osds=3, ms_type="loopback").start()
    c.wait_for_osd_count(3)
    client = c.client(timeout=20.0)
    base = c.create_pool(client, pg_num=4, size=2)
    cache = c.create_pool(client, pg_num=4, size=2)
    for cmd in (
        {"prefix": "osd tier add", "pool": base, "tierpool": cache},
        {"prefix": "osd tier cache-mode", "pool": cache,
         "mode": "writeback"},
        {"prefix": "osd tier set-overlay", "pool": base,
         "overlaypool": cache},
    ):
        rc, out = client.mon_command(cmd)
        assert rc == 0, (cmd, out)
    epoch = c.mon.osdmap.epoch
    c.wait_for_epoch(epoch)
    client.wait_for_epoch(epoch)
    yield c, client, base, cache
    c.stop()


def _holding_osds(c, pool, oid):
    out = set()
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if cid.startswith(f"{pool}.") \
                    and oid in osd.store.list_objects(cid):
                out.add(osd.osd_id)
    return out


def test_writes_land_in_cache_then_flush_to_base(tiered):
    c, client, base, cache = tiered
    io = client.open_ioctx(base)     # caller talks to the BASE pool
    io.write_full("hot", b"cached-write" * 20)
    assert io.read("hot") == b"cached-write" * 20
    # the object physically lives in the cache pool, not the base
    assert _holding_osds(c, cache, "hot")
    assert not _holding_osds(c, base, "hot")
    # age out: agent flushes to base and evicts from cache
    rc, out = client.mon_command({
        "prefix": "osd pool set", "pool": cache,
        "var": "cache_min_flush_age", "val": "0.1"})
    assert rc == 0, out
    deadline = time.time() + 15
    while time.time() < deadline and not _holding_osds(c, base, "hot"):
        time.sleep(0.2)
    assert _holding_osds(c, base, "hot"), "agent never flushed to base"
    deadline = time.time() + 10
    while time.time() < deadline and _holding_osds(c, cache, "hot"):
        time.sleep(0.2)
    assert not _holding_osds(c, cache, "hot"), "flush did not evict"
    # data still correct (served via re-promotion)
    assert io.read("hot") == b"cached-write" * 20


def test_read_miss_promotes_from_base(tiered):
    c, client, base, cache = tiered
    # seed the BASE pool directly (as if written before the tier)
    base_io = client.open_ioctx(cache)  # trick: write via cache's id?
    # no — seed through an OSD-internal path: write via overlay then
    # flush quickly
    io = client.open_ioctx(base)
    rc, _ = client.mon_command({
        "prefix": "osd pool set", "pool": cache,
        "var": "cache_min_flush_age", "val": "0.1"})
    assert rc == 0
    io.write_full("cold", b"base-resident")
    deadline = time.time() + 15
    while time.time() < deadline and not _holding_osds(c, base, "cold"):
        time.sleep(0.2)
    deadline = time.time() + 10
    while time.time() < deadline and _holding_osds(c, cache, "cold"):
        time.sleep(0.2)
    assert not _holding_osds(c, cache, "cold")
    # stop flushing so the promotion stays observable
    rc, _ = client.mon_command({
        "prefix": "osd pool set", "pool": cache,
        "var": "cache_min_flush_age", "val": "3600"})
    assert rc == 0
    # read through the overlay: miss -> promote -> serve
    assert io.read("cold") == b"base-resident"
    assert _holding_osds(c, cache, "cold"), "read miss did not promote"


def test_delete_writes_through(tiered):
    c, client, base, cache = tiered
    io = client.open_ioctx(base)
    rc, _ = client.mon_command({
        "prefix": "osd pool set", "pool": cache,
        "var": "cache_min_flush_age", "val": "0.1"})
    assert rc == 0
    io.write_full("doomed", b"x")
    deadline = time.time() + 15
    while time.time() < deadline and not _holding_osds(c, base, "doomed"):
        time.sleep(0.2)
    io.remove("doomed")
    # the base copy must not resurrect on a later read
    deadline = time.time() + 10
    while time.time() < deadline and _holding_osds(c, base, "doomed"):
        time.sleep(0.2)
    assert not _holding_osds(c, base, "doomed"), \
        "delete never propagated to base"
    with pytest.raises(OSError):
        io.read("doomed")


def test_eviction_over_target(tiered):
    c, client, base, cache = tiered
    io = client.open_ioctx(base)
    # flush everything quickly, then promote a working set back
    rc, _ = client.mon_command({
        "prefix": "osd pool set", "pool": cache,
        "var": "cache_min_flush_age", "val": "0.05"})
    assert rc == 0
    for i in range(8):
        io.write_full(f"e{i}", f"evict-{i}".encode())
    deadline = time.time() + 20
    while time.time() < deadline and any(
            _holding_osds(c, cache, f"e{i}") for i in range(8)):
        time.sleep(0.2)
    # promote all back as CLEAN copies, with a small cache target
    rc, _ = client.mon_command({
        "prefix": "osd pool set", "pool": cache,
        "var": "cache_min_flush_age", "val": "3600"})
    assert rc == 0
    rc, _ = client.mon_command({
        "prefix": "osd pool set", "pool": cache,
        "var": "target_max_objects", "val": "2"})
    assert rc == 0
    for i in range(8):
        assert io.read(f"e{i}") == f"evict-{i}".encode()
    n0 = sum(1 for i in range(8) if _holding_osds(c, cache, f"e{i}"))
    deadline = time.time() + 15
    while time.time() < deadline:
        n = sum(1 for i in range(8) if _holding_osds(c, cache, f"e{i}"))
        if n < n0:
            break
        time.sleep(0.2)
    assert n < n0, "agent never evicted clean objects over target"
    # all objects still readable (from base or cache)
    for i in range(8):
        assert io.read(f"e{i}") == f"evict-{i}".encode()


def test_tier_commands_validation(tiered):
    c, client, base, cache = tiered
    # cannot remove the tier while the overlay is active
    rc, out = client.mon_command({
        "prefix": "osd tier remove", "pool": base, "tierpool": cache})
    assert rc == -16, out
    rc, out = client.mon_command({
        "prefix": "osd tier remove-overlay", "pool": base})
    assert rc == 0, out
    rc, out = client.mon_command({
        "prefix": "osd tier remove", "pool": base, "tierpool": cache})
    assert rc == 0, out
    # after teardown, ops hit the base pool directly
    epoch = c.mon.osdmap.epoch
    client.wait_for_epoch(epoch)
    c.wait_for_epoch(epoch)
    io = client.open_ioctx(base)
    io.write_full("direct", b"no-tier")
    assert _holding_osds(c, base, "direct")
    assert io.read("direct") == b"no-tier"


def test_tier_add_rejects_self_and_chains(tiered):
    c, client, base, cache = tiered
    rc, out = client.mon_command({
        "prefix": "osd tier add", "pool": base, "tierpool": base})
    assert rc == -22, out
    # the base already has a tier; it cannot itself become one
    third = c.create_pool(client, pg_num=2, size=2)
    rc, out = client.mon_command({
        "prefix": "osd tier add", "pool": third, "tierpool": cache})
    assert rc == -22, out          # cache is already a tier
    rc, out = client.mon_command({
        "prefix": "osd tier add", "pool": cache, "tierpool": third})
    assert rc == -22, out          # no chains: base is itself a tier


def test_evict_aborts_when_write_races(tiered):
    """The guarded evict is atomic under the PG lock: a dirty stamp
    that changed since the agent scanned aborts the delete."""
    c, client, base, cache = tiered
    io = client.open_ioctx(base)
    io.write_full("race", b"v1")
    # find the cache primary holding it and evict with a STALE stamp
    holders = _holding_osds(c, cache, "race")
    assert holders
    osd = c.osds[sorted(holders)[0]]
    pgid = next(p for p in osd.pgs
                if p[0] == cache and "race" in
                osd.store.list_objects(f"{p[0]}.{p[1]}"))
    stale = b"0.0"   # wrong stamp: must abort the evict
    osd._evict_object(pgid, "race", stale)
    assert io.read("race") == b"v1"
    # with the true stamp the evict goes through (after a base flush)
    cid = f"{pgid[0]}.{pgid[1]}"
    osd._do_flush(pgid, "race", base, evict_only=False)
    deadline = time.time() + 5
    while time.time() < deadline and _holding_osds(c, cache, "race"):
        time.sleep(0.1)
    assert not _holding_osds(c, cache, "race")
    assert io.read("race") == b"v1"   # re-promoted from base


def test_watch_notify_through_overlay(tiered):
    """Watch registered on the base pool still fires when the overlay
    redirects the object to the cache pool."""
    c, client, base, cache = tiered
    io = client.open_ioctx(base)
    io.write_full("watched", b"x")
    got = []
    io.watch("watched", got.append)
    other = c.client(timeout=10.0)
    other.open_ioctx(base).notify("watched", b"ping")
    assert got == [b"ping"]
    io.unwatch("watched")
