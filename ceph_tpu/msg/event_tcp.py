"""Event-driven TCP messenger stack (the AsyncMessenger proper).

The reference's default messenger is an epoll event loop
(src/msg/async/EventEpoll.h, AsyncMessenger.cc): a small fixed number of
threads own every socket, connections are non-blocking state machines,
and nothing scales with connection count.  This stack is its analog on
``selectors`` (epoll on Linux):

* ONE event-loop thread per messenger owns the listener and every
  connection socket: accept, non-blocking connect, the handshake state
  machine, frame reads and buffered writes all run there.
* ONE dispatch thread drains decoded messages in arrival order and walks
  the dispatcher chain — handlers may block or send without stalling
  socket I/O.  (The reference similarly separates the event centers from
  the DispatchQueue.)

So a daemon costs 2 messenger threads regardless of peer count, where
the threaded stack (`async_tcp`, kept as the "threaded" type) spawns
2 threads per connection.

Wire format: byte-for-byte the v1-lite protocol of the threaded stack
(banner | name | auth mode+nonce | optional HMAC proofs | compression
byte | [u32 len][u8 comp] frames) — the two stacks interoperate on the
same cluster, which is also how this one is tested.

Policy semantics match msg/Policy.h via the threaded stack: stateful
dialing connections reconnect with backoff and resend their backlog
(messages are re-framed at flush time, so a renegotiated compression
mode applies); lossy or accepted connections drop on failure and fire
ms_handle_reset.  Inbound-byte backpressure: when decoded-but-not-yet-
dispatched bytes exceed the high watermark the loop stops reading from
all sockets until the dispatcher drains below the low watermark (the
DispatchQueue throttle analog).
"""

from __future__ import annotations

import collections
import errno
import hashlib
import hmac
import os
import queue
import selectors
import socket
import struct
import threading
import time
import zlib

from ceph_tpu.auth.handshake import (
    AUTH_CEPHX_ENTITY, AUTH_CEPHX_TICKET, accept_ticket, entity_proof,
    proof as _sess_proof, ticket_for)

from .async_tcp import (
    AUTH_CEPHX, AUTH_NONE, BANNER, COMP_NONE, COMP_THRESHOLD, COMP_ZLIB,
    MAX_FRAME)
from .message import Message
from .messenger import Connection, ConnectionPolicy, EntityName, Messenger

_LEN = struct.Struct("<I")

from .features import FEAT_FRAME as _FEAT  # noqa: E402

# connection states
_CONNECTING = "connecting"
_HANDSHAKE = "handshake"
_OPEN = "open"
_CLOSED = "closed"
_WAIT_RECONNECT = "wait-reconnect"

_RECONNECT_DELAY = 0.1


class EventConnection(Connection):
    """Non-blocking connection state machine; all socket work happens on
    the owning messenger's event-loop thread."""

    def __init__(self, messenger: "EventMessenger", peer_addr: str,
                 peer_name: EntityName | None, policy: ConnectionPolicy,
                 sock: socket.socket | None = None,
                 accepted: bool = False):
        super().__init__(messenger, peer_addr)
        self.peer_name = peer_name
        self.policy = policy
        self.accepted = accepted
        self.comp = COMP_NONE
        self.sock = sock
        self.state = _HANDSHAKE if sock is not None else _CONNECTING
        #: unsent messages (framed lazily at flush time)
        self.backlog: collections.deque[Message] = collections.deque()
        #: framed-but-unflushed (bytes, msg) pairs; msg None = handshake
        #: bytes (regenerated on reconnect, never resent)
        self.out_frames: collections.deque = collections.deque()
        self.out_off = 0
        self.inbuf = bytearray()
        self._down = False
        # handshake scratch
        self.hs_stage = "banner"
        self.hs_nonce = b""
        self.hs_peer_mode = AUTH_NONE
        self.hs_session: bytes | None = None   # cephx session/entity key
        self.hs_peer_nonce = b""
        self.hs_my_mode = AUTH_NONE
        self.hs_eff = AUTH_NONE
        #: authenticated cephx identity (e.g. "client.admin") — distinct
        #: from the transport-level peer_name instance
        self.auth_entity: str | None = None
        self.reconnect_at = 0.0
        #: interest cache: last mask set on the selector (0 = not
        #: registered) — skips no-op epoll_ctl syscalls
        self._cur_want = 0
        #: handshake must finish by this deadline or the conn is torn
        #: down (the threaded stack's 10s guard: a stalled peer must
        #: not leak an fd)
        self.hs_deadline = (time.monotonic() + 10.0
                            if sock is not None else 0.0)
        if sock is not None:
            sock.setblocking(False)

    # -- public (any thread) --------------------------------------------------

    def send_message(self, msg: Message) -> None:
        if self._down:
            return
        from ceph_tpu.common import tracing
        from ceph_tpu.msg.features import FEATURE_TRACE, FEATURE_TRACE_SPANS
        if self.features & FEATURE_TRACE:
            # NEVER emit the trace header extension against a peer
            # that did not negotiate it (features.py's invariant)
            tracing.stamp(msg, str(self.messenger.my_name))
            if not self.features & FEATURE_TRACE_SPANS:
                # peer predates the v2 (trace_id, parent_span_id)
                # extension: fall back to the v1 bare-u64 frame
                msg.parent_span_id = 0
        m = self.messenger
        with m._lock:
            if self._down:
                return
            self.backlog.append(msg)
        m.wakeup()

    def mark_down(self) -> None:
        self._down = True
        self.messenger.defer(self._close_now)
        self.messenger.wakeup()

    def is_connected(self) -> bool:
        return self.state == _OPEN and not self._down

    # -- event-loop side ------------------------------------------------------

    def _close_now(self, reset: bool = False) -> None:
        """Loop thread: tear the socket down; maybe schedule reconnect."""
        m = self.messenger
        if self.sock is not None:
            try:
                m.sel.unregister(self.sock)
            except (KeyError, ValueError):
                pass
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        self._cur_want = 0
        m._accepting.discard(self)
        self.inbuf.clear()
        # salvage framed-but-unflushed messages back onto the backlog in
        # order (the threaded stack's resend granularity: whole frames)
        salvage = [om for _, om in self.out_frames if om is not None]
        self.out_frames.clear()
        self.out_off = 0
        if salvage:
            with self.messenger._lock:
                self.backlog.extendleft(reversed(salvage))
        self.hs_stage = "banner"
        self.hs_session = None
        self.auth_entity = None
        if self._down:
            self.state = _CLOSED
            return
        if reset and (self.policy.lossy or self.accepted):
            # lossy/accepted sessions die with their socket
            self._down = True
            self.state = _CLOSED
            m.notify_reset(self)
            m.reap(self)
            return
        if reset:
            if not self.policy.resend_on_reconnect:
                self.backlog.clear()
            self.state = _WAIT_RECONNECT
            self.reconnect_at = time.monotonic() + _RECONNECT_DELAY
        else:
            self.state = _CLOSED

    def _start_connect(self) -> None:
        """Loop thread: begin a non-blocking dial."""
        host, port = self.peer_addr.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        self.sock = s
        self.state = _CONNECTING
        # fresh deadline per dial: covers both the TCP connect and the
        # handshake (a redial must not inherit an expired deadline)
        self.hs_deadline = time.monotonic() + 10.0
        try:
            rc = s.connect_ex((host, int(port)))
        except OSError:
            self._close_now(reset=True)
            return
        if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            self._close_now(reset=True)
            return
        self.messenger.sel.register(
            s, selectors.EVENT_READ | selectors.EVENT_WRITE, self)
        self._cur_want = selectors.EVENT_READ | selectors.EVENT_WRITE

    def _on_connected(self) -> None:
        err = self.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
        if err:
            self._close_now(reset=True)
            return
        self.state = _HANDSHAKE
        self.hs_stage = "banner"
        self.hs_deadline = time.monotonic() + 10.0
        self._emit_handshake_head()
        self._update_interest()

    # -- handshake state machine ---------------------------------------------
    # Outgoing bytes per direction (matching async_tcp._handshake):
    #   banner | [len]name | [feat16] | [mode][nonce16] |
    #   (proof32 if both cephx) | [comp1] — each side's stream is fixed
    #   once the peer's auth mode is known, so both sides can emit
    #   eagerly and parse statefully.  feat16 = (supported u64,
    #   required u64); unmet requirements abort the handshake.

    def _emit_handshake_head(self) -> None:
        m = self.messenger
        me = str(m.my_name).encode()
        self.hs_nonce = os.urandom(16)
        if m.cephx is not None:
            my_mode = (m.cephx.acceptor_mode() if self.accepted
                       else m.cephx.initiator_mode(
                           self.peer_name.type if self.peer_name
                           else ""))
        else:
            my_mode = AUTH_CEPHX if m.auth_key else AUTH_NONE
        self.hs_my_mode = my_mode
        # stream: banner | name | feat | mode+nonce.  The feat frame's
        # required bits depend on the PEER type: an initiator that knows
        # who it dialed emits everything eagerly; an acceptor (or a dial
        # to an unnamed peer) defers feat+mode+nonce until the peer's
        # name arrives so the frames stay in stream order
        self.out_frames.append((BANNER + _LEN.pack(len(me)) + me, None))
        if not self.accepted and self.peer_name is not None:
            self._emit_feat_auth(self.peer_name.type)

    def _emit_feat_auth(self, peer_type: str) -> None:
        m = self.messenger
        self.hs_my_req = m.required_for(peer_type)
        self.out_frames.append(
            (_FEAT.pack(m.local_features, self.hs_my_req)
             + bytes([self.hs_my_mode]) + self.hs_nonce, None))

    def _hs_step(self) -> bool:
        """Consume handshake bytes from inbuf; True on progress.
        Raises ConnectionError on protocol/auth failure."""
        m = self.messenger
        if self.hs_stage == "banner":
            if len(self.inbuf) < len(BANNER):
                return False
            got = bytes(self.inbuf[:len(BANNER)])
            del self.inbuf[:len(BANNER)]
            if got != BANNER:
                raise ConnectionError(f"bad banner {got!r}")
            self.hs_stage = "name"
        if self.hs_stage == "name":
            if len(self.inbuf) < _LEN.size:
                return False
            plen = _LEN.unpack(bytes(self.inbuf[:_LEN.size]))[0]
            if plen > 256:
                raise ConnectionError("oversized name frame")
            if len(self.inbuf) < _LEN.size + plen:
                return False
            name = bytes(self.inbuf[_LEN.size:_LEN.size + plen])
            del self.inbuf[:_LEN.size + plen]
            peer = EntityName.parse(name.decode())
            if self.peer_name is None:
                self.peer_name = peer
            if self.accepted:
                self.policy = m.policy_for(peer.type)
                self._emit_feat_auth(peer.type)
            elif not hasattr(self, "hs_my_req"):
                # dialed without a known peer name: the feat+auth frames
                # were deferred to now
                self._emit_feat_auth(peer.type)
            self.hs_stage = "feat"
        if self.hs_stage == "feat":
            if len(self.inbuf) < _FEAT.size:
                return False
            pf, pr = _FEAT.unpack(bytes(self.inbuf[:_FEAT.size]))
            del self.inbuf[:_FEAT.size]
            from ceph_tpu.msg.features import check_compat
            self.features = check_compat(
                str(self.peer_name), m.local_features, self.hs_my_req,
                pf, pr)
            self.hs_stage = "auth"
        if self.hs_stage == "auth":
            if len(self.inbuf) < 17:
                return False
            self.hs_peer_mode = self.inbuf[0]
            self.hs_peer_nonce = bytes(self.inbuf[1:17])
            del self.inbuf[:17]
            if m.cephx is not None:
                self._hs_cephx_start()
            else:
                if m.auth_required and self.hs_peer_mode != AUTH_CEPHX:
                    raise ConnectionError(
                        f"peer {self.peer_name} refused authentication")
                both = (m.auth_key is not None
                        and self.hs_peer_mode == AUTH_CEPHX)
                if both:
                    me = str(m.my_name).encode()
                    self.out_frames.append((
                        hmac.new(m.auth_key, self.hs_peer_nonce + me,
                                 hashlib.sha256).digest(), None))
                    self.hs_stage = "proof"
                else:
                    self.out_frames.append((bytes([m.comp_mode]), None))
                    self.hs_stage = "comp"
        if self.hs_stage == "cred":        # acceptor: [len][credential]
            if len(self.inbuf) < _LEN.size:
                return False
            clen = _LEN.unpack(bytes(self.inbuf[:_LEN.size]))[0]
            if clen > 4096:
                raise ConnectionError("oversized auth credential")
            if len(self.inbuf) < _LEN.size + clen:
                return False
            cred = bytes(self.inbuf[_LEN.size:_LEN.size + clen])
            del self.inbuf[:_LEN.size + clen]
            self._hs_cephx_cred(cred)      # sets hs_session or raises
            self.hs_stage = "proof"
        if self.hs_stage == "proof":
            if len(self.inbuf) < 32:
                return False
            peer_proof = bytes(self.inbuf[:32])
            del self.inbuf[:32]
            if self.hs_session is not None:     # cephx ticket/entity
                # initiator proved over MY nonce + the auth identity;
                # I prove back over ITS nonce + my transport name
                ident = (self.auth_entity if self.accepted
                         else str(self.peer_name))
                want = hmac.new(self.hs_session,
                                self.hs_nonce + ident.encode(),
                                hashlib.sha256).digest()
                if not hmac.compare_digest(peer_proof, want):
                    raise ConnectionError(
                        f"peer {self.peer_name} failed cephx proof")
                if self.accepted:
                    self.out_frames.append((hmac.new(
                        self.hs_session,
                        self.hs_peer_nonce + str(m.my_name).encode(),
                        hashlib.sha256).digest(), None))
            else:                               # legacy shared key
                want = hmac.new(
                    self.messenger.auth_key,
                    self.hs_nonce + str(self.peer_name).encode(),
                    hashlib.sha256).digest()
                if not hmac.compare_digest(peer_proof, want):
                    raise ConnectionError(
                        f"peer {self.peer_name} failed authentication")
            self.out_frames.append(
                (bytes([self.messenger.comp_mode]), None))
            self.hs_stage = "comp"
        if self.hs_stage == "comp":
            if len(self.inbuf) < 1:
                return False
            peer_comp = self.inbuf[0]
            del self.inbuf[:1]
            from ceph_tpu.msg.features import FEATURE_WIRE_COMPRESSION
            my_comp = (self.messenger.comp_mode
                       if self.features & FEATURE_WIRE_COMPRESSION
                       else COMP_NONE)
            self.comp = min(my_comp, peer_comp)
            self.state = _OPEN
            if self.accepted:
                self.messenger.register_accepted(self)
            self.hs_stage = "done"
        return True

    # -- cephx handshake halves ------------------------------------------------

    def _hs_cephx_start(self) -> None:
        """Head exchanged under a cephx config: initiator emits its
        credential + proof; acceptor waits for them."""
        m = self.messenger
        cfg = m.cephx
        if not self.accepted:
            eff = self.hs_my_mode
            if eff == AUTH_CEPHX_TICKET:
                t = ticket_for(cfg, self.peer_name.type
                               if self.peer_name else "")
                if t is None:
                    raise ConnectionError(
                        f"no ticket for service "
                        f"{self.peer_name.type if self.peer_name else '?'}")
                self.hs_session = t.session_key
                blob = t.blob()
                pf = _sess_proof(self.hs_session, self.hs_peer_nonce,
                                 t.entity)
                self.out_frames.append(
                    (_LEN.pack(len(blob)) + blob + pf, None))
                self.hs_stage = "proof"
            elif eff == AUTH_CEPHX_ENTITY:
                self.hs_session = cfg.key.encode()
                ent = cfg.entity.encode()
                pf = entity_proof(cfg.key, self.hs_peer_nonce,
                                  cfg.entity)
                self.out_frames.append(
                    (_LEN.pack(len(ent)) + ent + pf, None))
                self.hs_stage = "proof"
            else:
                self.out_frames.append((bytes([m.comp_mode]), None))
                self.hs_stage = "comp"
        else:
            eff = self.hs_peer_mode
            if eff in (AUTH_CEPHX_TICKET, AUTH_CEPHX_ENTITY):
                self.hs_eff = eff
                self.hs_stage = "cred"
            elif eff == AUTH_NONE and not cfg.required:
                self.out_frames.append((bytes([m.comp_mode]), None))
                self.hs_stage = "comp"
            else:
                raise ConnectionError(
                    f"peer {self.peer_name} auth mode {eff} "
                    "not acceptable")

    def _hs_cephx_cred(self, cred: bytes) -> None:
        cfg = self.messenger.cephx
        if self.hs_eff == AUTH_CEPHX_TICKET:
            got = accept_ticket(cfg, cred)
            if got is None:
                raise ConnectionError(
                    f"peer {self.peer_name} presented an invalid/"
                    "expired ticket")
            self.auth_entity, self.hs_session = got
        else:
            entity = cred.decode()
            key = None
            if cfg.auth_lookup is not None:
                key = cfg.auth_lookup(entity)
            elif entity == cfg.entity:
                key = cfg.key
            if key is None:
                raise ConnectionError(
                    f"unknown or revoked entity {entity!r}")
            self.auth_entity = entity
            self.hs_session = key.encode()

    # -- frame I/O ------------------------------------------------------------

    def _frame(self, msg: Message) -> bytes:
        if getattr(self.messenger, "ici_wire", False):
            from ceph_tpu.msg.features import FEATURE_ICI_TOKENS
            if self.features & FEATURE_ICI_TOKENS:
                # ici-wire data plane: the bulk payload moves through
                # the device transfer engine; the frame carries a token
                from ceph_tpu.msg.ici import maybe_stage
                maybe_stage(msg, self.peer_name)
        payload = msg.encode()
        comp = COMP_NONE
        if self.comp == COMP_ZLIB and len(payload) >= COMP_THRESHOLD:
            z = zlib.compress(payload, 1)
            if len(z) < len(payload):
                comp, payload = COMP_ZLIB, z
        return _LEN.pack(len(payload)) + bytes([comp]) + payload

    def _fill_out_frames(self) -> None:
        m = self.messenger
        pending = sum(len(b) for b, _ in self.out_frames)
        while pending < 256 << 10:
            with m._lock:
                if not self.backlog:
                    return
                msg = self.backlog.popleft()
            b = self._frame(msg)
            self.out_frames.append((b, msg))
            pending += len(b)

    def _on_writable(self) -> None:
        if self.state == _CONNECTING:
            self._on_connected()
            return
        if self.state == _OPEN:
            self._fill_out_frames()
        while self.out_frames:
            head, _msg = self.out_frames[0]
            try:
                n = self.sock.send(head[self.out_off:] if self.out_off
                                   else head)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close_now(reset=True)
                return
            self.out_off += n
            if self.out_off >= len(head):
                self.out_frames.popleft()
                self.out_off = 0
                # count at FLUSH, not frame-build: fault-salvaged
                # messages re-frame on reconnect and must only count
                # per actual wire traversal (handshake frames carry no
                # message and are not message traffic)
                if _msg is not None:
                    self.messenger.count_sent(len(head))
            else:
                break
            if self.state == _OPEN:
                self._fill_out_frames()
        self._update_interest()

    def _on_readable(self) -> None:
        try:
            data = self.sock.recv(256 << 10)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_now(reset=True)
            return
        if not data:
            self._close_now(reset=True)
            return
        self.inbuf += data
        try:
            if self.state == _HANDSHAKE:
                while self.state == _HANDSHAKE and self._hs_step():
                    pass
                # a handshake step may queue outgoing bytes (auth proof,
                # compression offer) from within this READ event; the
                # write interest must follow or the handshake deadlocks
                # with both sides read-waiting
                self._update_interest()
            if self.state == _OPEN:
                self._drain_frames()
        except ConnectionError:
            self._close_now(reset=True)

    def _drain_frames(self) -> None:
        m = self.messenger
        while True:
            if len(self.inbuf) < _LEN.size + 1:
                return
            flen = _LEN.unpack(bytes(self.inbuf[:_LEN.size]))[0]
            if flen > MAX_FRAME:
                raise ConnectionError(
                    f"oversized frame ({flen} bytes) from {self.peer_name}")
            total = _LEN.size + 1 + flen
            if len(self.inbuf) < total:
                return
            comp = self.inbuf[_LEN.size]
            data = bytes(self.inbuf[_LEN.size + 1:total])
            del self.inbuf[:total]
            if comp == COMP_ZLIB:
                d = zlib.decompressobj()
                data = d.decompress(data, MAX_FRAME)
                if d.unconsumed_tail:
                    raise ConnectionError(
                        f"decompressed frame exceeds cap from "
                        f"{self.peer_name}")
            m.enqueue_dispatch(self, data, wire_len=total)

    def _update_interest(self) -> None:
        if self.sock is None:
            return
        want = selectors.EVENT_READ if not self.messenger.paused else 0
        with self.messenger._lock:
            pending = bool(self.backlog)
        # backlog counts only once OPEN: mid-handshake it cannot be
        # framed yet, and write interest with nothing to write busy-spins
        if self.out_frames or self.state == _CONNECTING or (
                pending and self.state == _OPEN):
            want |= selectors.EVENT_WRITE
        if want == self._cur_want:
            return
        sel = self.messenger.sel
        try:
            if want:
                sel.modify(self.sock, want, self)
            else:
                # fully quiesced (paused + nothing to write): drop from
                # the selector; unpausing re-registers via refresh
                sel.unregister(self.sock)
            self._cur_want = want
        except (KeyError, ValueError):
            if want:
                try:
                    sel.register(self.sock, want, self)
                    self._cur_want = want
                except (KeyError, ValueError, OSError):
                    pass


class EventMessenger(Messenger):
    """selectors-based messenger: 2 threads total (event loop + dispatch)."""

    is_wire = True

    #: stop reading sockets when this many decoded bytes sit undispatched
    DISPATCH_HIGH = 256 << 20
    DISPATCH_LOW = 192 << 20

    def __init__(self, name: EntityName):
        super().__init__(name)
        self.sel = selectors.DefaultSelector()
        self._listener: socket.socket | None = None
        self._conns: dict[str, EventConnection] = {}
        self._stop = False
        self.auth_key: bytes | None = None
        self.auth_required = False
        #: per-entity cephx config (tickets / entity secrets); when set
        #: it supersedes the legacy shared-key handshake
        self.cephx = None
        self.comp_mode = COMP_NONE
        self.paused = False
        #: accepted connections still mid-handshake (not yet in _conns):
        #: tracked so deadlines and shutdown reach them
        self._accepting: set = set()
        self._deferred: collections.deque = collections.deque()
        self._dispatch_q: queue.Queue = queue.Queue()
        self._dispatch_bytes = 0
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._loop_thread: threading.Thread | None = None
        self._dispatch_thread: threading.Thread | None = None
        self._started = False

    # -- config ---------------------------------------------------------------

    def set_compression(self, mode: str | int) -> None:
        if isinstance(mode, str):
            mode = {"none": COMP_NONE, "zlib": COMP_ZLIB}[mode]
        self.comp_mode = int(mode)

    def set_auth(self, key: bytes | str | None,
                 required: bool = True) -> None:
        if isinstance(key, str):
            key = key.encode()
        self.auth_key = key
        self.auth_required = bool(key) and required

    def set_auth_cephx(self, config) -> None:
        self.cephx = config

    # -- loop plumbing --------------------------------------------------------

    def wakeup(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def defer(self, fn, *args) -> None:
        """Run fn(*args) on the event-loop thread."""
        self._deferred.append((fn, args))
        self.wakeup()

    def enqueue_dispatch(self, con: EventConnection, data: bytes,
                         wire_len: int = 0) -> None:
        with self._lock:
            self._dispatch_bytes += len(data)
            if self._dispatch_bytes >= self.DISPATCH_HIGH:
                self.paused = True
        self._dispatch_q.put((con, data, wire_len))

    def register_accepted(self, con: EventConnection) -> None:
        """Handshake done on an accepted session: index it so redials
        replace (and reap) the prior session from the same peer."""
        key = f"accepted:{con.peer_name}"
        with self._lock:
            self._accepting.discard(con)
            old = self._conns.get(key)
            self._conns[key] = con
        if old is not None and old is not con:
            old.mark_down()

    def reap(self, con: EventConnection) -> None:
        if not con._down and not con.accepted:
            return
        with self._lock:
            for key, c in list(self._conns.items()):
                if c is con:
                    del self._conns[key]

    # -- lifecycle ------------------------------------------------------------

    def bind(self, addr: str) -> None:
        host, port = addr.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, int(port)))
        s.listen(256)
        s.setblocking(False)
        self.my_addr = f"{host}:{s.getsockname()[1]}"
        self._listener = s

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._loop_thread = threading.Thread(
            target=self._loop, name=f"ms-ev:{self.my_name}", daemon=True)
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name=f"ms-disp:{self.my_name}",
            daemon=True)
        self._loop_thread.start()
        self._dispatch_thread.start()

    def shutdown(self) -> None:
        self._stop = True
        self.wakeup()
        self._dispatch_q.put(None)
        for t in (self._loop_thread, self._dispatch_thread):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=5)
        with self._lock:
            conns = list(self._conns.values()) + list(self._accepting)
            self._conns.clear()
            self._accepting.clear()
        for c in conns:
            c._down = True
            if c.sock is not None:
                try:
                    c.sock.close()
                except OSError:
                    pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def connect_to(self, addr: str, peer_name: EntityName) -> Connection:
        # clients may call before start() (mon bootstrap does); lazily
        # spin the threads up
        self.start()
        key = f"{addr}/{peer_name}"
        with self._lock:
            con = self._conns.get(key)
            if con is not None and not con._down:
                return con
            policy = self.policy_for(peer_name.type)
            con = EventConnection(self, addr, peer_name, policy)
            self._conns[key] = con
        self.defer(con._start_connect)
        return con

    # -- event loop -----------------------------------------------------------

    def _loop(self) -> None:
        from ceph_tpu.common.logging import get_logger
        sel = self.sel
        sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        if self._listener is not None:
            sel.register(self._listener, selectors.EVENT_READ, "accept")
        while not self._stop:
            try:
                self._loop_once(sel)
            except Exception:
                # the loop thread IS the transport: it must survive any
                # per-tick failure
                get_logger("ms").exception(
                    "%s: event loop tick failed", self.my_name)
        try:
            sel.close()
        except OSError:
            pass

    def _loop_once(self, sel) -> None:
            while self._deferred:
                fn, args = self._deferred.popleft()
                try:
                    fn(*args)
                except Exception:
                    from ceph_tpu.common.logging import get_logger
                    get_logger("ms").exception(
                        "%s: deferred event failed", self.my_name)
            timeout = self._next_timer()
            try:
                events = sel.select(timeout)
            except OSError:
                return
            now = time.monotonic()
            for skey, mask in events:
                tag = skey.data
                if tag == "wake":
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                    continue
                if tag == "accept":
                    self._accept_ready()
                    continue
                con: EventConnection = tag
                try:
                    if mask & selectors.EVENT_WRITE:
                        con._on_writable()
                    if (mask & selectors.EVENT_READ
                            and con.sock is not None):
                        con._on_readable()
                except Exception:
                    from ceph_tpu.common.logging import get_logger
                    get_logger("ms").exception(
                        "%s: connection event failed", self.my_name)
                    con._close_now(reset=True)
            self._run_timers(now)
            self._refresh_writers()

    def _refresh_writers(self) -> None:
        """Pick up messages queued from other threads: any connection
        with a pending backlog (or newly unpaused reads) re-registers;
        stalled handshakes are torn down at their deadline."""
        now = time.monotonic()
        with self._lock:
            conns = list(self._conns.values()) + list(self._accepting)
        for con in conns:
            if con.sock is not None and con.state in (
                    _OPEN, _HANDSHAKE, _CONNECTING):
                if (con.state in (_HANDSHAKE, _CONNECTING)
                        and now >= con.hs_deadline > 0):
                    # the threaded stack's handshake timeout: a peer
                    # that stalls mid-handshake must not leak the fd
                    con._close_now(reset=True)
                    continue
                con._update_interest()
            elif con.state in (_CLOSED, _WAIT_RECONNECT) and not con._down:
                with self._lock:
                    pending = bool(con.backlog)
                if pending and (con.state == _CLOSED
                                or now >= con.reconnect_at):
                    if not con.accepted:
                        con._start_connect()

    def _next_timer(self) -> float:
        with self._lock:
            waits = [c.reconnect_at for c in self._conns.values()
                     if c.state == _WAIT_RECONNECT and c.backlog]
        if not waits:
            return 0.2
        return max(0.0, min(min(waits) - time.monotonic(), 0.2))

    def _run_timers(self, now: float) -> None:
        pass  # reconnects handled by _refresh_writers

    def _accept_ready(self) -> None:
        while True:
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            con = EventConnection(self, f"{addr[0]}:0", None,
                                  self._default_policy, sock=sock,
                                  accepted=True)
            con._emit_handshake_head()
            try:
                self.sel.register(
                    sock, selectors.EVENT_READ | selectors.EVENT_WRITE,
                    con)
                con._cur_want = (selectors.EVENT_READ
                                 | selectors.EVENT_WRITE)
                with self._lock:
                    self._accepting.add(con)
            except (KeyError, ValueError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass

    # -- dispatch thread ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        from ceph_tpu.common.logging import get_logger
        while True:
            item = self._dispatch_q.get()
            if item is None or self._stop:
                return
            con, data, wire_len = item
            try:
                msg = Message.decode(data)
                # on-wire size (header + possibly-compressed payload):
                # matches the sender's flush-time count_sent
                msg.wire_bytes = wire_len or len(data)
                msg.connection = con
                self.deliver(msg)
            except Exception:
                get_logger("ms").exception(
                    "%s: dispatch failed for frame from %s",
                    self.my_name, con.peer_name)
            finally:
                with self._lock:
                    self._dispatch_bytes -= len(data)
                    unpause = (self.paused
                               and self._dispatch_bytes <= self.DISPATCH_LOW)
                    if unpause:
                        self.paused = False
                if unpause:
                    self.wakeup()
